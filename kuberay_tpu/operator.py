"""Operator entrypoint: flags/config -> wired controllers -> run loop.

The main.go analogue (ref ray-operator/main.go:55: flag/config parse at
:76-112, feature gates :188, controller registration :309-343).  Also the
embedding API: ``Operator(...)`` with an in-memory store is a fully
functional single-process control plane (used by tests, the CLI's demo
mode, and the e2e harness).

``python -m kuberay_tpu.operator --help`` for flags.
"""

from __future__ import annotations

import argparse
import json
import logging
import threading
import time
from typing import Optional

from kuberay_tpu.api.config import OperatorConfiguration
from kuberay_tpu.apiserver.server import serve_background
from kuberay_tpu.controlplane.autoscaler import SliceAutoscaler
from kuberay_tpu.controlplane.cluster_controller import TpuClusterController
from kuberay_tpu.controlplane.cronjob_controller import TpuCronJobController
from kuberay_tpu.controlplane.events import EventRecorder
from kuberay_tpu.controlplane.fake_kubelet import FakeKubelet
from kuberay_tpu.controlplane.job_controller import TpuJobController
from kuberay_tpu.controlplane.manager import (
    Manager,
    originated_from_mapper,
    owned_pod_mapper,
)
from kuberay_tpu.controlplane.networkpolicy_controller import NetworkPolicyController
from kuberay_tpu.controlplane.service_controller import TpuServiceController
from kuberay_tpu.controlplane.leader import LeaderElector
from kuberay_tpu.controlplane.store import ObjectStore, StoreError
from kuberay_tpu.controlplane.warmpool_controller import (
    KIND_WARM_POOL,
    WarmSlicePoolController,
)
from kuberay_tpu.runtime.coordinator_client import default_client_provider
from kuberay_tpu.scheduler.adapters import (
    KaiAdapter,
    SchedulerPluginsAdapter,
    VolcanoAdapter,
    YuniKornAdapter,
)
from kuberay_tpu.scheduler.gang import GangScheduler
from kuberay_tpu.scheduler.interface import SchedulerManager
from kuberay_tpu.utils import constants as C
from kuberay_tpu.utils import features
from kuberay_tpu.utils.metrics import ControlPlaneMetrics


class Operator:
    def __init__(self, config: Optional[OperatorConfiguration] = None,
                 store: Optional[ObjectStore] = None,
                 client_provider=None,
                 fake_kubelet: bool = False,
                 watch_dispatch: str = "sync",
                 slo_signal=None):
        self.config = config or OperatorConfiguration()
        features.set_gates(self.config.featureGates)
        # ``watch_dispatch`` applies only when the Operator builds its
        # own store: "async" moves watch fan-out onto the store's
        # dispatcher thread (writers never wait on reconcile-side
        # callbacks — the live-operator mode main() selects); "sync"
        # keeps inline delivery, which embedded/run_until_idle tests
        # rely on for determinism.
        self._owns_store = store is None
        self.store = store if store is not None else \
            ObjectStore(dispatch=watch_dispatch,
                        backlog_max=self.config.watchBacklogMax,
                        bookmark_interval=self.config.watchBookmarkInterval)
        self.metrics = ControlPlaneMetrics()
        # Backlog-eviction accounting (tpu_watch_backlog_evictions_total)
        # wants the operator's registry even on a pre-built store.
        if hasattr(self.store, "set_metrics"):
            self.store.set_metrics(self.metrics)
        # Observability (kuberay_tpu.obs): always on — all bounded
        # ring/LRU structures; /debug/traces + /debug/flight answer
        # "where did the time go" per reconcile, /debug/goodput answers
        # it per job lifetime (productive vs. lost seconds).
        from kuberay_tpu.obs import (FlightRecorder, GoodputLedger,
                                     RequestProfiler, Tracer,
                                     TransitionRecorder)
        self.tracer = Tracer()
        # Flight records made inside an active span carry its trace_id
        # (timeline row -> span join during forensics).
        self.flight = FlightRecorder(tracer=self.tracer)
        # Critical-path profiler over the span store (/debug/profile);
        # an embedded gateway notes request completions into it.
        self.profiler = RequestProfiler(self.tracer)
        # Span-store eviction counter, synced as a delta each background
        # tick — the tracer itself stays observational.
        self.metrics.registry.describe(
            "tpu_trace_spans_dropped_total",
            "Spans evicted from the bounded trace store by tail-sampling "
            "retention — nonzero means /debug/profile and /debug/traces "
            "are working from a truncated window")
        self._trace_dropped_seen = 0
        self.goodput = GoodputLedger(metrics=self.metrics)
        self.transitions = TransitionRecorder(flight=self.flight,
                                              ledger=self.goodput)
        # Training-step straggler microscope (obs/steps.py): fed by the
        # coordinator's step_heartbeat events, fans skew/MFU gauges into
        # the registry and stall edges into the goodput ledger.
        from kuberay_tpu.obs import StepTracker
        self.steps = StepTracker(metrics=self.metrics, flight=self.flight,
                                 goodput=self.goodput)
        # The ledger folds every store event (CR lifecycle + pod phase
        # accounting); controllers feed state writes via ``transitions``.
        self._goodput_cancel = self.store.watch(self.goodput.observe_event)
        self.recorder = EventRecorder(self.store)
        self.manager = Manager(self.store, metrics=self.metrics,
                               tracer=self.tracer, flight=self.flight,
                               shards=max(1, self.config.shardCount))

        self.schedulers = SchedulerManager()
        # Hierarchical multi-tenant quota (controlplane/quota.py): the
        # capacity oracle behind the builtin gang scheduler's admission
        # seam.  Workloads without spec.tenant (or namespaces without a
        # QuotaPool) bypass the ledger, so mounting it is always safe.
        from kuberay_tpu.controlplane.quota import QuotaManager
        self.quota = QuotaManager(self.store, metrics=self.metrics)
        self.schedulers.register(GangScheduler(
            self.store, quota=self.quota, metrics=self.metrics))
        self.schedulers.register(VolcanoAdapter(self.store))
        self.schedulers.register(YuniKornAdapter(self.store))
        self.schedulers.register(KaiAdapter(self.store))
        self.schedulers.register(SchedulerPluginsAdapter(self.store))
        scheduler = (self.schedulers.get(self.config.batchScheduler)
                     if self.config.enableBatchScheduler else None)

        provider = client_provider
        if provider is None:
            provider = lambda status: default_client_provider(status)

        self.cluster_controller = TpuClusterController(
            self.store, expectations=self.manager.expectations,
            recorder=self.recorder, scheduler=scheduler,
            config_env=self.config.defaultPodEnv, metrics=self.metrics,
            use_openshift_route=self.config.useOpenShiftRoute,
            tracer=self.tracer, transitions=self.transitions)
        self.job_controller = TpuJobController(
            self.store, recorder=self.recorder,
            client_provider=provider,
            scheduler=scheduler, metrics=self.metrics,
            tracer=self.tracer, transitions=self.transitions)
        from kuberay_tpu.controlplane.autoscaler import DecisionAudit
        self.autoscaler_audit = DecisionAudit(metrics=self.metrics)
        self.service_controller = TpuServiceController(
            self.store, recorder=self.recorder,
            client_provider=lambda cname, status: provider(status),
            tracer=self.tracer, transitions=self.transitions,
            profiler=self.profiler, audit=self.autoscaler_audit)
        self.cronjob_controller = TpuCronJobController(
            self.store, recorder=self.recorder, tracer=self.tracer,
            scheduler=scheduler)
        self.networkpolicy_controller = NetworkPolicyController(self.store)
        self.warmpool_controller = WarmSlicePoolController(
            self.store, recorder=self.recorder, tracer=self.tracer)
        # SLO burn-rate alerting (obs/alerts.py): evaluated from the
        # background tick over the same registry everything above feeds;
        # served at /debug/alerts, cross-linked to the decision audit
        # and the flight recorder.
        from kuberay_tpu.obs import AlertEngine
        self.alerts = AlertEngine(self.metrics.registry,
                                  audit=self.autoscaler_audit,
                                  flight=self.flight)
        # Incident forensics (obs/incident.py): every mounted evidence
        # surface behind one trigger->bundle engine, evaluated right
        # after the alert tick; served at /debug/incidents and archived
        # per-entity by the history collector.
        from kuberay_tpu.obs import IncidentEngine
        self.incidents = IncidentEngine(
            registry=self.metrics.registry, tracer=self.tracer,
            flight=self.flight, goodput=self.goodput, alerts=self.alerts,
            steps=self.steps, audit=self.autoscaler_audit,
            quota=self.quota)
        # ``slo_signal`` (controlplane/slo.ServeSloSignal): embedders
        # serving traffic in-process hand the autoscaler their serve
        # TTFT/queue-depth SLO signal; None keeps the resource-only path.
        self.autoscaler = SliceAutoscaler(self.store,
                                          audit=self.autoscaler_audit,
                                          slo=slo_signal)

        m = self.manager
        m.register(C.KIND_CLUSTER, self._timed(C.KIND_CLUSTER,
                                               self.cluster_controller.reconcile))
        m.register(C.KIND_JOB, self._timed(C.KIND_JOB,
                                           self.job_controller.reconcile))
        m.register(C.KIND_SERVICE, self._timed(C.KIND_SERVICE,
                                               self.service_controller.reconcile))
        if features.enabled("TpuCronJob"):
            m.register(C.KIND_CRONJOB, self._timed(
                C.KIND_CRONJOB, self.cronjob_controller.reconcile))
        if features.enabled("WarmSlicePools"):
            m.register(KIND_WARM_POOL, self._timed(
                KIND_WARM_POOL, self.warmpool_controller.reconcile))
            # Warm pods carry the pool label; their churn re-reconciles it.
            from kuberay_tpu.controlplane.warmpool_controller import LABEL_WARM_POOL

            def warm_pod_mapper(ev):
                if ev.kind != "Pod":
                    return None
                md = ev.obj.get("metadata", {})
                pool = md.get("labels", {}).get(LABEL_WARM_POOL)
                if not pool:
                    return None
                return (KIND_WARM_POOL, md.get("namespace", "default"), pool)
            m.map_owned(warm_pod_mapper)
        def compute_template_mapper(ev):
            # A ComputeTemplate create/update re-reconciles every cluster
            # referencing it, so a cluster that failed on a missing or
            # broken template self-heals once the template appears/is fixed.
            if ev.kind != "ComputeTemplate":
                return None
            md = ev.obj.get("metadata", {})
            ns, tname = md.get("namespace", "default"), md.get("name", "")
            return [(C.KIND_CLUSTER, ns, cl["metadata"]["name"])
                    for cl in self.store.list(C.KIND_CLUSTER, namespace=ns)
                    if any(g.get("computeTemplate") == tname
                           for g in cl.get("spec", {}).get(
                               "workerGroupSpecs", []))]
        m.map_owned(compute_template_mapper)
        m.map_owned(owned_pod_mapper)
        m.map_owned(originated_from_mapper(C.KIND_JOB))
        m.map_owned(originated_from_mapper(C.KIND_SERVICE))
        m.map_owned(originated_from_mapper(C.KIND_CRONJOB))
        if features.enabled("TpuClusterNetworkPolicy"):
            self._netpol_watch()

        self.kubelet = (FakeKubelet(self.store, tracer=self.tracer)
                        if fake_kubelet else None)
        self.history_collector = None
        if self.config.historyArchiveURL:
            from kuberay_tpu.history.server import HistoryCollector
            from kuberay_tpu.history.storage import backend_from_url
            self.history_collector = HistoryCollector(
                self.store, backend_from_url(self.config.historyArchiveURL),
                goodput=self.goodput, incidents=self.incidents)
        self._stop = threading.Event()
        self.apiserver = None
        self.api_url = ""
        self.elector: Optional[LeaderElector] = None
        self.shard_elector = None

    def _timed(self, kind, fn):
        def wrapped(name, ns):
            t0 = time.time()
            try:
                return fn(name, ns)
            finally:
                self.metrics.reconcile(kind, time.time() - t0)
        return wrapped

    def _netpol_watch(self):
        def mapper(ev):
            if ev.kind == C.KIND_CLUSTER:
                md = ev.obj.get("metadata", {})
                self.networkpolicy_controller.reconcile(
                    md.get("name", ""), md.get("namespace", "default"))
            return None
        self.manager.map_owned(mapper)

    # -- lifecycle ---------------------------------------------------------

    def start(self, api_port: int = 0, api_host: str = "127.0.0.1",
              leader_election: bool = False, shard_leases: bool = False):
        """Start workers + API server; returns the API base URL.

        ``leader_election``: multi-replica mode (ref main.go:232
        'ray-operator-leader') — reconcilers only run while this replica
        holds the Lease; the API server always serves (reads are safe).

        ``shard_leases`` (with ``leader_election`` and ``shardCount>1``):
        instead of one whole-operator lease, each reconcile shard has
        its own Lease and replicas SPLIT the shard set (docs/scaling.md)
        — workers start immediately but every pool begins paused; the
        :class:`ShardLeaseElector` resumes exactly the pools whose
        leases this replica holds.
        """
        history = None
        if self.history_collector is not None:
            from kuberay_tpu.history.server import HistoryServer
            history = HistoryServer(self.history_collector.storage)
        self.apiserver, self.api_url = serve_background(
            self.store, api_host, api_port, metrics=self.metrics,
            history=history, tracer=self.tracer, flight=self.flight,
            goodput=self.goodput, autoscaler=self.autoscaler_audit,
            alerts=self.alerts, steps=self.steps, quota=self.quota,
            profiler=self.profiler, incidents=self.incidents)
        if leader_election and shard_leases and self.manager.shards > 1:
            from kuberay_tpu.controlplane.leader import ShardLeaseElector
            # Start unowned: every pool paused until its lease is won.
            for shard in range(self.manager.shards):
                self.manager.release_shard(shard)
            self.shard_elector = ShardLeaseElector(
                self.store, self.manager.shards,
                namespace=self.config.leaderElectionNamespace,
                max_owned=self.config.maxOwnedShards or None,
                on_acquired=self.manager.acquire_shard,
                on_released=self.manager.release_shard)
            self._start_reconcilers()
            self.shard_elector.start()
        elif leader_election:
            self.elector = LeaderElector(
                self.store,
                namespace=self.config.leaderElectionNamespace,
                on_started_leading=self._start_reconcilers,
                on_stopped_leading=self._stop_reconcilers)
            self.elector.start()
        else:
            self._start_reconcilers()
        return self.api_url

    def _start_reconcilers(self):
        self.manager.start(workers=max(1, self.config.reconcileConcurrency))
        # The loop thread captures ITS stop event: replacing self._stop for
        # a later re-election must not leave an orphan running.
        self._loops_thread = threading.Thread(
            target=self._background_loops, args=(self._stop,), daemon=True,
            name="operator-loops")
        self._loops_thread.start()

    def _stop_reconcilers(self):
        self._stop.set()
        self.manager.stop()
        t = getattr(self, "_loops_thread", None)
        if t is not None:
            t.join(timeout=3.0)
        self._stop = threading.Event()   # allow re-election to restart

    def _background_loops(self, stop: threading.Event):
        """Periodic work: autoscaler passes, cron ticks, fake kubelet."""
        log = logging.getLogger("kuberay_tpu.operator")
        while not stop.is_set():
            try:
                clusters = self.store.list(C.KIND_CLUSTER)
                self.autoscaler.prune_clusters(
                    {(o["metadata"]["namespace"], o["metadata"]["name"])
                     for o in clusters})
                for obj in clusters:
                    if obj.get("spec", {}).get("enableInTreeAutoscaling"):
                        md = obj["metadata"]
                        if self.autoscaler.reconcile(md["name"], md["namespace"]):
                            self.manager.enqueue(
                                (C.KIND_CLUSTER, md["namespace"], md["name"]))
                if features.enabled("TpuCronJob"):
                    for obj in self.store.list(C.KIND_CRONJOB):
                        md = obj["metadata"]
                        self.manager.enqueue(
                            (C.KIND_CRONJOB, md["namespace"], md["name"]))
                if self.kubelet is not None:
                    self.kubelet.step()
                fired = self.alerts.evaluate()
                self.incidents.evaluate(fired)
                self._sync_trace_dropped()
                self._gc_events()
            except Exception:
                log.exception("operator background loop iteration failed")
            stop.wait(1.0)

    def _sync_trace_dropped(self):
        """Mirror the span store's lifetime eviction count into the
        registry as a cumulative counter (delta per tick) — scrapers
        learn a profile window got truncated without polling
        /debug/traces."""
        dropped = self.tracer.store.dropped
        delta = dropped - self._trace_dropped_seen
        if delta > 0:
            self.metrics.registry.inc("tpu_trace_spans_dropped_total",
                                      value=float(delta))
            self._trace_dropped_seen = dropped

    _EVENT_TTL_SECONDS = 3600.0
    _EVENT_GC_INTERVAL = 60.0

    def _gc_events(self):
        """Events expire like K8s's (~1h) — unbounded accumulation is a
        slow leak in a long-lived store.  Swept once a minute: a per-second
        full Event scan would contend the store lock for nothing."""
        now = time.time()
        if now - getattr(self, "_last_event_gc", 0.0) < self._EVENT_GC_INTERVAL:
            return
        self._last_event_gc = now
        cutoff = now - self._EVENT_TTL_SECONDS
        for ev in self.store.list("Event"):
            if ev.get("eventTime", cutoff + 1) < cutoff:
                try:
                    self.store.delete("Event", ev["metadata"]["name"],
                                      ev["metadata"]["namespace"])
                except StoreError:
                    # Raced another GC / server blip: the event either
                    # died already or ages out next sweep.
                    continue

    def stop(self):
        # Per-shard leases release FIRST: each on_released pauses and
        # drains its pool, so the lease only moves after our in-flight
        # reconciles for that shard finished.
        if self.shard_elector is not None:
            self.shard_elector.stop()
        # Reconcilers stop BEFORE the lease is released: a successor must
        # never overlap with our in-flight reconciles (dual-writer window).
        self._stop_reconcilers()
        if self.elector is not None:
            self.elector.stop()
        self._goodput_cancel()
        if self.history_collector is not None:
            self.history_collector.close()
        if self.apiserver is not None:
            self.apiserver.shutdown()
        if self._owns_store and hasattr(self.store, "close"):
            self.store.close()   # stops the async watch dispatcher

    # test/demo helper
    def run_until_idle(self):
        self.manager.flush_delayed()
        n = self.manager.run_until_idle()
        if self.kubelet is not None:
            self.kubelet.step()
            self.manager.run_until_idle()
        return n


def load_config(path: str) -> OperatorConfiguration:
    with open(path) as f:
        return OperatorConfiguration.from_dict(json.load(f))


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="kuberay-tpu-operator",
        description="TPU-native pod-slice orchestration operator")
    ap.add_argument("--config", help="operator config JSON file")
    ap.add_argument("--feature-gates", default="",
                    help="e.g. TpuCronJob=true,TpuClusterNetworkPolicy=true")
    ap.add_argument("--api-port", type=int, default=8765)
    ap.add_argument("--api-host", default="127.0.0.1")
    ap.add_argument("--batch-scheduler", default="",
                    help="gang | volcano | yunikorn | kai")
    ap.add_argument("--reconcile-concurrency", type=int, default=2,
                    help="reconcile worker threads PER SHARD")
    ap.add_argument("--shards", type=int, default=1,
                    help="hash-shard reconcile keys across N worker pools "
                         "(per-key serialization holds globally: a key "
                         "hashes to exactly one pool — docs/scaling.md)")
    ap.add_argument("--shard-leases", action="store_true",
                    help="with --leader-election and --shards N: one Lease "
                         "per shard so multiple operator processes SPLIT "
                         "the shard set instead of standing by")
    ap.add_argument("--max-owned-shards", type=int, default=0,
                    help="cap shards this replica acquires (0 = no cap); "
                         "set ceil(shards/replicas) for an even split")
    ap.add_argument("--watch-backlog-max", type=int, default=10000,
                    help="resumable watch-backlog window in events; "
                         "undersizing forces full relists on informer "
                         "resume (tpu_watch_backlog_evictions_total)")
    ap.add_argument("--watch-bookmark-interval", type=int, default=500,
                    help="emit a BOOKMARK progress event to subscribers "
                         "every N committed revisions (0 disables)")
    ap.add_argument("--fake-kubelet", action="store_true",
                    help="run pods with the in-process fake kubelet (demo)")
    ap.add_argument("--leader-election", action="store_true",
                    help="multi-replica mode: reconcile only while holding "
                         "the leader Lease (requires a SHARED store — pass "
                         "--store-url so replicas see the same Lease)")
    ap.add_argument("--store-url", default="",
                    help="remote API server URL; the operator runs against "
                         "it over REST instead of an in-memory store")
    ap.add_argument("--journal", default="",
                    help="journal file for durable standalone state "
                         "(CRs survive operator restarts)")
    ap.add_argument("--watch-dispatch", default="async",
                    choices=("sync", "async"),
                    help="watch fan-out mode: async (dispatcher thread; "
                         "writers never wait on watcher callbacks — the "
                         "live default) or sync (inline, deterministic)")
    ap.add_argument("--history-archive", default="",
                    help="archive CR lifecycles for the history server: "
                         "file:///path | s3://bucket?endpoint=... | "
                         "gs://bucket?endpoint=...")
    args = ap.parse_args(argv)

    cfg = load_config(args.config) if args.config else OperatorConfiguration()
    if args.history_archive:
        cfg.historyArchiveURL = args.history_archive
    if args.batch_scheduler:
        cfg.batchScheduler = args.batch_scheduler
        cfg.enableBatchScheduler = True
    cfg.reconcileConcurrency = args.reconcile_concurrency
    cfg.shardCount = max(1, args.shards)
    cfg.maxOwnedShards = max(0, args.max_owned_shards)
    cfg.watchBacklogMax = args.watch_backlog_max
    cfg.watchBookmarkInterval = args.watch_bookmark_interval
    features.parse_and_set(args.feature_gates)

    if args.store_url:
        from kuberay_tpu.controlplane.rest_store import RestObjectStore
        store = RestObjectStore(args.store_url)
    elif args.journal:
        store = ObjectStore(journal_path=args.journal,
                            dispatch=args.watch_dispatch,
                            backlog_max=cfg.watchBacklogMax,
                            bookmark_interval=cfg.watchBookmarkInterval)
    else:
        store = None
    if args.leader_election and not args.store_url and not args.journal:
        print("warning: --leader-election without --store-url elects "
              "against a private store (every replica wins); pass "
              "--store-url for real multi-replica mode", flush=True)
    op = Operator(cfg, store=store, fake_kubelet=args.fake_kubelet,
                  watch_dispatch=args.watch_dispatch)
    url = op.start(api_port=args.api_port, api_host=args.api_host,
                   leader_election=args.leader_election,
                   shard_leases=args.shard_leases)
    print(f"kuberay-tpu operator running; API at {url}", flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        op.stop()


if __name__ == "__main__":
    main()
