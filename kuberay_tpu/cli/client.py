"""HTTP client for the apiserver (the python-client analogue,
ref clients/python-client: RayClusterApi over the K8s API)."""

from __future__ import annotations

import json
import urllib.error
import urllib.parse
import urllib.request
from typing import Any, Dict, List, Optional

from kuberay_tpu.utils import constants as C

PLURAL = {**C.CRD_PLURALS, **C.CORE_PLURALS}


class ApiError(Exception):
    def __init__(self, code: int, message: str):
        super().__init__(f"HTTP {code}: {message}")
        self.code = code


class ApiClient:
    def __init__(self, base_url: str = "http://127.0.0.1:8765",
                 timeout: float = 10.0):
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout

    def _path(self, kind: str, ns: str, name: str = "") -> str:
        plural = PLURAL[kind]
        if kind in C.CORE_PLURALS:
            base = f"/api/v1/namespaces/{ns}/{plural}"
        else:
            base = f"/apis/tpu.dev/v1/namespaces/{ns}/{plural}"
        return base + (f"/{name}" if name else "")

    def _req(self, method: str, path: str, body: Any = None,
             content_type: str = "application/json"):
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(self.base_url + path, data=data,
                                     method=method,
                                     headers={"Content-Type": content_type})
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                payload = resp.read()
                if not payload:
                    return {}
                try:
                    return json.loads(payload)
                except json.JSONDecodeError:
                    return {"raw": payload.decode(errors="replace")}
        except urllib.error.HTTPError as e:
            try:
                msg = json.loads(e.read()).get("message", str(e))
            except Exception:
                msg = str(e)
            raise ApiError(e.code, msg) from None

    def list(self, kind: str, namespace: str = "default",
             label_selector: str = "") -> List[Dict[str, Any]]:
        path = self._path(kind, namespace)
        if label_selector:
            path += "?" + urllib.parse.urlencode(
                {"labelSelector": label_selector})
        return self._req("GET", path).get("items", [])

    def get(self, kind: str, name: str, namespace: str = "default"):
        return self._req("GET", self._path(kind, namespace, name))

    def create(self, obj: Dict[str, Any]):
        md = obj.get("metadata", {})
        return self._req("POST", self._path(obj["kind"],
                                            md.get("namespace", "default")),
                         obj)

    def update(self, obj: Dict[str, Any]):
        md = obj["metadata"]
        return self._req("PUT", self._path(obj["kind"],
                                           md.get("namespace", "default"),
                                           md["name"]), obj)

    _PATCH_CTYPES = C.PATCH_CONTENT_TYPES

    def patch(self, kind: str, name: str, namespace: str = "default",
              body: Any = None, *, patch_type: str = "merge",
              field_manager: str = "", force: bool = False):
        """Wire PATCH (merge | strategic | json | apply): one round trip
        instead of a get→update conflict loop.  ``apply`` is Server-Side
        Apply and requires ``field_manager``."""
        path = self._path(kind, namespace, name)
        q = {}
        if field_manager:
            q["fieldManager"] = field_manager
        if force:
            q["force"] = "true"
        if q:
            path += "?" + urllib.parse.urlencode(q)
        return self._req("PATCH", path, body,
                         content_type=self._PATCH_CTYPES[patch_type])

    def delete(self, kind: str, name: str, namespace: str = "default"):
        return self._req("DELETE", self._path(kind, namespace, name))

    def healthy(self) -> bool:
        try:
            self._req("GET", "/healthz")
            return True
        except Exception:
            return False
