"""Session port-forwarding (ref kubectl-plugin session.go pattern):
forward local TCP ports to the cluster head's dashboard/serve ports so
`localhost:<port>` works from the operator's machine — a plain TCP relay
(works wherever the head host is routable; inside K8s the kubectl
port-forward API would slot in behind the same interface)."""

from __future__ import annotations

import socket
import threading
from typing import Iterable, List, Tuple


def _pipe(a: socket.socket, b: socket.socket):
    try:
        while True:
            data = a.recv(65536)
            if not data:
                break
            b.sendall(data)
    except OSError:
        pass
    finally:
        for s in (a, b):
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                s.close()
            except OSError:
                pass


class PortForward:
    """One local listener relaying to (host, port)."""

    def __init__(self, local_port: int, host: str, remote_port: int):
        self.host = host
        self.remote_port = remote_port
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind(("127.0.0.1", local_port))
        self._srv.listen(16)
        self.local_port = self._srv.getsockname()[1]
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._accept_loop,
                                        daemon=True, name="port-forward")
        self._thread.start()

    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                client, _ = self._srv.accept()
            except OSError:
                return
            try:
                upstream = socket.create_connection(
                    (self.host, self.remote_port), timeout=10)
            except OSError:
                client.close()
                continue
            threading.Thread(target=_pipe, args=(client, upstream),
                             daemon=True).start()
            threading.Thread(target=_pipe, args=(upstream, client),
                             daemon=True).start()

    def close(self):
        self._stop.set()
        try:
            self._srv.close()
        except OSError:
            pass


def run_session(target: str, forwards: Iterable[Tuple[int, int, str]],
                print_only: bool = False) -> int:
    """forwards: (local_port, remote_port, label).  Blocks until Ctrl-C."""
    if print_only:
        for local, remote, label in forwards:
            print(f"{label}: http://127.0.0.1:{local} -> "
                  f"{target}:{remote}")
        return 0
    import sys
    active: List[PortForward] = []
    try:
        for local, remote, label in forwards:
            try:
                pf = PortForward(local, target, remote)
            except OSError as e:
                print(f"error: cannot bind 127.0.0.1:{local} ({e})",
                      file=sys.stderr)
                return 1
            active.append(pf)
            print(f"forwarding {label}: http://127.0.0.1:{pf.local_port} -> "
                  f"{target}:{remote}", flush=True)
        threading.Event().wait()    # until interrupted
    except KeyboardInterrupt:
        pass
    finally:
        for pf in active:
            pf.close()
    return 0
