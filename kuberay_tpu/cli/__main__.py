"""tpuctl: the kubectl-plugin analogue (ref kubectl-plugin/pkg/cmd/ray.go:46-53).

Subcommands mirror `kubectl ray` with TPU flags first-class
(generation.go:150-232 TPU resource/node-selector handling is native here):

    tpuctl get clusters|jobs|services|slices|workergroups|events
    tpuctl create cluster NAME --tpu v5p --topology 4x4x4 --slices 2 ...
    tpuctl create workergroup NAME --cluster C --tpu v5e --topology 2x4
    tpuctl scale NAME --group G --replicas N
    tpuctl submit NAME --tpu ... -- python -m train ...
    tpuctl incident list|show ID
    tpuctl suspend|resume (cluster|job) NAME
    tpuctl delete (cluster|job|service) NAME
    tpuctl status (cluster|job|service) NAME

Usage: python -m kuberay_tpu.cli <subcommand> [...]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
import urllib.parse
from typing import Any, Dict, List

from kuberay_tpu.cli.client import ApiClient, ApiError
from kuberay_tpu.topology import SliceTopology, TopologyError
from kuberay_tpu.utils import constants as C

KIND_BY_ALIAS = {
    "cluster": "TpuCluster", "clusters": "TpuCluster",
    "job": "TpuJob", "jobs": "TpuJob",
    "service": "TpuService", "services": "TpuService",
    "cronjob": "TpuCronJob", "cronjobs": "TpuCronJob",
    "events": "Event", "pods": "Pod", "slices": "Pod",
    "workergroup": "TpuCluster", "workergroups": "TpuCluster",
    "computetemplate": "ComputeTemplate",
    "computetemplates": "ComputeTemplate",
}


def _table(rows: List[List[str]], headers: List[str]) -> str:
    widths = [max(len(str(r[i])) for r in [headers] + rows)
              for i in range(len(headers))]
    fmt = "  ".join(f"{{:<{w}}}" for w in widths)
    out = [fmt.format(*headers)]
    out += [fmt.format(*[str(c) for c in r]) for r in rows]
    return "\n".join(out)


def _cluster_rows(items):
    rows = []
    for c in items:
        st = c.get("status", {})
        rows.append([
            c["metadata"]["name"],
            st.get("state", "") or "provisioning",
            f"{st.get('readySlices', 0)}/{st.get('desiredSlices', 0)}",
            f"{st.get('readyWorkerHosts', 0)}/{st.get('desiredWorkerHosts', 0)}",
            st.get("desiredTpuChips", 0),
        ])
    return _table(rows, ["NAME", "STATE", "SLICES", "HOSTS", "TPU-CHIPS"])


def _job_rows(items):
    rows = []
    for j in items:
        st = j.get("status", {})
        rows.append([
            j["metadata"]["name"],
            st.get("jobDeploymentStatus", ""),
            st.get("jobStatus", ""),
            st.get("clusterName", ""),
            int(st.get("failed", 0)),
        ])
    return _table(rows, ["NAME", "DEPLOYMENT", "JOB", "CLUSTER", "RETRIES"])


def _slice_rows(items):
    by_slice: Dict[str, List[dict]] = {}
    for p in items:
        sname = p["metadata"]["labels"].get(C.LABEL_SLICE_NAME)
        if sname:
            by_slice.setdefault(sname, []).append(p)
    rows = []
    for sname, pods in sorted(by_slice.items()):
        phases = [p.get("status", {}).get("phase", "Pending") for p in pods]
        ready = sum(1 for ph in phases if ph == "Running")
        rows.append([sname,
                     pods[0]["metadata"]["labels"].get(C.LABEL_CLUSTER, ""),
                     pods[0]["metadata"]["labels"].get(C.LABEL_GROUP, ""),
                     f"{ready}/{len(pods)}"])
    return _table(rows, ["SLICE", "CLUSTER", "GROUP", "HOSTS-READY"])


def build_worker_group(args, group_name: str) -> Dict[str, Any]:
    """One WorkerGroupSpec from CLI flags (shared by `create cluster` and
    `create workergroup` — ref kubectl-plugin generation.go:150-232)."""
    SliceTopology.create(args.tpu, args.topology)         # validates early
    return {
        "groupName": group_name,
        "accelerator": args.tpu,
        "topology": args.topology,
        "replicas": args.slices,
        "minReplicas": args.min_slices if args.min_slices is not None else 0,
        "maxReplicas": args.max_slices or max(args.slices, 1),
        "template": {"spec": {"containers": [
            {"name": "worker", "image": args.image,
             "resources": {"requests": {"cpu": args.worker_cpu,
                                        "memory": args.worker_memory}}}]}},
    }


def build_cluster_manifest(args) -> Dict[str, Any]:
    worker = build_worker_group(args, args.group)
    spec = {
        "headGroupSpec": {"template": {"spec": {"containers": [
            {"name": "head", "image": args.image}]}}},
        "workerGroupSpecs": [worker],
    }
    if args.autoscale:
        spec["enableInTreeAutoscaling"] = True
    return {
        "apiVersion": C.API_VERSION, "kind": C.KIND_CLUSTER,
        "metadata": {"name": args.name, "namespace": args.namespace},
        "spec": spec,
    }


def build_service_manifest(args) -> Dict[str, Any]:
    """TpuService with the serveConfig-to-engine wire prewired: the
    worker command reads its engine settings from the coordinator, so
    spec.serveConfig is the one source of truth and config edits roll
    through the normal zero-downtime upgrade."""
    cluster_spec = build_cluster_manifest(args)["spec"]
    worker = cluster_spec["workerGroupSpecs"][0]["template"]["spec"][
        "containers"][0]
    worker["command"] = ["python", "-m", "kuberay_tpu.serve.server"]
    worker["args"] = ["--tp", "0", "--coordinator", "auto",
                      "--app-name", "llm", "--config-from-coordinator"]
    app: Dict[str, Any] = {
        "name": "llm", "model": args.model,
        "max_len": args.max_serve_len,
    }
    if args.paged:
        app["paged"] = True
    if args.checkpoint_dir:
        app["checkpoint_dir"] = args.checkpoint_dir
    return {
        "apiVersion": C.API_VERSION, "kind": C.KIND_SERVICE,
        "metadata": {"name": args.name, "namespace": args.namespace},
        "spec": {
            "serveConfig": {"applications": [app]},
            "clusterSpec": cluster_spec,
        },
    }


def _profile_summary(doc: Dict[str, Any]) -> str:
    """Per-shape one-liners for a tpu-profile/v1 document: the top span
    kinds by total exclusive self time."""
    lines = []
    for shape, body in sorted(doc.get("shapes", {}).items()):
        kinds = sorted(body.get("kinds", {}).items(),
                       key=lambda kv: -kv[1]["total_s"])[:5]
        parts = ", ".join(
            f"{k} {v['total_s']:.3f}s ({v['fraction'] * 100:.0f}%)"
            for k, v in kinds)
        lines.append(f"[{shape}] {body['traces']} windows, "
                     f"p90 {body['duration_p90_s']:.4f}s: {parts}")
    return "\n".join(lines) or "no profiled windows"


def _profile_diff(args) -> int:
    """`tpuctl profile diff BASELINE CANDIDATE`: noise-gated trace diff
    of two tpu-profile/v1 artifacts.  Exit 1 when any regression
    survives the gate — shell-gateable, same engine the upgrade ramp
    and tools/bench_serve.sh use."""
    from kuberay_tpu.obs.profile import describe_regression, diff_profiles
    if len(args.paths) != 2:
        print("error: profile diff needs exactly two files: "
              "BASELINE CANDIDATE", file=sys.stderr)
        return 2
    docs = []
    for path in args.paths:
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            print(f"error reading {path}: {e}", file=sys.stderr)
            return 2
        # tpu-bench-profile/v1 artifacts nest the profile; accept both.
        if "shapes" not in doc and isinstance(doc.get("profile"), dict):
            doc = doc["profile"]
        docs.append(doc)
    diff = diff_profiles(docs[0], docs[1], min_count=args.min_samples,
                         rel_threshold=args.threshold)
    for entry in diff["regressions"]:
        print(f"REGRESSION [{entry['shape']}] "
              f"{describe_regression(entry)}")
    for entry in diff["improvements"]:
        pct = -entry["rel_change"] * 100.0
        print(f"improvement [{entry['shape']}] {entry['kind']} "
              f"{entry['metric']} self {entry['baseline_s']:.4f}s -> "
              f"{entry['candidate_s']:.4f}s (-{pct:.0f}%)")
    for entry in diff["skipped"]:
        print(f"skipped [{entry['shape']}] {entry['kind']}: "
              f"{entry['reason']}")
    n = len(diff["regressions"])
    print(f"{n} regression{'s' if n != 1 else ''}, "
          f"{len(diff['improvements'])} improvements, "
          f"{len(diff['skipped'])} skipped "
          f"(gate: n>={args.min_samples}, rel>={args.threshold})")
    return 1 if diff["regressions"] else 0


def _profile_live(args) -> int:
    """`tpuctl profile live`: fetch the apiserver's /debug/profile and
    print the per-shape critical-path summary (full JSON on stdout is
    one `curl` away; this is the human view)."""
    import urllib.request
    url = f"{args.server.rstrip('/')}/debug/profile"
    if args.backend:
        url += "?backend=" + urllib.parse.quote(args.backend)
    try:
        with urllib.request.urlopen(url, timeout=15) as resp:
            doc = json.load(resp)
    except Exception as e:
        print(f"error: /debug/profile unreachable at {url}: {e}",
              file=sys.stderr)
        return 1
    print(_profile_summary(doc))
    retention = doc.get("retention")
    if retention and retention.get("dropped"):
        print(f"warning: {retention['dropped']} spans dropped by "
              "tail-sampling retention — the profile window is truncated")
    return 0


def _incident(args) -> int:
    """`tpuctl incident list` / `tpuctl incident show ID`: the
    apiserver's /debug/incidents surface — ranked root-cause bundles
    for every rollback/breach/straggler/preemption/reclaim the
    operator's forensics engine has seen."""
    import urllib.request
    base = f"{args.server.rstrip('/')}/debug/incidents"
    if args.verb == "show":
        if not args.id:
            print("error: incident show needs an incident id",
                  file=sys.stderr)
            return 2
        url = base + "/" + urllib.parse.quote(args.id)
        try:
            with urllib.request.urlopen(url, timeout=15) as resp:
                bundle = json.load(resp)
        except Exception as e:
            print(f"error: {url} unreachable: {e}", file=sys.stderr)
            return 1
        print(json.dumps(bundle, indent=2, sort_keys=True))
        return 0
    url = base + (f"?limit={args.limit}" if args.limit else "")
    try:
        with urllib.request.urlopen(url, timeout=15) as resp:
            doc = json.load(resp)
    except Exception as e:
        print(f"error: {url} unreachable: {e}", file=sys.stderr)
        return 1
    rows = []
    for row in doc.get("incidents", []):
        ent = row.get("entity") or {}
        top = row.get("top_suspect") or {}
        rows.append([
            row.get("id", ""), row.get("trigger", ""),
            (f"{ent.get('namespace', '')}/{ent.get('name', '')}"
             if ent else "-"),
            (f"{top.get('kind', '')} {top.get('key', '')}"
             if top else "-"),
            f"{top.get('lead_s', 0):.1f}s" if top else "-",
        ])
    if not rows:
        print("no incidents")
        return 0
    print(_table(rows, ["ID", "TRIGGER", "ENTITY", "TOP-SUSPECT",
                        "LEAD"]))
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(prog="tpuctl",
                                 description="TPU pod-slice orchestration CLI")
    ap.add_argument("--server", default="http://127.0.0.1:8765")
    ap.add_argument("-n", "--namespace", default="default")
    sub = ap.add_subparsers(dest="cmd", required=True)

    g = sub.add_parser("get", help="list resources")
    g.add_argument("resource", choices=sorted(KIND_BY_ALIAS))
    g.add_argument("-l", "--selector", default="")

    app = sub.add_parser("apply", help="apply manifest file(s)")
    app.add_argument("-f", "--filename", action="append", required=True,
                     help="YAML/JSON manifest (repeatable; multi-doc ok)")
    app.add_argument("--force-conflicts", action="store_true",
                     help="steal fields owned by other managers "
                          "(Server-Side Apply force)")

    st = sub.add_parser("status", help="full status of one resource")
    st.add_argument("resource", choices=["cluster", "job", "service", "cronjob"])
    st.add_argument("name")

    cc = sub.add_parser("create",
                        help="create a cluster or add a worker group")
    cc.add_argument("what", choices=["cluster", "workergroup", "service"])
    cc.add_argument("name")
    cc.add_argument("--cluster", default="",
                    help="(workergroup) existing TpuCluster to extend")
    cc.add_argument("--tpu", default="v5e", help="TPU generation (v4/v5e/v5p/v6e)")
    cc.add_argument("--topology", default="2x2", help="ICI topology, e.g. 4x4x4")
    cc.add_argument("--slices", type=int, default=1)
    cc.add_argument("--min-slices", type=int, default=None)
    cc.add_argument("--max-slices", type=int, default=None)
    cc.add_argument("--group", default="workers")
    cc.add_argument("--image", default="kuberay-tpu/runtime:latest")
    cc.add_argument("--worker-cpu", default="8")
    cc.add_argument("--worker-memory", default="16Gi")
    cc.add_argument("--autoscale", action="store_true")
    # service-only flags (serveConfig application block).
    cc.add_argument("--model", default="llama3_8b",
                    help="(service) model the serve app runs")
    cc.add_argument("--paged", action="store_true",
                    help="(service) paged KV cache engine")
    cc.add_argument("--max-serve-len", type=int, default=2048,
                    help="(service) engine max sequence length")
    cc.add_argument("--checkpoint-dir", default="",
                    help="(service) serve trained weights from this "
                         "train checkpoint")

    sc = sub.add_parser("scale", help="scale a worker group (slice units)")
    sc.add_argument("name")
    sc.add_argument("--group", default=None)
    sc.add_argument("--replicas", type=int, required=True)

    sj = sub.add_parser("submit", help="submit a TpuJob")
    sj.add_argument("name")
    sj.add_argument("--tpu", default="v5e")
    sj.add_argument("--topology", default="2x2")
    sj.add_argument("--slices", type=int, default=1)
    sj.add_argument("--image", default="kuberay-tpu/runtime:latest")
    sj.add_argument("--mode", default="K8sJobMode",
                    choices=["K8sJobMode", "HTTPMode", "SidecarMode",
                             "InteractiveMode"])
    sj.add_argument("--backoff-limit", type=int, default=0)
    sj.add_argument("--shutdown-after-finish", action="store_true")
    sj.add_argument("--wait", action="store_true",
                    help="poll until the job reaches a terminal state")
    # Entrypoint is everything after a literal "--" (split before argparse;
    # REMAINDER would swallow flags that precede it).

    se = sub.add_parser("session",
                        help="forward local ports to a cluster's head "
                             "(the port-forward analogue)")
    se.add_argument("name")
    se.add_argument("--target", default="",
                    help="head host to forward to (default: derived from "
                         "cluster status coordinatorAddress)")
    se.add_argument("--local-dashboard", type=int, default=8265)
    se.add_argument("--local-serve", type=int, default=8000)
    se.add_argument("--print-only", action="store_true",
                    help="print the endpoints without forwarding")

    lg = sub.add_parser("logs", help="fetch a job's logs via its coordinator")
    lg.add_argument("name")
    lg.add_argument("--coordinator", default="",
                    help="coordinator base URL (default: derived from the "
                         "job's cluster status)")

    # Per-pod log download from the history archive (the kubectl-plugin
    # `ray log` analogue, ref kubectl-plugin/pkg/cmd/log.go — downloads
    # every node's collected log dir; works for crashed/deleted hosts).
    dlg = sub.add_parser(
        "download-logs",
        help="download a cluster's per-node logs from the history archive")
    dlg.add_argument("cluster")
    dlg.add_argument("--out-dir", default="",
                     help="destination (default ./<cluster>-logs)")
    dlg.add_argument("--node", default="",
                     help="only this node's logs (default: all nodes)")
    dlg.add_argument("--history-url", default="",
                     help="history API base URL (default: the apiserver's "
                          "/api/history mount on --server)")

    # Orchestration timeline (chrome://tracing JSON) + device profiling
    # (the Ray-timeline/profile-events analogue, SURVEY §5.1).
    tl = sub.add_parser("timeline",
                        help="cluster lifecycle as Chrome-trace JSON "
                             "(stdout; load in chrome://tracing/Perfetto)")
    tl.add_argument("cluster")

    pf = sub.add_parser("profile",
                        help="device profiling and critical-path analytics: "
                             "`profile CLUSTER` captures a jax.profiler "
                             "trace; `profile live` fetches the apiserver's "
                             "/debug/profile; `profile diff BASE CAND` "
                             "compares two tpu-profile/v1 artifacts")
    pf.add_argument("target",
                    help="cluster name, or the verbs 'live' / 'diff'")
    pf.add_argument("paths", nargs="*",
                    help="(diff) baseline and candidate profile JSON files")
    pf.add_argument("--duration", type=float, default=5.0)
    pf.add_argument("--coordinator", default="",
                    help="coordinator base URL (default: derived from "
                         "cluster status)")
    pf.add_argument("--backend", default="",
                    help="(live) scope the profile to one serve backend")
    pf.add_argument("--min-samples", type=int, default=5,
                    help="(diff) noise gate: both sides need this many "
                         "windows per span kind")
    pf.add_argument("--threshold", type=float, default=0.25,
                    help="(diff) noise gate: relative change a kind must "
                         "clear to count as a regression")

    inc = sub.add_parser(
        "incident",
        help="incident forensics bundles: `incident list` shows the "
             "ranked index from /debug/incidents, `incident show ID` "
             "prints one full tpu-incident/v1 bundle")
    inc.add_argument("verb", choices=["list", "show"])
    inc.add_argument("id", nargs="?", default="",
                     help="(show) incident id, e.g. inc000001")
    inc.add_argument("--limit", type=int, default=0,
                     help="(list) newest rows to fetch (server default "
                          "64)")

    for name in ("suspend", "resume"):
        sp = sub.add_parser(name)
        sp.add_argument("resource", choices=["cluster", "job"])
        sp.add_argument("name")

    dl = sub.add_parser("delete")
    dl.add_argument("resource", choices=["cluster", "job", "service", "cronjob"])
    dl.add_argument("name")

    argv = list(sys.argv[1:] if argv is None else argv)
    entry: List[str] = []
    if "--" in argv:
        split = argv.index("--")
        argv, entry = argv[:split], argv[split + 1:]
    args = ap.parse_args(argv)
    args.entrypoint = entry
    client = ApiClient(args.server)

    try:
        return _dispatch(args, client)
    except ApiError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    except TopologyError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1


def _dispatch(args, client: ApiClient) -> int:
    ns = args.namespace
    if args.cmd == "get":
        kind = KIND_BY_ALIAS[args.resource]
        items = client.list(kind, ns, getattr(args, "selector", ""))
        if args.resource in ("workergroup", "workergroups"):
            rows = []
            for c in items:
                st = c.get("status", {})
                for grp in c.get("spec", {}).get("workerGroupSpecs", []):
                    rows.append([
                        grp.get("groupName", ""), c["metadata"]["name"],
                        grp.get("accelerator", ""),
                        grp.get("topology", ""),
                        str(grp.get("replicas", 0)),
                        f"{grp.get('minReplicas', 0)}/"
                        f"{grp.get('maxReplicas', 0)}",
                        str(st.get("state", ""))])
            print(_table(rows, ["GROUP", "CLUSTER", "ACCEL", "TOPOLOGY",
                                "SLICES", "MIN/MAX", "CLUSTER-STATE"]))
        elif args.resource == "slices":
            print(_slice_rows(items))
        elif kind == "TpuCluster":
            print(_cluster_rows(items))
        elif kind == "TpuJob":
            print(_job_rows(items))
        else:
            rows = [[i["metadata"]["name"],
                     i.get("status", {}).get("serviceStatus",
                                             i.get("reason", ""))]
                    for i in items]
            print(_table(rows, ["NAME", "STATUS"]))
        return 0

    if args.cmd == "apply":
        import yaml
        applied, errors = 0, 0
        for fn in args.filename:
            try:
                with open(fn) as f:
                    docs = [d for d in yaml.safe_load_all(f) if d]
            except (OSError, yaml.YAMLError) as e:
                print(f"error reading {fn}: {e}", file=sys.stderr)
                errors += 1
                continue
            for doc in docs:
                if not isinstance(doc, dict) or not isinstance(
                        doc.get("metadata", {}), dict):
                    print(f"error in {fn}: document is not a mapping",
                          file=sys.stderr)
                    errors += 1
                    continue
                doc.setdefault("metadata", {}).setdefault("namespace", ns)
                kind = doc.get("kind", "?")
                name = doc["metadata"].get("name", "?")
                try:
                    # Server-Side Apply upsert (kubectl apply --server-
                    # side semantics): the server creates or merges our
                    # declared fields, tracks tpuctl's ownership in
                    # managedFields, and 409s if another manager (the
                    # autoscaler, tpuctl-scale, ...) owns a field we
                    # change; --force-conflicts steals ownership.  A
                    # partial manifest against a MISSING object still
                    # 422s — there is nothing to merge into.
                    existed = True
                    try:
                        client.get(kind, name, doc["metadata"]["namespace"])
                    except ApiError as e:
                        if e.code != 404:
                            raise
                        existed = False
                    client.patch(
                        kind, name, doc["metadata"]["namespace"],
                        doc, patch_type="apply",
                        field_manager="tpuctl",
                        force=args.force_conflicts)
                    print(f"{kind.lower()}/{name} "
                          f"{'configured' if existed else 'created'}")
                    applied += 1
                except (ApiError, KeyError, AttributeError, TypeError) as e:
                    # kubectl semantics: report and continue the batch
                    # (unknown kinds / malformed docs included).
                    print(f"error applying {kind.lower()}/{name}: {e!r}",
                          file=sys.stderr)
                    errors += 1
        if not applied and not errors:
            print("error: no documents found", file=sys.stderr)
            return 1
        return 1 if errors else 0

    if args.cmd == "status":
        obj = client.get(KIND_BY_ALIAS[args.resource], args.name, ns)
        print(json.dumps(obj.get("status", {}), indent=2, default=str))
        return 0

    if args.cmd == "create":
        if args.what == "workergroup":
            # Add a worker group to an EXISTING cluster (ref
            # kubectl-plugin `kubectl ray create workergroup`), with
            # optimistic-concurrency retry against controller writes.
            if not args.cluster:
                print("error: --cluster is required for workergroup",
                      file=sys.stderr)
                return 1
            for flag, bad, why in (
                    ("--group", args.group != "workers",
                     "the positional NAME names the group"),
                    ("--autoscale", args.autoscale,
                     "autoscaling is a cluster-level field")):
                if bad:
                    print(f"error: {flag} is not valid for workergroup "
                          f"({why})", file=sys.stderr)
                    return 1
            group = build_worker_group(args, args.name)

            cur = client.get(C.KIND_CLUSTER, args.cluster, ns)
            if any(g.get("groupName") == args.name
                   for g in cur["spec"].get("workerGroupSpecs", [])):
                print(f"error: group {args.name!r} already exists in "
                      f"{args.cluster}", file=sys.stderr)
                return 1
            # Strategic merge on workerGroupSpecs (mergeKey groupName):
            # an unknown key APPENDS, existing groups are untouched —
            # one round trip, no conflict loop.
            client.patch(C.KIND_CLUSTER, args.cluster, ns,
                         {"spec": {"workerGroupSpecs": [group]}},
                         patch_type="strategic",
                         field_manager="tpuctl-edit")
            print(f"workergroup/{args.name} added to "
                  f"tpucluster/{args.cluster}")
            return 0
        if args.cluster:
            print("error: --cluster only applies to workergroup",
                  file=sys.stderr)
            return 1
        if args.what == "service":
            obj = client.create(build_service_manifest(args))
            print(f"tpuservice/{obj['metadata']['name']} created")
            return 0
        obj = client.create(build_cluster_manifest(args))
        print(f"tpucluster/{obj['metadata']['name']} created")
        return 0

    if args.cmd == "scale":
        # One read resolves the target group; the write is a strategic
        # PATCH on just {replicas, maxReplicas} of that group — a
        # concurrent controller/autoscaler edit to anything else is
        # never clobbered and never 409s us.
        obj = client.get(C.KIND_CLUSTER, args.name, ns)
        groups = obj["spec"].get("workerGroupSpecs", [])
        if args.group is None and len(groups) > 1:
            print("error: cluster has multiple worker groups "
                  f"({', '.join(g['groupName'] for g in groups)}) — "
                  "pass --group", file=sys.stderr)
            return 1
        target = next((g for g in groups
                       if args.group in (None, g["groupName"])), None)
        if target is None:
            print(f"error: group {args.group!r} not found", file=sys.stderr)
            return 1
        client.patch(
            C.KIND_CLUSTER, args.name, ns,
            {"spec": {"workerGroupSpecs": [{
                "groupName": target["groupName"],
                "replicas": args.replicas,
                "maxReplicas": max(target.get("maxReplicas", 0),
                                   args.replicas)}]}},
            patch_type="strategic", field_manager="tpuctl-scale")
        print(f"tpucluster/{args.name} group {target['groupName']} "
              f"scaled to {args.replicas} slices")
        return 0

    if args.cmd == "submit":
        entry = args.entrypoint
        if not entry and args.mode != "InteractiveMode":
            print("error: entrypoint required (after --)", file=sys.stderr)
            return 1
        job = {
            "apiVersion": C.API_VERSION, "kind": C.KIND_JOB,
            "metadata": {"name": args.name, "namespace": ns},
            "spec": {
                "entrypoint": " ".join(entry),
                "submissionMode": args.mode,
                "backoffLimit": args.backoff_limit,
                "shutdownAfterJobFinishes": args.shutdown_after_finish,
                "clusterSpec": build_cluster_manifest(argparse.Namespace(
                    name=args.name, namespace=ns, tpu=args.tpu,
                    topology=args.topology, slices=args.slices,
                    min_slices=None, max_slices=None, group="workers",
                    image=args.image, worker_cpu="8", worker_memory="16Gi",
                    autoscale=False))["spec"],
            },
        }
        client.create(job)
        print(f"tpujob/{args.name} submitted")
        if args.wait:
            while True:
                st = client.get(C.KIND_JOB, args.name, ns).get("status", {})
                state = st.get("jobDeploymentStatus", "")
                if state in ("Complete", "Failed", "Suspended"):
                    print(f"tpujob/{args.name}: {state} "
                          f"({st.get('jobStatus', '')})")
                    return 0 if state == "Complete" else 2
                time.sleep(1.0)
        return 0

    if args.cmd == "session":
        from kuberay_tpu.cli.session import run_session
        cluster = client.get(C.KIND_CLUSTER, args.name, ns)
        target = args.target
        if not target:
            addr = cluster.get("status", {}).get("coordinatorAddress", "")
            target = addr.split(":")[0] if addr else ""
        if not target:
            print("error: no coordinator address known; pass --target",
                  file=sys.stderr)
            return 1
        return run_session(target, [
            (args.local_dashboard, C.PORT_DASHBOARD, "dashboard"),
            (args.local_serve, C.PORT_SERVE, "serve"),
        ], print_only=args.print_only)

    if args.cmd == "logs":
        from kuberay_tpu.runtime.coordinator_client import (
            CoordinatorClient, CoordinatorError, default_client_provider)
        job = client.get(C.KIND_JOB, args.name, ns)
        st = job.get("status", {})
        if args.coordinator:
            coord = CoordinatorClient(args.coordinator)
        else:
            cluster_status = st.get("clusterStatus", {})
            if not cluster_status.get("coordinatorAddress"):
                print("error: no coordinator address known; pass "
                      "--coordinator", file=sys.stderr)
                return 1
            coord = default_client_provider(cluster_status)
        jid = st.get("jobId", "")
        if not jid:
            print(f"error: job {args.name} has no jobId yet "
                  f"(state: {st.get('jobDeploymentStatus', 'unknown')})",
                  file=sys.stderr)
            return 1
        try:
            print(coord.get_job_logs(jid), end="")
        except CoordinatorError as e:
            print(f"error: {e}", file=sys.stderr)
            return 1
        return 0

    if args.cmd == "timeline":
        from kuberay_tpu.utils.timeline import cluster_timeline
        cluster = client.get(C.KIND_CLUSTER, args.cluster, ns)
        events = client.list("Event", ns)
        jobs = [j for j in client.list(C.KIND_JOB, ns)
                if j.get("status", {}).get("clusterName") == args.cluster]
        print(json.dumps(cluster_timeline(cluster, events, jobs)))
        return 0

    if args.cmd == "incident":
        return _incident(args)

    if args.cmd == "profile":
        if args.target == "diff":
            return _profile_diff(args)
        if args.target == "live":
            return _profile_live(args)
        from kuberay_tpu.runtime.coordinator_client import (
            CoordinatorClient, default_client_provider)
        if args.coordinator:
            coord = CoordinatorClient(args.coordinator)
        else:
            cluster = client.get(C.KIND_CLUSTER, args.target, ns)
            status = cluster.get("status", {})
            if not status.get("coordinatorAddress"):
                print("error: no coordinator address known; pass "
                      "--coordinator", file=sys.stderr)
                return 1
            coord = default_client_provider(status)
        try:
            out = coord.start_profile(args.duration)
        except Exception as e:
            print(f"error: profile start failed: {e}", file=sys.stderr)
            return 1
        print(f"profiling for {args.duration}s -> {out.get('trace_dir')}")
        print("trace is archived with node logs; fetch via "
              "`tpuctl download-logs` once collected")
        return 0

    if args.cmd == "download-logs":
        import urllib.request
        base = (args.history_url or client.base_url).rstrip("/")
        prefix = f"{base}/api/history/logs/{ns}/{args.cluster}"
        try:
            with urllib.request.urlopen(prefix, timeout=15) as resp:
                files = json.load(resp).get("files", [])
        except Exception as e:
            print(f"error: history archive unreachable at {base}: {e}",
                  file=sys.stderr)
            return 1
        if args.node:
            files = [f for f in files if f.split("/", 1)[0] == args.node]
        if not files:
            print(f"no archived logs for {ns}/{args.cluster}"
                  + (f" node {args.node}" if args.node else ""),
                  file=sys.stderr)
            return 1
        out_dir = args.out_dir or f"./{args.cluster}-logs"
        quoted = urllib.parse.quote
        for rel in files:
            parts = rel.split("/")
            # The file list is server-supplied: refuse traversal segments
            # so a hostile archive can't write outside --out-dir.
            if any(p in ("", ".", "..") for p in parts) or rel.startswith("/"):
                print(f"  skip {rel}: unsafe path", file=sys.stderr)
                continue
            url = prefix + "/" + "/".join(quoted(p) for p in parts)
            dest = os.path.join(out_dir, *parts)
            os.makedirs(os.path.dirname(dest), exist_ok=True)
            try:
                with urllib.request.urlopen(url, timeout=30) as resp:
                    data = resp.read()
            except Exception as e:
                print(f"  skip {rel}: {e}", file=sys.stderr)
                continue
            with open(dest, "wb") as f:
                f.write(data)
            print(f"  {rel} ({len(data)} bytes)")
        print(f"downloaded to {out_dir}")
        return 0

    if args.cmd in ("suspend", "resume"):
        kind = KIND_BY_ALIAS[args.resource]
        spec_patch = {"suspend": args.cmd == "suspend"}
        if args.cmd == "suspend" and kind == C.KIND_JOB:
            spec_patch["shutdownAfterJobFinishes"] = True
        client.patch(kind, args.name, ns, {"spec": spec_patch},
                     patch_type="merge", field_manager="tpuctl-edit")
        print(f"{args.resource}/{args.name} {args.cmd}{'ed' if args.cmd == 'suspend' else 'd'}")
        return 0

    if args.cmd == "delete":
        client.delete(KIND_BY_ALIAS[args.resource], args.name, ns)
        print(f"{args.resource}/{args.name} deleted")
        return 0
    return 1


if __name__ == "__main__":
    sys.exit(main())
