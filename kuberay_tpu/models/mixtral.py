"""Mixtral-family sparse MoE transformer, TPU-first.

The payload of BASELINE config #5 (Mixtral-8x7B expert-parallel across two
v5p-32 worker groups).  Same pure-pytree/scan design as models/llama.py;
the FFN is replaced by a top-k routed expert layer built for the MXU:

- GShard/Switch-style capacity dispatch: one-hot dispatch/combine einsums
  (dense, batched matmuls — no gathers/scatters XLA can't tile);
- expert weights carry the ``expert`` logical axis -> sharded over the
  ``ep`` mesh axis, so dispatch/combine einsums lower to all-to-alls over
  ICI/DCN;
- router aux losses: load-balancing (Switch) + z-loss on router logits.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from kuberay_tpu.ops.attention import flash_attention
from kuberay_tpu.ops.rmsnorm import rmsnorm
from kuberay_tpu.ops.rope import apply_rope, rope_frequencies


@dataclasses.dataclass(frozen=True)
class MixtralConfig:
    vocab_size: int = 32000
    d_model: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    d_ff: int = 14336
    n_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    router_aux_weight: float = 0.01
    router_z_weight: float = 1e-3
    max_seq_len: int = 8192
    rope_theta: float = 1e6
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    attn_impl: str = "auto"
    remat: bool = True
    xent_chunk: int = 0        # vocab-chunked CE (ops/xent.py); 0 = dense

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads


CONFIGS: Dict[str, MixtralConfig] = {
    "mixtral_tiny": MixtralConfig(
        vocab_size=256, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=128, n_experts=4, top_k=2, max_seq_len=128,
        dtype=jnp.float32, attn_impl="xla", remat=False),
    # 8192 (lane-aligned): 3 full chunks + a 7424-wide tail over V=32000.
    "mixtral_8x7b": MixtralConfig(xent_chunk=8192),
}


def param_axes(cfg: MixtralConfig) -> Dict[str, Any]:
    return {
        "embed": ("vocab", "embed"),
        "layers": {
            "attn_norm": ("layers", "norm"),
            "wq": ("layers", "embed", "heads"),
            "wk": ("layers", "embed", "kv_heads"),
            "wv": ("layers", "embed", "kv_heads"),
            "wo": ("layers", "heads", "embed"),
            "mlp_norm": ("layers", "norm"),
            "router": ("layers", "embed", "expert"),
            "w_gate": ("layers", "expert", "embed", "mlp"),
            "w_up": ("layers", "expert", "embed", "mlp"),
            "w_down": ("layers", "expert", "mlp", "embed"),
        },
        "final_norm": ("norm",),
        "lm_head": ("embed", "vocab"),
    }


def init_params(cfg: MixtralConfig, key: jax.Array) -> Dict[str, Any]:
    d, f, v, L, E = (cfg.d_model, cfg.d_ff, cfg.vocab_size, cfg.n_layers,
                     cfg.n_experts)
    hq, hkv, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    k = iter(jax.random.split(key, 16))
    std = 1.0 / math.sqrt(d)
    out_std = std / math.sqrt(2 * L)

    def rnd(key, shape, scale):
        return (jax.random.normal(key, shape, dtype=jnp.float32) * scale
                ).astype(cfg.dtype)

    return {
        "embed": rnd(next(k), (v, d), std),
        "layers": {
            "attn_norm": jnp.ones((L, d), cfg.dtype),
            "wq": rnd(next(k), (L, d, hq * hd), std),
            "wk": rnd(next(k), (L, d, hkv * hd), std),
            "wv": rnd(next(k), (L, d, hkv * hd), std),
            "wo": rnd(next(k), (L, hq * hd, d), out_std),
            "mlp_norm": jnp.ones((L, d), cfg.dtype),
            "router": rnd(next(k), (L, d, E), std),
            "w_gate": rnd(next(k), (L, E, d, f), std),
            "w_up": rnd(next(k), (L, E, d, f), std),
            "w_down": rnd(next(k), (L, E, f, d), out_std),
        },
        "final_norm": jnp.ones((d,), cfg.dtype),
        "lm_head": rnd(next(k), (d, v), std),
    }


# --------------------------------------------------------------------------
# MoE layer
# --------------------------------------------------------------------------

def moe_ffn_dropless(cfg: MixtralConfig, x: jax.Array,
                     lp: Dict[str, jax.Array],
                     token_mask: Optional[jax.Array] = None,
                     impl: str = "grouped") -> jax.Array:
    """Dropless top-k MoE: every token's chosen experts always run.  Used
    for serving decode steps, where it buys per-request determinism: no
    cross-request capacity contention.

    impl="grouped" (default): tokens sorted by expert, one ragged_dot per
    weight tensor (ops/moe_matmul.py) — K·T matmul rows.
    impl="dense": every expert runs on every token, unchosen experts
    zero-weighted — E·T rows ((E/K)x the FLOPs); the numeric reference.
    """
    from kuberay_tpu.ops.moe_matmul import dropless_reference, grouped_moe_ffn

    B, S, d = x.shape
    K = cfg.top_k
    T = B * S
    xt = x.reshape(T, d)
    logits = (xt @ lp["router"]).astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)
    topw, topi = jax.lax.top_k(probs, K)
    topw = topw / jnp.clip(topw.sum(-1, keepdims=True), 1e-9)
    if token_mask is not None:
        topw = topw * token_mask.reshape(T, 1).astype(topw.dtype)
    fn = grouped_moe_ffn if impl == "grouped" else dropless_reference
    out = fn(xt, lp["w_gate"], lp["w_up"], lp["w_down"], topi, topw)
    return out.reshape(B, S, d).astype(x.dtype)


def moe_ffn(cfg: MixtralConfig, x: jax.Array, lp: Dict[str, jax.Array],
            token_mask: Optional[jax.Array] = None
            ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Top-k routed expert FFN.  x: [B, S, d] -> (out, aux_losses).

    Capacity dispatch (GShard): each expert processes at most
    C = ceil(T * top_k / E * capacity_factor) tokens; overflow tokens drop
    that expert assignment (their other top-k picks still apply).

    ``token_mask`` [B, S] (1 = real token): masked tokens neither claim
    expert capacity nor contribute output — essential under serving where
    the batch mixes active requests with padding/inactive slots (a padding
    token must never evict a real token's expert assignment).
    """
    B, S, d = x.shape
    E, K = cfg.n_experts, cfg.top_k
    T = B * S
    C = max(1, int(math.ceil(T * K / E * cfg.capacity_factor)))
    xt = x.reshape(T, d)

    logits = (xt @ lp["router"]).astype(jnp.float32)           # [T, E]
    probs = jax.nn.softmax(logits, axis=-1)
    topw, topi = jax.lax.top_k(probs, K)                        # [T, K]
    topw = topw / jnp.clip(topw.sum(-1, keepdims=True), 1e-9)   # renormalize
    if token_mask is not None:
        flat_mask = token_mask.reshape(T).astype(topw.dtype)
        topw = topw * flat_mask[:, None]

    # Aux losses: Switch load-balance + router z-loss.
    me = probs.mean(axis=0)                                     # [E]
    ce = jnp.zeros(E).at[topi[:, 0]].add(1.0) / T               # top-1 fraction
    aux = {
        "load_balance": E * jnp.sum(me * ce) * cfg.router_aux_weight,
        "router_z": (jnp.mean(jax.nn.logsumexp(logits, -1) ** 2)
                     * cfg.router_z_weight),
    }

    # Position of each (token, k) within its expert's capacity buffer.
    onehot = jax.nn.one_hot(topi, E, dtype=jnp.int32)           # [T, K, E]
    if token_mask is not None:
        # Masked tokens claim no capacity slots at all.
        onehot = onehot * token_mask.reshape(T).astype(jnp.int32)[:, None, None]
    flat = onehot.reshape(T * K, E)
    pos = jnp.cumsum(flat, axis=0) * flat - 1                   # [T*K, E]
    pos = pos.reshape(T, K, E)
    in_cap = (pos >= 0) & (pos < C)
    # dispatch [T, E, C]: token t occupies slot pos in expert e.
    disp = (jax.nn.one_hot(pos, C, dtype=x.dtype)
            * in_cap[..., None].astype(x.dtype))               # [T, K, E, C]
    combine = disp * topw[..., None, None].astype(x.dtype)     # [T, K, E, C]
    disp = disp.sum(axis=1)                                     # [T, E, C]
    combine = combine.sum(axis=1)                               # [T, E, C]

    # Expert compute: batched over E (shards over the ep mesh axis; the
    # dispatch einsum lowers to an all-to-all when T is dp/fsdp-sharded).
    ex_in = jnp.einsum("tec,td->ecd", disp, xt)                 # [E, C, d]
    gated = jax.nn.silu(jnp.einsum("ecd,edf->ecf", ex_in, lp["w_gate"])) \
        * jnp.einsum("ecd,edf->ecf", ex_in, lp["w_up"])
    ex_out = jnp.einsum("ecf,efd->ecd", gated, lp["w_down"])    # [E, C, d]
    out = jnp.einsum("tec,ecd->td", combine, ex_out)            # [T, d]
    return out.reshape(B, S, d).astype(x.dtype), aux


def _layer(cfg: MixtralConfig, x, lp, cos, sin):
    B, S, d = x.shape
    hq, hkv, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    h = rmsnorm(x, lp["attn_norm"], cfg.norm_eps)
    q = (h @ lp["wq"]).reshape(B, S, hq, hd)
    kk = (h @ lp["wk"]).reshape(B, S, hkv, hd)
    vv = (h @ lp["wv"]).reshape(B, S, hkv, hd)
    q = apply_rope(q, cos, sin)
    kk = apply_rope(kk, cos, sin)
    attn = flash_attention(q, kk, vv, causal=True, impl=cfg.attn_impl)
    x = x + (attn.reshape(B, S, hq * hd) @ lp["wo"]).astype(x.dtype)

    h = rmsnorm(x, lp["mlp_norm"], cfg.norm_eps)
    moe_out, aux = moe_ffn(cfg, h, lp)
    x = x + moe_out
    return x, aux


def forward(cfg: MixtralConfig, params: Dict[str, Any], tokens: jax.Array
            ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """tokens [B,S] -> (logits [B,S,V] f32, aux losses summed over layers)."""
    x, aux = forward_hidden(cfg, params, tokens)
    logits = jnp.einsum("bsd,dv->bsv", x, params["lm_head"],
                        preferred_element_type=jnp.float32)
    return logits, aux


def forward_hidden(cfg: MixtralConfig, params, tokens):
    """tokens [B,S] -> (final hidden [B,S,d], aux) without the logits."""
    B, S = tokens.shape
    x = jnp.take(params["embed"], tokens, axis=0)
    cos, sin = rope_frequencies(cfg.head_dim, S, cfg.rope_theta)

    def layer_fn(x, lp):
        return _layer(cfg, x, lp, cos, sin)
    if cfg.remat:
        layer_fn = jax.checkpoint(layer_fn, prevent_cse=False)
    x, aux_stack = jax.lax.scan(layer_fn, x, params["layers"])
    aux = {k: v.sum() for k, v in aux_stack.items()}
    return rmsnorm(x, params["final_norm"], cfg.norm_eps), aux


def loss_fn(cfg: MixtralConfig, params, tokens, targets,
            mask: Optional[jax.Array] = None,
            z_loss: float = 1e-4) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    if cfg.xent_chunk:
        from kuberay_tpu.ops.xent import chunked_softmax_xent_loss
        B, S = tokens.shape
        x, aux = forward_hidden(cfg, params, tokens)
        ce_total, m = chunked_softmax_xent_loss(
            x.reshape(B * S, -1), params["lm_head"], targets.reshape(-1),
            mask=None if mask is None else
            mask.reshape(-1).astype(jnp.float32),
            z_loss=z_loss, chunk=cfg.xent_chunk)
        total = ce_total + aux["load_balance"] + aux["router_z"]
        metrics = {"loss": m["loss"], "total_loss": total,
                   "aux_load_balance": aux["load_balance"],
                   "aux_router_z": aux["router_z"],
                   "accuracy": m["accuracy"]}
        return total, metrics

    logits, aux = forward(cfg, params, tokens)
    logz = jax.nn.logsumexp(logits, axis=-1)
    true_logit = jnp.take_along_axis(logits, targets[..., None], -1).squeeze(-1)
    nll = logz - true_logit
    if mask is None:
        mask = jnp.ones_like(nll)
    denom = jnp.maximum(mask.sum(), 1.0)
    ce = (nll * mask).sum() / denom
    zl = z_loss * ((logz ** 2) * mask).sum() / denom
    total = ce + zl + aux["load_balance"] + aux["router_z"]
    metrics = {"loss": ce, "total_loss": total,
               "aux_load_balance": aux["load_balance"],
               "aux_router_z": aux["router_z"],
               "accuracy": ((logits.argmax(-1) == targets) * mask).sum() / denom}
    return total, metrics
