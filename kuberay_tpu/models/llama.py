"""Llama-3-family transformer, TPU-first.

Design (not a torch port):
- pure functional: params are a pytree of arrays; ``forward(params, tokens)``
  is jit/pjit-able with zero Python state;
- layers are *stacked* ([n_layers, ...] leading dim) and iterated with
  ``lax.scan`` — one compiled layer body regardless of depth (fast compiles,
  natural remat boundary);
- every param leaf carries logical sharding axes (``param_axes``) consumed
  by parallel/mesh.py rules -> NamedSharding;
- attention is the Pallas flash kernel on TPU (ops/attention.py), GQA
  native; norms are the fused Pallas RMSNorm;
- bfloat16 activations/params by default, f32 logits for a stable loss.

The flagship config (llama3_8b) is BASELINE config #3's payload
(RayJob Llama-3-8B pretrain); smaller presets serve tests and single-chip
benchmarks.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from kuberay_tpu.ops.attention import flash_attention
from kuberay_tpu.ops.rmsnorm import rmsnorm
from kuberay_tpu.ops.rope import apply_rope, rope_frequencies


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 128256
    d_model: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    d_ff: int = 14336
    max_seq_len: int = 8192
    rope_theta: float = 500000.0
    norm_eps: float = 1e-5
    tie_embeddings: bool = False
    dtype: Any = jnp.bfloat16
    # auto | pallas | xla | pallas_interpret | ring | ring_rdma
    # 'ring' = sequence-parallel ring attention over the mesh's sp axis
    # (long-context training; forward() must receive the mesh);
    # 'ring_rdma' = same, with the Pallas make_async_remote_copy ring
    # (parallel/ring_pallas.py) overlapping exchange with compute.
    attn_impl: str = "auto"
    remat: bool = True
    # Remat granularity (docs/roofline_llama1b.md): "full" checkpoints
    # whole layers (max memory savings; re-runs the whole fwd in bwd —
    # ~25% of reported-MFU headroom at the bench shape); "dots" saves
    # matmul outputs and recomputes only cheap elementwise ops (less
    # memory headroom, higher useful-FLOPs MFU).
    remat_policy: str = "full"
    # Vocab-chunked cross entropy (ops/xent.py): 0 = dense logits.  Set
    # for large-vocab configs — the [B,S,V] f32 logits tensor is the
    # single largest training activation at Llama-3 scale.
    xent_chunk: int = 0

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    def num_params(self) -> int:
        d, f, v, L = self.d_model, self.d_ff, self.vocab_size, self.n_layers
        hd = self.head_dim
        attn = d * (self.n_heads * hd) + 2 * d * (self.n_kv_heads * hd) \
            + (self.n_heads * hd) * d
        mlp = 3 * d * f
        per_layer = attn + mlp + 2 * d
        head = 0 if self.tie_embeddings else d * v
        return v * d + L * per_layer + d + head


CONFIGS: Dict[str, LlamaConfig] = {
    # Test-size: everything tiny, CPU-friendly.
    "llama_tiny": LlamaConfig(
        vocab_size=256, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=128, max_seq_len=128, dtype=jnp.float32, attn_impl="xla",
        remat=False),
    # ~125M for smoke benchmarks.
    "llama_125m": LlamaConfig(
        vocab_size=32000, d_model=768, n_layers=12, n_heads=12, n_kv_heads=12,
        d_ff=2048, max_seq_len=2048),
    # ~1.2B: single-chip bench model (fits v5e 16 GiB with bf16 + adam).
    "llama_1b": LlamaConfig(
        vocab_size=32768, d_model=2048, n_layers=16, n_heads=16, n_kv_heads=8,
        d_ff=8192, max_seq_len=4096),
    # The flagship (BASELINE config #3).  128k vocab -> chunked CE.
    "llama3_8b": LlamaConfig(xent_chunk=16384),
    "llama3_70b": LlamaConfig(
        d_model=8192, n_layers=80, n_heads=64, n_kv_heads=8, d_ff=28672,
        xent_chunk=16384),
}


# --------------------------------------------------------------------------
# Params: init + logical axes
# --------------------------------------------------------------------------

def param_axes(cfg: LlamaConfig) -> Dict[str, Any]:
    """Logical sharding axes per leaf, same tree structure as params."""
    axes = {
        "embed": ("vocab", "embed"),
        "layers": {
            "attn_norm": ("layers", "norm"),
            "wq": ("layers", "embed", "heads"),
            "wk": ("layers", "embed", "kv_heads"),
            "wv": ("layers", "embed", "kv_heads"),
            "wo": ("layers", "heads", "embed"),
            "mlp_norm": ("layers", "norm"),
            "w_gate": ("layers", "embed", "mlp"),
            "w_up": ("layers", "embed", "mlp"),
            "w_down": ("layers", "mlp", "embed"),
        },
        "final_norm": ("norm",),
    }
    if not cfg.tie_embeddings:
        axes["lm_head"] = ("embed", "vocab")
    return axes


def init_params(cfg: LlamaConfig, key: jax.Array) -> Dict[str, Any]:
    """Scaled-normal init (GPT-NeoX style residual scaling on out-projs)."""
    d, f, v, L = cfg.d_model, cfg.d_ff, cfg.vocab_size, cfg.n_layers
    hq, hkv, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    k = iter(jax.random.split(key, 16))
    std = 1.0 / math.sqrt(d)
    out_std = std / math.sqrt(2 * L)

    def norm_init(*shape):
        return jnp.ones(shape, dtype=cfg.dtype)

    def rnd(key, shape, scale):
        return (jax.random.normal(key, shape, dtype=jnp.float32) * scale
                ).astype(cfg.dtype)

    params = {
        "embed": rnd(next(k), (v, d), std),
        "layers": {
            "attn_norm": norm_init(L, d),
            "wq": rnd(next(k), (L, d, hq * hd), std),
            "wk": rnd(next(k), (L, d, hkv * hd), std),
            "wv": rnd(next(k), (L, d, hkv * hd), std),
            "wo": rnd(next(k), (L, hq * hd, d), out_std),
            "mlp_norm": norm_init(L, d),
            "w_gate": rnd(next(k), (L, d, f), std),
            "w_up": rnd(next(k), (L, d, f), std),
            "w_down": rnd(next(k), (L, f, d), out_std),
        },
        "final_norm": norm_init(d),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = rnd(next(k), (d, v), std)
    return params


# --------------------------------------------------------------------------
# Forward
# --------------------------------------------------------------------------

def _layer(cfg: LlamaConfig, x: jax.Array, lp: Dict[str, jax.Array],
           cos: jax.Array, sin: jax.Array, mesh=None) -> jax.Array:
    """One transformer block.  x: [B, S, d]."""
    B, S, d = x.shape
    hq, hkv, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim

    h = rmsnorm(x, lp["attn_norm"], cfg.norm_eps)
    q = (h @ lp["wq"]).reshape(B, S, hq, hd)
    kk = (h @ lp["wk"]).reshape(B, S, hkv, hd)
    vv = (h @ lp["wv"]).reshape(B, S, hkv, hd)
    q = apply_rope(q, cos, sin)
    kk = apply_rope(kk, cos, sin)
    if cfg.attn_impl in ("ring", "ring_rdma"):
        if mesh is None:
            raise ValueError(
                f"attn_impl={cfg.attn_impl!r} requires forward(..., mesh=)")
        from kuberay_tpu.parallel.ring import ring_attention
        attn = ring_attention(
            q, kk, vv, mesh, causal=True,
            impl="rdma" if cfg.attn_impl == "ring_rdma" else "ppermute")
    else:
        attn = flash_attention(q, kk, vv, causal=True, impl=cfg.attn_impl)
    x = x + (attn.reshape(B, S, hq * hd) @ lp["wo"]).astype(x.dtype)

    h = rmsnorm(x, lp["mlp_norm"], cfg.norm_eps)
    gated = jax.nn.silu(h @ lp["w_gate"]) * (h @ lp["w_up"])
    x = x + (gated @ lp["w_down"]).astype(x.dtype)
    return x


def forward_hidden(cfg: LlamaConfig, params: Dict[str, Any],
                   tokens: jax.Array, mesh=None):
    """tokens: [B, S] -> (final hidden [B, S, d], head [d, V])."""
    B, S = tokens.shape
    x = jnp.take(params["embed"], tokens, axis=0)          # [B, S, d]
    cos, sin = rope_frequencies(cfg.head_dim, S, cfg.rope_theta)

    layer_fn = lambda x, lp: (_layer(cfg, x, lp, cos, sin, mesh), None)
    if cfg.remat:
        if cfg.remat_policy == "dots":
            policy = jax.checkpoint_policies.dots_with_no_batch_dims_saveable
        elif cfg.remat_policy == "full":
            policy = None
        else:
            raise ValueError(
                f"unknown remat_policy {cfg.remat_policy!r} "
                f"(expected 'full' or 'dots')")
        layer_fn = jax.checkpoint(layer_fn, prevent_cse=False,
                                  policy=policy)
    x, _ = jax.lax.scan(layer_fn, x, params["layers"])

    x = rmsnorm(x, params["final_norm"], cfg.norm_eps)
    head = params["embed"].T if cfg.tie_embeddings else params["lm_head"]
    return x, head


def forward(cfg: LlamaConfig, params: Dict[str, Any],
            tokens: jax.Array, mesh=None) -> jax.Array:
    """tokens: [B, S] int32 -> logits [B, S, vocab] float32.

    ``mesh`` is required for attn_impl='ring' (sequence parallelism over
    its sp axis — the long-context training path)."""
    x, head = forward_hidden(cfg, params, tokens, mesh)
    return jnp.einsum("bsd,dv->bsv", x, head,
                      preferred_element_type=jnp.float32)


def loss_fn(cfg: LlamaConfig, params: Dict[str, Any], tokens: jax.Array,
            targets: jax.Array, mask: Optional[jax.Array] = None,
            z_loss: float = 1e-4,
            mesh=None) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Next-token cross entropy with z-loss regularization.

    tokens/targets: [B, S]; mask: [B, S] (1 = contributes to loss).

    With ``cfg.xent_chunk > 0`` the [B,S,V] logits tensor is never
    materialized (ops/xent.py chunked CE — identical math).
    """
    if cfg.xent_chunk:
        from kuberay_tpu.ops.xent import chunked_softmax_xent_loss
        B, S = tokens.shape
        x, head = forward_hidden(cfg, params, tokens, mesh)
        return chunked_softmax_xent_loss(
            x.reshape(B * S, -1), head, targets.reshape(-1),
            mask=None if mask is None else
            mask.reshape(-1).astype(jnp.float32),
            z_loss=z_loss, chunk=cfg.xent_chunk)

    logits = forward(cfg, params, tokens, mesh)            # [B,S,V] f32
    logz = jax.nn.logsumexp(logits, axis=-1)               # [B,S]
    true_logit = jnp.take_along_axis(
        logits, targets[..., None], axis=-1).squeeze(-1)
    nll = logz - true_logit
    zl = z_loss * jnp.square(logz)
    per_tok = nll + zl
    if mask is None:
        mask = jnp.ones_like(nll)
    denom = jnp.maximum(mask.sum(), 1.0)
    loss = (per_tok * mask).sum() / denom
    metrics = {
        "loss": (nll * mask).sum() / denom,
        "z_loss": (zl * mask).sum() / denom,
        "accuracy": ((logits.argmax(-1) == targets) * mask).sum() / denom,
    }
    return loss, metrics
