"""Spec validation for all four CR kinds (ref utils/validation.go:23-831).

Called at the head of each reconcile (and by the admission webhooks) exactly
like the reference; invalid specs get a status condition, not a crash.
"""

from __future__ import annotations

import re
from typing import List

from kuberay_tpu.api.tpucluster import TpuCluster, TpuClusterSpec, UpgradeStrategyType
from kuberay_tpu.api.tpucronjob import ConcurrencyPolicy, TpuCronJob
from kuberay_tpu.api.tpujob import (
    DeletionPolicyType,
    JobSubmissionMode,
    TpuJob,
)
from kuberay_tpu.api.tpuservice import ServiceUpgradeType, TpuService
from kuberay_tpu.topology import TopologyError
from kuberay_tpu.utils import features
from kuberay_tpu.utils.cron import CronError, parse_cron

_DNS1123 = re.compile(r"^[a-z0-9]([-a-z0-9]*[a-z0-9])?$")


class ValidationError(ValueError):
    pass


def _check(cond: bool, msg: str, errs: List[str]):
    if not cond:
        errs.append(msg)


def validate_metadata(name: str, errs: List[str], max_len: int = 63):
    _check(bool(name), "metadata.name must be set", errs)
    if name:
        _check(len(name) <= max_len, f"metadata.name {name!r} exceeds {max_len} chars", errs)
        _check(bool(_DNS1123.match(name)),
               f"metadata.name {name!r} is not a valid DNS-1123 label", errs)


def validate_cluster_spec(spec: TpuClusterSpec, errs: List[str]):
    # Head group: a head container must exist (ref ValidateRayClusterSpec
    # head template checks).
    _check(bool(spec.headGroupSpec.template.spec.containers),
           "headGroupSpec.template must have at least one container", errs)

    seen = set()
    for i, g in enumerate(spec.workerGroupSpecs):
        prefix = f"workerGroupSpecs[{i}]"
        _check(bool(g.groupName), f"{prefix}.groupName must be set", errs)
        if g.groupName:
            _check(bool(_DNS1123.match(g.groupName)),
                   f"{prefix}.groupName {g.groupName!r} is not a valid DNS-1123 label", errs)
            _check(g.groupName not in seen,
                   f"{prefix}.groupName {g.groupName!r} is duplicated", errs)
            seen.add(g.groupName)
        try:
            g.slice_topology()
        except TopologyError as e:
            errs.append(f"{prefix}: {e}")
        _check(g.replicas >= 0, f"{prefix}.replicas must be >= 0", errs)
        _check(g.minReplicas >= 0, f"{prefix}.minReplicas must be >= 0", errs)
        _check(g.maxReplicas >= g.minReplicas,
               f"{prefix}.maxReplicas must be >= minReplicas", errs)
        if spec.enableInTreeAutoscaling:
            _check(g.minReplicas <= g.replicas <= g.maxReplicas,
                   f"{prefix}.replicas must be within [minReplicas, maxReplicas] "
                   "when autoscaling is enabled", errs)
        _check(bool(g.template.spec.containers),
               f"{prefix}.template must have at least one container", errs)

    _check(spec.upgradeStrategy in (UpgradeStrategyType.RECREATE, UpgradeStrategyType.NONE),
           f"upgradeStrategy must be Recreate or None, got {spec.upgradeStrategy!r}", errs)

    if spec.headStateOptions is not None:
        hso = spec.headStateOptions
        _check(hso.backend in ("memory", "external", "persistent"),
               f"headStateOptions.backend {hso.backend!r} invalid", errs)
        if hso.backend == "external":
            _check(bool(hso.externalStorageAddress),
                   "headStateOptions.externalStorageAddress required for external backend",
                   errs)
        if hso.backend == "persistent":
            _check(features.enabled("CoordinatorPersistentState"),
                   "headStateOptions.backend=persistent requires the "
                   "CoordinatorPersistentState feature gate", errs)

    if spec.managedBy:
        _check(spec.managedBy in ("kuberay-tpu-operator", "kueue.x-k8s.io/multikueue"),
               f"managedBy {spec.managedBy!r} not recognized", errs)


def validate_cluster(cluster: TpuCluster) -> List[str]:
    errs: List[str] = []
    validate_metadata(cluster.metadata.name, errs)
    validate_cluster_spec(cluster.spec, errs)
    return errs


def validate_job(job: TpuJob) -> List[str]:
    errs: List[str] = []
    validate_metadata(job.metadata.name, errs)
    spec = job.spec

    has_spec = spec.clusterSpec is not None
    has_selector = bool(spec.clusterSelector)
    _check(has_spec or has_selector,
           "one of clusterSpec or clusterSelector must be set", errs)
    _check(not (has_spec and has_selector),
           "clusterSpec and clusterSelector are mutually exclusive", errs)
    if has_spec:
        validate_cluster_spec(spec.clusterSpec, errs)

    _check(spec.submissionMode in (
        JobSubmissionMode.K8S_JOB, JobSubmissionMode.HTTP,
        JobSubmissionMode.SIDECAR, JobSubmissionMode.INTERACTIVE),
        f"submissionMode {spec.submissionMode!r} invalid", errs)
    if spec.submissionMode != JobSubmissionMode.INTERACTIVE:
        _check(bool(spec.entrypoint),
               "entrypoint must be set unless submissionMode is InteractiveMode", errs)
    if spec.submissionMode == JobSubmissionMode.INTERACTIVE:
        _check(not spec.entrypoint,
               "entrypoint must be empty in InteractiveMode", errs)
    # Sidecar mode cannot be combined with a selected (pre-existing) cluster:
    if spec.submissionMode == JobSubmissionMode.SIDECAR:
        _check(not has_selector,
               "SidecarMode requires clusterSpec (submitter rides the head pod)", errs)

    # Selector-mode constraints (ref validation.go:409,423,438): a job on a
    # pre-existing shared cluster cannot suspend it or retry with fresh ones.
    if has_selector:
        _check(not spec.suspend,
               "suspend cannot be used with clusterSelector", errs)
        _check(spec.backoffLimit == 0,
               "backoffLimit cannot be used with clusterSelector "
               "(retries mint fresh clusters)", errs)
    if spec.suspend:
        _check(spec.shutdownAfterJobFinishes,
               "suspend requires shutdownAfterJobFinishes", errs)

    _check(spec.backoffLimit >= 0, "backoffLimit must be >= 0", errs)
    _check(spec.activeDeadlineSeconds >= 0, "activeDeadlineSeconds must be >= 0", errs)
    _check(spec.preRunningDeadlineSeconds >= 0,
           "preRunningDeadlineSeconds must be >= 0", errs)
    _check(spec.ttlSecondsAfterFinished >= 0,
           "ttlSecondsAfterFinished must be >= 0", errs)
    if spec.ttlSecondsAfterFinished and not spec.shutdownAfterJobFinishes:
        errs.append("ttlSecondsAfterFinished requires shutdownAfterJobFinishes")

    if spec.deletionStrategy is not None:
        _check(features.enabled("DeletionRules"),
               "deletionStrategy requires the DeletionRules feature gate", errs)
        for i, rule in enumerate(spec.deletionStrategy.rules):
            _check(rule.policy in (
                DeletionPolicyType.DELETE_CLUSTER, DeletionPolicyType.DELETE_WORKERS,
                DeletionPolicyType.DELETE_SELF, DeletionPolicyType.DELETE_NONE),
                f"deletionStrategy.rules[{i}].policy {rule.policy!r} invalid", errs)
            _check(rule.condition in ("Succeeded", "Failed"),
                   f"deletionStrategy.rules[{i}].condition must be Succeeded|Failed", errs)
            _check(rule.ttlSeconds >= 0,
                   f"deletionStrategy.rules[{i}].ttlSeconds must be >= 0", errs)
        if spec.shutdownAfterJobFinishes and spec.deletionStrategy.rules:
            errs.append("deletionStrategy and shutdownAfterJobFinishes are mutually exclusive")
    return errs


def validate_service(svc: TpuService) -> List[str]:
    errs: List[str] = []
    validate_metadata(svc.metadata.name, errs, max_len=50)  # room for cluster suffixes
    validate_cluster_spec(svc.spec.clusterSpec, errs)
    _check(svc.spec.upgradeStrategy in (
        ServiceUpgradeType.NEW_CLUSTER, ServiceUpgradeType.INCREMENTAL,
        ServiceUpgradeType.NONE),
        f"upgradeStrategy {svc.spec.upgradeStrategy!r} invalid", errs)
    if svc.spec.upgradeStrategy == ServiceUpgradeType.INCREMENTAL:
        _check(features.enabled("TpuServiceIncrementalUpgrade"),
               "incremental upgrade requires the TpuServiceIncrementalUpgrade gate", errs)
        opts = svc.spec.upgradeOptions
        if opts is not None:
            _check(0 < opts.stepSizePercent <= 100,
                   "upgradeOptions.stepSizePercent must be in (0, 100]", errs)
            _check(opts.intervalSeconds > 0,
                   "upgradeOptions.intervalSeconds must be > 0", errs)
            _check(0 <= opts.maxSurgePercent <= 100,
                   "upgradeOptions.maxSurgePercent must be in [0, 100]", errs)
    _check(bool(svc.spec.serveConfig), "serveConfig must be set", errs)
    _check(svc.spec.clusterDeletionDelaySeconds >= 0,
           "clusterDeletionDelaySeconds must be >= 0", errs)
    return errs


def validate_cronjob(cron: TpuCronJob) -> List[str]:
    errs: List[str] = []
    validate_metadata(cron.metadata.name, errs)
    _check(features.enabled("TpuCronJob"),
           "TpuCronJob requires the TpuCronJob feature gate", errs)
    try:
        parse_cron(cron.spec.schedule)
    except CronError as e:
        errs.append(f"schedule: {e}")
    _check(cron.spec.concurrencyPolicy in (
        ConcurrencyPolicy.ALLOW, ConcurrencyPolicy.FORBID, ConcurrencyPolicy.REPLACE),
        f"concurrencyPolicy {cron.spec.concurrencyPolicy!r} invalid", errs)
    # Validate the template as a job (minus metadata).
    tmpl_job = TpuJob(spec=cron.spec.jobTemplate)
    tmpl_job.metadata.name = cron.metadata.name or "template"
    errs.extend(f"jobTemplate: {e}" for e in validate_job(tmpl_job))
    return errs


def kind_validators():
    """kind -> dict-validating callable (shared by the apiserver and the
    admission webhook — one validation surface, two front doors)."""
    from kuberay_tpu.api.computetemplate import (
        ComputeTemplate,
        validate_compute_template,
    )
    return {
        "TpuCluster": lambda d: validate_cluster(TpuCluster.from_dict(d)),
        "TpuJob": lambda d: validate_job(TpuJob.from_dict(d)),
        "TpuService": lambda d: validate_service(TpuService.from_dict(d)),
        "TpuCronJob": lambda d: validate_cronjob(TpuCronJob.from_dict(d)),
        "ComputeTemplate": lambda d: validate_compute_template(
            ComputeTemplate.from_dict(d)),
    }
