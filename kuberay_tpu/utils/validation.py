"""Spec validation for all four CR kinds (ref utils/validation.go:23-831).

Called at the head of each reconcile (and by the admission webhooks) exactly
like the reference; invalid specs get a status condition, not a crash.
"""

from __future__ import annotations

import re
from typing import List

from kuberay_tpu.api.tpucluster import TpuCluster, TpuClusterSpec, UpgradeStrategyType
from kuberay_tpu.api.tpucronjob import ConcurrencyPolicy, TpuCronJob
from kuberay_tpu.api.tpujob import (
    DeletionPolicyType,
    JobSubmissionMode,
    TpuJob,
)
from kuberay_tpu.api.tpuservice import ServiceUpgradeType, TpuService
from kuberay_tpu.topology import TopologyError
from kuberay_tpu.utils import constants as C
from kuberay_tpu.utils import features
from kuberay_tpu.utils.cron import CronError, parse_cron

_DNS1123 = re.compile(r"^[a-z0-9]([-a-z0-9]*[a-z0-9])?$")
# DNS-1035 (must start with a letter): CR names feed Service names, and
# kube rejects digit-leading Service names (ref IsDNS1035Label checks in
# ValidateRayClusterMetadata/ValidateRayServiceMetadata).
_DNS1035 = re.compile(r"^[a-z]([-a-z0-9]*[a-z0-9])?$")
_QUANTITY = re.compile(r"^[0-9]+(\.[0-9]+)?(Ki|Mi|Gi|Ti|Pi|k|M|G|T)?$")

# Marks errors that only apply at CREATE time (admission strips them on
# updates so legacy objects that predate the rule stay modifiable).
DNS1035_CREATE_ONLY_PREFIX = "[create-only] "


def waive_create_only(errs: List[str]) -> List[str]:
    """Drop create-only errors — for validation of objects that already
    exist (updates in admission; every controller re-validation)."""
    return [e for e in errs if not e.startswith(DNS1035_CREATE_ONLY_PREFIX)]


def surface_create_only(errs: List[str]) -> List[str]:
    """Strip the internal marker for user-facing create errors."""
    return [e[len(DNS1035_CREATE_ONLY_PREFIX):]
            if e.startswith(DNS1035_CREATE_ONLY_PREFIX) else e
            for e in errs]


class ValidationError(ValueError):
    pass


def _check(cond: bool, msg: str, errs: List[str]):
    if not cond:
        errs.append(msg)


def validate_metadata(name: str, errs: List[str], max_len: int = 63):
    _check(bool(name), "metadata.name must be set", errs)
    if name:
        _check(len(name) <= max_len, f"metadata.name {name!r} exceeds {max_len} chars", errs)
        if not _DNS1035.match(name):
            # Two distinguishable failures: a digit-leading but otherwise
            # valid DNS-1123 name only breaks *derived Service* creation,
            # so admission relaxes it on UPDATE (a pre-existing legacy
            # object must stay mutable — see validate_admission); any
            # other shape violation is unconditionally fatal.
            if _DNS1123.match(name):
                errs.append(DNS1035_CREATE_ONLY_PREFIX +
                            f"metadata.name {name!r} must start with a "
                            "letter (derived Service names require "
                            "DNS-1035)")
            else:
                errs.append(f"metadata.name {name!r} is not a valid "
                            "DNS-1123 label")


def _container_env(template) -> dict:
    """name -> value for the first container's env (the operator-managed
    container; ref RayContainerIndex)."""
    cs = template.spec.containers
    if not cs:
        return {}
    return {e.name: e.value for e in (cs[0].env or [])}


def validate_cluster_spec(spec: TpuClusterSpec, errs: List[str]):
    # Head group: a head container must exist (ref ValidateRayClusterSpec
    # head template checks).
    _check(bool(spec.headGroupSpec.template.spec.containers),
           "headGroupSpec.template must have at least one container", errs)

    seen = set()
    for i, g in enumerate(spec.workerGroupSpecs):
        prefix = f"workerGroupSpecs[{i}]"
        _check(bool(g.groupName), f"{prefix}.groupName must be set", errs)
        if g.groupName:
            _check(bool(_DNS1123.match(g.groupName)),
                   f"{prefix}.groupName {g.groupName!r} is not a valid DNS-1123 label", errs)
            _check(g.groupName not in seen,
                   f"{prefix}.groupName {g.groupName!r} is duplicated", errs)
            seen.add(g.groupName)
        chips_per_host = None
        try:
            chips_per_host = g.slice_topology().chips_per_host
        except TopologyError as e:
            errs.append(f"{prefix}: {e}")
        _check(g.replicas >= 0, f"{prefix}.replicas must be >= 0", errs)
        _check(g.minReplicas >= 0, f"{prefix}.minReplicas must be >= 0", errs)
        _check(g.maxReplicas >= g.minReplicas,
               f"{prefix}.maxReplicas must be >= minReplicas", errs)
        if spec.enableInTreeAutoscaling:
            _check(g.minReplicas <= g.replicas <= g.maxReplicas,
                   f"{prefix}.replicas must be within [minReplicas, maxReplicas] "
                   "when autoscaling is enabled", errs)
            # Ref validation.go:212-217: a suspended group under the
            # autoscaler would immediately be resized back up.
            _check(not g.suspend,
                   f"{prefix} cannot be suspended with autoscaling enabled",
                   errs)
        _check(g.idleTimeoutSeconds >= 0,
               f"{prefix}.idleTimeoutSeconds must be >= 0", errs)
        if g.idleTimeoutSeconds and not spec.enableInTreeAutoscaling:
            # Ref validateWorkerGroupIdleTimeout (:868): the field only
            # means something to the autoscaler.
            errs.append(f"{prefix}.idleTimeoutSeconds is set but "
                        "autoscaling is not enabled")
        if g.suspend:
            # Ref :195-199 (RayJobDeletionPolicy gates worker suspend).
            _check(features.enabled("DeletionRules"),
                   f"{prefix}.suspend requires the DeletionRules feature "
                   "gate", errs)
        _check(bool(g.template.spec.containers),
               f"{prefix}.template must have at least one container", errs)
        # Conflicting TPU resource declarations (ref
        # validateRayGroupResources:60): the operator derives
        # google.com/tpu from the topology; an explicit different value
        # would silently win and break the slice's ICI assumptions.
        for c in g.template.spec.containers:
            for kind in ("requests", "limits"):
                declared = getattr(c.resources, kind).get(C.RESOURCE_TPU)
                if declared is not None and chips_per_host is not None and \
                        str(declared) != str(chips_per_host):
                    errs.append(
                        f"{prefix}: container {c.name!r} {kind} "
                        f"{C.RESOURCE_TPU}={declared} conflicts with "
                        f"topology-derived {chips_per_host} chips/host — "
                        "drop the explicit resource (the operator owns it)")

    _check(spec.upgradeStrategy in (UpgradeStrategyType.RECREATE, UpgradeStrategyType.NONE),
           f"upgradeStrategy must be Recreate or None, got {spec.upgradeStrategy!r}", errs)

    head_env = _container_env(spec.headGroupSpec.template)
    if spec.headStateOptions is not None:
        hso = spec.headStateOptions
        _check(hso.backend in ("memory", "external", "persistent"),
               f"headStateOptions.backend {hso.backend!r} invalid", errs)
        if hso.backend == "external":
            _check(bool(hso.externalStorageAddress),
                   "headStateOptions.externalStorageAddress required for external backend",
                   errs)
        else:
            # Ref redis-only field rejection (validation.go:306): fields
            # of the wrong backend silently doing nothing hides typos.
            _check(not hso.externalStorageAddress,
                   "headStateOptions.externalStorageAddress is only valid "
                   "for backend=external", errs)
        if hso.backend == "persistent":
            _check(features.enabled("CoordinatorPersistentState"),
                   "headStateOptions.backend=persistent requires the "
                   "CoordinatorPersistentState feature gate", errs)
        else:
            _check(not hso.storageClassName,
                   "headStateOptions.storageClassName is only valid for "
                   "backend=persistent", errs)
        _check(bool(_QUANTITY.match(hso.storageSize)),
               f"headStateOptions.storageSize {hso.storageSize!r} is not "
               "a valid quantity", errs)
        # Operator-managed env must not be hand-set alongside the options
        # (ref RAY_REDIS_ADDRESS / REDIS_PASSWORD rejections :158-183).
        _check("TPU_HEAD_EXTERNAL_STORAGE_ADDRESS" not in head_env,
               "cannot set TPU_HEAD_EXTERNAL_STORAGE_ADDRESS env in the "
               "head pod when headStateOptions is set — use "
               "headStateOptions.externalStorageAddress", errs)
    else:
        # Env implying external state without the options block (ref
        # :156: RAY_REDIS_ADDRESS without GcsFaultToleranceOptions).
        _check("TPU_HEAD_EXTERNAL_STORAGE_ADDRESS" not in head_env,
               "TPU_HEAD_EXTERNAL_STORAGE_ADDRESS implies external head "
               "state; set headStateOptions (backend=external) instead",
               errs)

    if spec.autoscalerOptions is not None:
        ao = spec.autoscalerOptions
        _check(ao.idleTimeoutSeconds >= 0,
               "autoscalerOptions.idleTimeoutSeconds must be >= 0", errs)
        _check(ao.upscalingMode in ("Default", "Aggressive", "Conservative"),
               f"autoscalerOptions.upscalingMode {ao.upscalingMode!r} "
               "invalid (Default|Aggressive|Conservative)", errs)
        _check(ao.imagePullPolicy in ("", "Always", "IfNotPresent", "Never"),
               f"autoscalerOptions.imagePullPolicy "
               f"{ao.imagePullPolicy!r} invalid", errs)

    if spec.networkPolicy is not None and spec.networkPolicy.enabled:
        _check(features.enabled("TpuClusterNetworkPolicy"),
               "spec.networkPolicy requires the TpuClusterNetworkPolicy "
               "feature gate", errs)
        _check(spec.networkPolicy.mode in ("DenyAll", "DenyAllEgress"),
               f"networkPolicy.mode {spec.networkPolicy.mode!r} invalid "
               "(DenyAll|DenyAllEgress)", errs)

    if spec.managedBy:
        _check(spec.managedBy in ("kuberay-tpu-operator", "kueue.x-k8s.io/multikueue"),
               f"managedBy {spec.managedBy!r} not recognized", errs)


def validate_cluster(cluster: TpuCluster) -> List[str]:
    errs: List[str] = []
    validate_metadata(cluster.metadata.name, errs)
    validate_cluster_spec(cluster.spec, errs)
    # upgradeStrategy is a direct-user knob: child clusters roll through
    # their owning CR's machinery (ref ValidateRayClusterUpgradeOptions
    # :50-56).
    origin = (cluster.metadata.labels or {}).get(
        C.LABEL_ORIGINATED_FROM_CRD, "")
    if origin in (C.KIND_JOB, C.KIND_SERVICE) and \
            cluster.spec.upgradeStrategy != UpgradeStrategyType.NONE:
        errs.append(f"upgradeStrategy cannot be set on a TpuCluster "
                    f"created by a {origin}")
    return errs


def validate_cluster_status(cluster: TpuCluster) -> List[str]:
    """Ref ValidateRayClusterStatus (:23): mutually exclusive suspend
    conditions — both True means a controller bug or a forged status."""
    from kuberay_tpu.api.tpucluster import ClusterConditionType
    conds = {c.type: c.status for c in cluster.status.conditions}
    if conds.get(ClusterConditionType.SUSPENDING) == "True" and \
            conds.get(ClusterConditionType.SUSPENDED) == "True":
        return ["status conditions Suspending and Suspended cannot both "
                "be True"]
    return []


def validate_job(job: TpuJob) -> List[str]:
    errs: List[str] = []
    validate_metadata(job.metadata.name, errs)
    spec = job.spec

    has_spec = spec.clusterSpec is not None
    has_selector = bool(spec.clusterSelector)
    _check(has_spec or has_selector,
           "one of clusterSpec or clusterSelector must be set", errs)
    _check(not (has_spec and has_selector),
           "clusterSpec and clusterSelector are mutually exclusive", errs)
    if has_spec:
        validate_cluster_spec(spec.clusterSpec, errs)

    _check(spec.submissionMode in (
        JobSubmissionMode.K8S_JOB, JobSubmissionMode.HTTP,
        JobSubmissionMode.SIDECAR, JobSubmissionMode.INTERACTIVE),
        f"submissionMode {spec.submissionMode!r} invalid", errs)
    if spec.submissionMode != JobSubmissionMode.INTERACTIVE:
        _check(bool(spec.entrypoint),
               "entrypoint must be set unless submissionMode is InteractiveMode", errs)
    if spec.submissionMode == JobSubmissionMode.INTERACTIVE:
        _check(not spec.entrypoint,
               "entrypoint must be empty in InteractiveMode", errs)
    # Sidecar mode cannot be combined with a selected (pre-existing) cluster:
    if spec.submissionMode == JobSubmissionMode.SIDECAR:
        _check(not has_selector,
               "SidecarMode requires clusterSpec (submitter rides the head pod)", errs)
        # Ref :454-465: the sidecar rides the head pod, so a custom
        # submitter template cannot apply, and a restarting head would
        # resubmit.
        _check(spec.submitterConfig.template is None,
               "SidecarMode does not support submitterConfig.template "
               "(the submitter rides the head pod)", errs)
        if has_spec:
            rp = spec.clusterSpec.headGroupSpec.template.spec.restartPolicy
            _check(rp in ("", "Never"),
                   "head pod restartPolicy must be Never or unset in "
                   "SidecarMode (a restarted head would resubmit)", errs)

    # Ref :451: a retried interactive job would reuse spec.jobId and jump
    # straight to Running instead of Waiting.
    if spec.submissionMode == JobSubmissionMode.INTERACTIVE:
        _check(spec.backoffLimit == 0,
               "backoffLimit cannot be used with InteractiveMode", errs)

    # Selector-mode constraints (ref validation.go:409,423,438): a job on a
    # pre-existing shared cluster cannot suspend it or retry with fresh ones.
    if has_selector:
        _check(all(v for v in spec.clusterSelector.values()),
               "clusterSelector values must not be empty", errs)
        _check(not spec.suspend,
               "suspend cannot be used with clusterSelector", errs)
        _check(spec.backoffLimit == 0,
               "backoffLimit cannot be used with clusterSelector "
               "(retries mint fresh clusters)", errs)
    if spec.suspend:
        _check(spec.shutdownAfterJobFinishes,
               "suspend requires shutdownAfterJobFinishes", errs)

    _check(spec.backoffLimit >= 0, "backoffLimit must be >= 0", errs)
    _check(spec.activeDeadlineSeconds >= 0, "activeDeadlineSeconds must be >= 0", errs)
    _check(spec.preRunningDeadlineSeconds >= 0,
           "preRunningDeadlineSeconds must be >= 0", errs)
    _check(spec.ttlSecondsAfterFinished >= 0,
           "ttlSecondsAfterFinished must be >= 0", errs)
    if spec.ttlSecondsAfterFinished and not spec.shutdownAfterJobFinishes:
        errs.append("ttlSecondsAfterFinished requires shutdownAfterJobFinishes")

    if spec.deletionStrategy is not None:
        _check(features.enabled("DeletionRules"),
               "deletionStrategy requires the DeletionRules feature gate", errs)
        autoscaled = (spec.clusterSpec is not None
                      and spec.clusterSpec.enableInTreeAutoscaling)
        seen_pairs = set()
        # (condition -> policy -> ttl) for the ordering check below.
        ttls: dict = {}
        for i, rule in enumerate(spec.deletionStrategy.rules):
            _check(rule.policy in (
                DeletionPolicyType.DELETE_CLUSTER, DeletionPolicyType.DELETE_WORKERS,
                DeletionPolicyType.DELETE_SELF, DeletionPolicyType.DELETE_NONE),
                f"deletionStrategy.rules[{i}].policy {rule.policy!r} invalid", errs)
            _check(rule.condition in ("Succeeded", "Failed"),
                   f"deletionStrategy.rules[{i}].condition must be Succeeded|Failed", errs)
            _check(rule.ttlSeconds >= 0,
                   f"deletionStrategy.rules[{i}].ttlSeconds must be >= 0", errs)
            # Ref validateDeletionRules (:659): per-(condition, policy)
            # uniqueness — a duplicate would make the engine's
            # most-impactful-rule selection ambiguous.
            pair = (rule.condition, rule.policy)
            _check(pair not in seen_pairs,
                   f"deletionStrategy.rules[{i}] duplicates policy "
                   f"{rule.policy!r} for condition {rule.condition!r}", errs)
            seen_pairs.add(pair)
            # Selector mode shares the cluster: rules may only delete the
            # job itself (ref :678-681).
            if has_selector and rule.policy in (
                    DeletionPolicyType.DELETE_CLUSTER,
                    DeletionPolicyType.DELETE_WORKERS):
                errs.append(
                    f"deletionStrategy.rules[{i}].policy {rule.policy!r} "
                    "not supported with clusterSelector (shared cluster)")
            # The autoscaler owns worker deletion (ref :682-685).
            if autoscaled and rule.policy == DeletionPolicyType.DELETE_WORKERS:
                errs.append(
                    f"deletionStrategy.rules[{i}].policy DeleteWorkers "
                    "not supported with autoscaling enabled")
            ttls.setdefault(rule.condition, {})[rule.policy] = rule.ttlSeconds
        # TTL ordering per condition (ref validateTTLConsistency :754):
        # Workers <= Cluster <= Self — a later stage deleting earlier
        # would race the earlier stage's resources away.
        order = (DeletionPolicyType.DELETE_WORKERS,
                 DeletionPolicyType.DELETE_CLUSTER,
                 DeletionPolicyType.DELETE_SELF)
        for cond, by_policy in ttls.items():
            chain = [(p, by_policy[p]) for p in order if p in by_policy]
            for (p1, t1), (p2, t2) in zip(chain, chain[1:]):
                _check(t2 >= t1,
                       f"deletionStrategy: for condition {cond!r}, "
                       f"{p2} TTL ({t2}) must be >= {p1} TTL ({t1})", errs)
        if spec.shutdownAfterJobFinishes and spec.deletionStrategy.rules:
            errs.append("deletionStrategy and shutdownAfterJobFinishes are mutually exclusive")
    return errs


def validate_service(svc: TpuService) -> List[str]:
    errs: List[str] = []
    validate_metadata(svc.metadata.name, errs, max_len=50)  # room for cluster suffixes
    validate_cluster_spec(svc.spec.clusterSpec, errs)
    _check(svc.spec.upgradeStrategy in (
        ServiceUpgradeType.NEW_CLUSTER, ServiceUpgradeType.INCREMENTAL,
        ServiceUpgradeType.NONE),
        f"upgradeStrategy {svc.spec.upgradeStrategy!r} invalid", errs)
    if svc.spec.upgradeStrategy == ServiceUpgradeType.INCREMENTAL:
        _check(features.enabled("TpuServiceIncrementalUpgrade"),
               "incremental upgrade requires the TpuServiceIncrementalUpgrade gate", errs)
        opts = svc.spec.upgradeOptions
        if opts is not None:
            _check(0 < opts.stepSizePercent <= 100,
                   "upgradeOptions.stepSizePercent must be in (0, 100]", errs)
            # Ref ValidateClusterUpgradeOptions (:579): a step larger
            # than the surge budget could never be applied.  maxSurge=0
            # is exempt: it means "no surge constraint consumer" here
            # (the controller steps traffic, not capacity surge), and
            # stepSizePercent > 0 would make it unsatisfiable.
            if opts.maxSurgePercent > 0:
                _check(opts.stepSizePercent <= opts.maxSurgePercent,
                       "upgradeOptions.stepSizePercent must be <= "
                       "maxSurgePercent", errs)
            _check(opts.intervalSeconds > 0,
                   "upgradeOptions.intervalSeconds must be > 0", errs)
            _check(0 <= opts.maxSurgePercent <= 100,
                   "upgradeOptions.maxSurgePercent must be in [0, 100]", errs)
    _check(bool(svc.spec.serveConfig), "serveConfig must be set", errs)
    # Serve-config shape: applications must be a list of uniquely named
    # app objects — the controller keys health/status by app name
    # (ref getAndCheckServeStatus / multi-app status contract).
    apps = svc.spec.serveConfig.get("applications") \
        if isinstance(svc.spec.serveConfig, dict) else None
    if apps is not None:
        if not isinstance(apps, list):
            errs.append("serveConfig.applications must be a list")
        else:
            app_names = set()
            for i, app in enumerate(apps):
                if not isinstance(app, dict) or not app.get("name"):
                    errs.append(f"serveConfig.applications[{i}] must be "
                                "an object with a non-empty name")
                    continue
                _check(app["name"] not in app_names,
                       f"serveConfig.applications[{i}].name "
                       f"{app['name']!r} is duplicated", errs)
                app_names.add(app["name"])
    kv = svc.spec.kvTiers
    if kv is not None:
        _check(kv.hostBlocks >= 0, "kvTiers.hostBlocks must be >= 0", errs)
        _check(kv.spillBlocks >= 0, "kvTiers.spillBlocks must be >= 0", errs)
        # A spill tier with no host tier is unreachable: demotion only
        # flows device → host → spill (docs/kv-tiers.md).
        _check(kv.spillBlocks == 0 or kv.hostBlocks > 0,
               "kvTiers.spillBlocks requires hostBlocks > 0", errs)
        _check(kv.sessionCapacity > 0,
               "kvTiers.sessionCapacity must be > 0", errs)
        _check(kv.sessionTtlSeconds > 0,
               "kvTiers.sessionTtlSeconds must be > 0", errs)
    _check(svc.spec.clusterDeletionDelaySeconds >= 0,
           "clusterDeletionDelaySeconds must be >= 0", errs)
    _check(svc.spec.serviceUnhealthySecondThreshold >= 0,
           "serviceUnhealthySecondThreshold must be >= 0", errs)
    _check(svc.spec.deploymentUnhealthySecondThreshold >= 0,
           "deploymentUnhealthySecondThreshold must be >= 0", errs)
    return errs


def validate_cronjob(cron: TpuCronJob) -> List[str]:
    errs: List[str] = []
    # Bound the name so deterministic child TpuJob names (cron name +
    # timestamp suffix) stay valid DNS labels (ref
    # MaxRayCronJobNameLength, validation.go:833).
    validate_metadata(cron.metadata.name, errs, max_len=52)
    _check(features.enabled("TpuCronJob"),
           "TpuCronJob requires the TpuCronJob feature gate", errs)
    # Ref :838: embedded TZ/CRON_TZ silently depends on the operator
    # pod's zoneinfo; reject it outright.
    _check("TZ" not in cron.spec.schedule,
           "cannot use TZ or CRON_TZ in schedule", errs)
    try:
        parse_cron(cron.spec.schedule)
    except CronError as e:
        errs.append(f"schedule: {e}")
    _check(cron.spec.startingDeadlineSeconds >= 0,
           "startingDeadlineSeconds must be >= 0", errs)
    _check(cron.spec.successfulJobsHistoryLimit >= 0,
           "successfulJobsHistoryLimit must be >= 0", errs)
    _check(cron.spec.failedJobsHistoryLimit >= 0,
           "failedJobsHistoryLimit must be >= 0", errs)
    _check(cron.spec.concurrencyPolicy in (
        ConcurrencyPolicy.ALLOW, ConcurrencyPolicy.FORBID, ConcurrencyPolicy.REPLACE),
        f"concurrencyPolicy {cron.spec.concurrencyPolicy!r} invalid", errs)
    # Validate the template as a job (minus metadata).
    tmpl_job = TpuJob(spec=cron.spec.jobTemplate)
    tmpl_job.metadata.name = cron.metadata.name or "template"
    errs.extend(f"jobTemplate: {e}" for e in validate_job(tmpl_job))
    return errs


def kind_validators():
    """kind -> dict-validating callable (shared by the apiserver and the
    admission webhook — one validation surface, two front doors)."""
    from kuberay_tpu.api.computetemplate import (
        ComputeTemplate,
        validate_compute_template,
    )
    return {
        "TpuCluster": lambda d: validate_cluster(TpuCluster.from_dict(d)),
        "TpuJob": lambda d: validate_job(TpuJob.from_dict(d)),
        "TpuService": lambda d: validate_service(TpuService.from_dict(d)),
        "TpuCronJob": lambda d: validate_cronjob(TpuCronJob.from_dict(d)),
        "ComputeTemplate": lambda d: validate_compute_template(
            ComputeTemplate.from_dict(d)),
    }
