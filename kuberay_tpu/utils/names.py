"""Name/hash generation (ref controllers/ray/utils/util.go).

- DNS-1123 truncation with stable hash suffixes (ref CheckName/TrimName).
- ``spec_hash_without_scale``: the upgrade-decision hash that ignores
  replica counts and slicesToDelete (ref
  GenerateHashWithoutReplicasAndWorkersToDelete util.go:645) so autoscaling
  never looks like a spec change.
"""

from __future__ import annotations

import copy
import hashlib
import json
from typing import Any, Dict

MAX_NAME_LEN = 63  # DNS-1123 label


def _short_hash(s: str, n: int = 8) -> str:
    return hashlib.sha256(s.encode()).hexdigest()[:n]


def truncate_name(name: str, max_len: int = MAX_NAME_LEN) -> str:
    """Truncate to a valid label length, keeping a stable suffix hash."""
    if len(name) <= max_len:
        return name
    h = _short_hash(name)
    return name[: max_len - len(h) - 1] + "-" + h


def head_pod_name(cluster: str) -> str:
    return truncate_name(f"{cluster}-head")


def head_service_name(cluster: str) -> str:
    return truncate_name(f"{cluster}-head-svc")


def headless_service_name(cluster: str) -> str:
    return truncate_name(f"{cluster}-headless")


def serve_service_name(cluster: str) -> str:
    return truncate_name(f"{cluster}-serve-svc")


def slice_name(cluster: str, group: str, slice_index: int) -> str:
    """Stable per-slice identity (ref worker-group-replica-name label).

    The reference generates random replica names (GenerateRayWorkerReplicaName);
    deterministic names make reconcile decisions replayable and testable.
    """
    return truncate_name(f"{cluster}-{group}-{slice_index}")


def worker_pod_name(cluster: str, group: str, slice_index: int, host_index: int) -> str:
    return truncate_name(f"{cluster}-{group}-{slice_index}-{host_index}")


def submitter_job_name(job: str) -> str:
    return truncate_name(f"{job}-submitter")


def cluster_name_for_job(job: str, attempt: int = 0) -> str:
    """Fresh cluster per retry attempt (ref getOrCreateRayClusterInstance)."""
    suffix = f"-{attempt}" if attempt else ""
    return truncate_name(f"{job}-cluster{suffix}")


def _strip_scale_fields(spec: Dict[str, Any]) -> Dict[str, Any]:
    spec = copy.deepcopy(spec)
    for group in spec.get("workerGroupSpecs", []):
        group.pop("replicas", None)
        group.pop("minReplicas", None)
        group.pop("maxReplicas", None)
        ss = group.get("scaleStrategy")
        if ss:
            ss.pop("slicesToDelete", None)
            if not ss:
                group.pop("scaleStrategy", None)
    return spec


def spec_hash_without_scale(cluster_spec: Dict[str, Any]) -> str:
    """Hash of a TpuClusterSpec dict ignoring scale-only fields
    (ref util.go:645).  Drives in-place-vs-new-cluster upgrade decisions."""
    stripped = _strip_scale_fields(cluster_spec)
    blob = json.dumps(stripped, sort_keys=True, separators=(",", ":"))
    return _short_hash(blob, 16)


def spec_hash(obj: Dict[str, Any]) -> str:
    return _short_hash(json.dumps(obj, sort_keys=True, separators=(",", ":")), 16)
