"""Feature gates (ref pkg/features/features.go:14-90).

Same alpha/beta/GA discipline as the reference's component-base gates; the
gate set maps the reference's 8 gates onto their TPU-native equivalents.
"""

from __future__ import annotations

import threading
from typing import Dict


class _Gate:
    def __init__(self, default: bool, stage: str):
        self.default = default
        self.stage = stage  # alpha | beta | ga


# Gate name -> (default, stage). Mirrors features.go:
#   RayMultiHostIndexing (beta, on) -> TpuMultiHostIndexing
#   RayServiceIncrementalUpgrade    -> TpuServiceIncrementalUpgrade
#   RayCronJob                      -> TpuCronJob
#   RayClusterNetworkPolicy         -> TpuClusterNetworkPolicy
#   GCSFaultToleranceEmbeddedStorage-> CoordinatorPersistentState
_DEFINITIONS: Dict[str, _Gate] = {
    "TpuMultiHostIndexing": _Gate(True, "beta"),
    "TpuServiceIncrementalUpgrade": _Gate(False, "alpha"),
    "TpuCronJob": _Gate(False, "alpha"),
    "TpuClusterNetworkPolicy": _Gate(False, "alpha"),
    "CoordinatorPersistentState": _Gate(False, "alpha"),
    "WarmSlicePools": _Gate(False, "alpha"),         # podpool analogue
    "SliceAutoscalerV2": _Gate(False, "alpha"),
    "DeletionRules": _Gate(True, "beta"),
}

_lock = threading.Lock()
_overrides: Dict[str, bool] = {}


class FeatureGateError(ValueError):
    pass


def enabled(name: str) -> bool:
    gate = _DEFINITIONS.get(name)
    if gate is None:
        raise FeatureGateError(f"unknown feature gate {name!r}")
    with _lock:
        return _overrides.get(name, gate.default)


def set_gates(gates: Dict[str, bool]) -> None:
    """Apply overrides (ref featureGates.Set main.go:188)."""
    for name in gates:
        if name not in _DEFINITIONS:
            raise FeatureGateError(
                f"unknown feature gate {name!r}; known: {sorted(_DEFINITIONS)}"
            )
    with _lock:
        _overrides.update(gates)


def parse_and_set(spec: str) -> None:
    """Parse ``"Gate1=true,Gate2=false"`` (the --feature-gates flag format)."""
    if not spec:
        return
    gates = {}
    for part in spec.split(","):
        if "=" not in part:
            raise FeatureGateError(f"malformed feature gate {part!r}")
        k, v = part.split("=", 1)
        if v.lower() not in ("true", "false"):
            raise FeatureGateError(f"feature gate {k!r} value must be true/false")
        gates[k.strip()] = v.lower() == "true"
    set_gates(gates)


def reset() -> None:
    """Test helper: drop all overrides."""
    with _lock:
        _overrides.clear()


def all_gates() -> Dict[str, bool]:
    with _lock:
        return {n: _overrides.get(n, g.default) for n, g in _DEFINITIONS.items()}
