"""Shared interpolated-quantile helpers.

Three copies of the same linear-interpolation estimator grew up
independently — ``benchmark/serve_bench.py`` (percentile over raw
samples), ``benchmark/controlplane_bench.py`` (quantile over a
pre-sorted list), and the GroupMonitor's adaptive watchdog budget
(``serve/group_health.py``) — plus a fourth variant interpolating
within histogram buckets in ``controlplane/slo.py``.  They all exist
for the same reason: a truncating index on a small window collapses
p99 toward p90 (for n=21 it never reports the tail sample at all),
which is exactly the outlier a p99 exists to surface.  This module is
the single implementation; the step-telemetry tracker
(``obs/steps.py``) uses it too.

Conventions (the 'inclusive' method, numpy's default linear
interpolation): position ``q * (n - 1)`` over the sorted samples,
linear blend between the two straddling values.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple


def quantile(samples: Sequence[float], q: float) -> float:
    """Interpolated quantile, ``q`` in [0, 1].  Sorts internally;
    returns 0.0 on an empty sample set (callers that need a loud empty
    case use :func:`percentile`)."""
    xs = sorted(samples)
    if not xs:
        return 0.0
    if len(xs) == 1:
        return xs[0]
    pos = q * (len(xs) - 1)
    lo = int(pos)
    hi = min(lo + 1, len(xs) - 1)
    frac = pos - lo
    return xs[lo] + (xs[hi] - xs[lo]) * frac


def percentile(samples: Sequence[float], pct: float) -> float:
    """Interpolated percentile, ``pct`` in (0, 100).  Raises
    ``ValueError`` on no samples — the benchmark contract, where a
    silent 0.0 would read as an impossibly good latency."""
    if not samples:
        raise ValueError("percentile() of no samples")
    return quantile(samples, pct / 100.0)


def median(samples: Sequence[float]) -> float:
    return quantile(samples, 0.5)


def histogram_quantile(bounds: Sequence[float], counts: Sequence[float],
                       q: float) -> Tuple[float, int]:
    """Interpolated quantile from histogram bucket counts.

    ``bounds`` are the buckets' upper bounds (ascending, trailing +inf
    allowed), ``counts`` the per-bucket (non-cumulative) observation
    counts.  Returns ``(value, total)``; ``(0.0, 0)`` when the
    histogram is empty.  Interpolation assumes observations are uniform
    within the crossing bucket (PromQL's ``histogram_quantile``
    convention); a rank landing in the open +inf tail reports the
    tail's floor — the largest claim the data supports.
    """
    n = sum(counts)
    if n <= 0:
        return 0.0, 0
    rank = q * n
    cum = 0
    lo = 0.0
    for bound, c in zip(bounds, counts):
        if c > 0:
            if cum + c >= rank:
                if bound == float("inf"):
                    return lo, n          # open tail: report the floor
                frac = (rank - cum) / c
                return lo + frac * (bound - lo), n
            cum += c
        if bound != float("inf"):
            lo = bound
    return lo, n


def sorted_quantile(sorted_samples: List[float], q: float) -> float:
    """Quantile over an already-sorted list (skips the re-sort; the
    controlplane bench calls this in a hot report loop)."""
    xs = sorted_samples
    if not xs:
        return 0.0
    if len(xs) == 1:
        return xs[0]
    pos = q * (len(xs) - 1)
    lo = int(pos)
    hi = min(lo + 1, len(xs) - 1)
    frac = pos - lo
    return xs[lo] + (xs[hi] - xs[lo]) * frac
