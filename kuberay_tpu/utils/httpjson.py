"""Shared JSON-over-HTTP handler plumbing for the framework's servers
(apiserver, coordinator, history server)."""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler
from typing import Any, Dict, Tuple


class JsonHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):   # quiet by default
        pass

    def _send(self, code: int, body: Any = None,
              headers: Dict[str, str] = None):
        data = (json.dumps(body).encode() if body is not None else b"")
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _send_text(self, code: int, text: str, ctype: str = "text/plain"):
        data = text.encode()
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _body(self) -> Dict[str, Any]:
        n = int(self.headers.get("Content-Length", 0))
        raw = self.rfile.read(n) if n else b"{}"
        return json.loads(raw or b"{}")


def serve_background(srv, name: str = "http-server") -> Tuple[object, str]:
    """Run an HTTPServer in a daemon thread; returns (server, base_url).
    Callers serving TLS (webhooks) format their own https URL."""
    threading.Thread(target=srv.serve_forever, daemon=True, name=name).start()
    return srv, f"http://{srv.server_address[0]}:{srv.server_address[1]}"
