"""Platform pinning: make JAX honor the JAX_PLATFORMS env var in-process.

Some hosting environments install site hooks that force a hardware plugin
into ``jax_platforms`` regardless of the env var; when the var names an
explicit platform list, re-assert it through the config API so CPU-only
runs never dial hardware tunnels."""

from __future__ import annotations

import os


def pin_platform_from_env() -> None:
    want = os.environ.get("JAX_PLATFORMS", "").strip().lower()
    if not want:
        return
    import jax
    if str(jax.config.jax_platforms or "").strip().lower() != want:
        jax.config.update("jax_platforms", want)
