"""Control-plane metrics: Prometheus text exposition, no client library.

Mirrors the reference's metric surface (controllers/ray/metrics/):
- ``tpu_cluster_provisioned_duration_seconds`` (ref
  kuberay_cluster_provisioned_duration_seconds, ray_cluster_metrics.go:35-37)
- ``tpu_job_execution_duration_seconds`` (ref
  kuberay_job_execution_duration_seconds, ray_job_metrics.go:33-35)
- state gauges per CR kind, reconcile counters/latencies.

Metrics are cleaned up when their CR disappears (ref
raycluster_controller.go:125 cleanup on delete).
"""

from __future__ import annotations

import bisect
import threading
import time
from typing import Dict, List, Optional, Tuple

_BUCKETS = (0.5, 1, 2, 5, 10, 30, 60, 120, 300, 600, 1800, float("inf"))

# Queue waits are milliseconds on a healthy control plane — the default
# (reconcile-scale) buckets would collapse them all into the first one.
_FAST_BUCKETS = (0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                 1, 2.5, 5, 10, float("inf"))

# Serving TTFT lives between the two: ms-scale when healthy, seconds
# when overloaded — SLO evaluation needs resolution across both regimes.
SERVE_LATENCY_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                         1, 2.5, 5, 10, 30, 60, float("inf"))

# Training steps span sub-second (small models) to minutes (giant
# pipelines); straggler forensics needs resolution both around a
# healthy median and in the 2-5x tail a slow host produces.
TRAIN_STEP_BUCKETS = (0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5,
                      10, 30, 60, 300, float("inf"))


class Histogram:
    def __init__(self, buckets=_BUCKETS):
        self.buckets = buckets
        self.counts = [0] * len(buckets)
        # exemplars[i]: latest (trace_id, value, ts) observed into bucket
        # i — OpenMetrics links a histogram bucket to one inspectable
        # trace (rendered only when set; plain Prometheus renders clean).
        self.exemplars: List[Optional[Tuple[str, float, float]]] = \
            [None] * len(buckets)
        self.total = 0.0
        self.n = 0

    def observe(self, v: float, exemplar: Optional[str] = None,
                exemplar_ts: Optional[float] = None):
        self.n += 1
        self.total += v
        # counts[i] holds observations landing in bucket i alone; render()
        # produces the cumulative le-series (doing both would double-count).
        # bisect_left finds the first bound >= v — the bucket the linear
        # scan would pick — without a Python-level loop (step heartbeats
        # hit this on every training step).
        i = bisect.bisect_left(self.buckets, v)
        if i < len(self.counts):
            self.counts[i] += 1
            if exemplar is not None:
                self.exemplars[i] = (exemplar, v, exemplar_ts)


class MetricsRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[Tuple[str, Tuple], float] = {}
        self._gauges: Dict[Tuple[str, Tuple], float] = {}
        self._hists: Dict[Tuple[str, Tuple], Histogram] = {}
        self._help: Dict[str, str] = {}

    def _labels_key(self, labels: Optional[Dict[str, str]]) -> Tuple:
        return tuple(sorted((labels or {}).items()))

    def describe(self, name: str, help_text: str):
        self._help[name] = help_text

    def inc(self, name: str, labels: Optional[Dict[str, str]] = None,
            value: float = 1.0):
        with self._lock:
            key = (name, self._labels_key(labels))
            self._counters[key] = self._counters.get(key, 0.0) + value

    def set_gauge(self, name: str, value: float,
                  labels: Optional[Dict[str, str]] = None):
        with self._lock:
            self._gauges[(name, self._labels_key(labels))] = value

    def observe(self, name: str, value: float,
                labels: Optional[Dict[str, str]] = None,
                buckets: Optional[Tuple] = None,
                exemplar: Optional[str] = None,
                exemplar_ts: Optional[float] = None):
        """``buckets`` applies on first observation of a series only (a
        histogram's buckets are fixed for its lifetime).  ``exemplar`` is
        a trace id attached to the bucket this observation lands in,
        rendered as an OpenMetrics exemplar so a p99 bucket links to an
        inspectable trace at /debug/traces?trace_id=."""
        with self._lock:
            key = (name, self._labels_key(labels))
            if key not in self._hists:
                self._hists[key] = Histogram(buckets or _BUCKETS)
            if exemplar is not None and exemplar_ts is None:
                exemplar_ts = time.time()
            self._hists[key].observe(value, exemplar=exemplar,
                                     exemplar_ts=exemplar_ts)

    def observe_keyed(self, key: Tuple[str, Tuple], value: float,
                      buckets: Optional[Tuple] = None,
                      exemplar: Optional[str] = None,
                      exemplar_ts: Optional[float] = None):
        """``observe`` with a caller-precomputed ``(name, labels_key)``
        pair — the per-heartbeat hot path (observe_train_step) caches
        the key per series instead of rebuilding and re-sorting the
        label dict on every training step."""
        with self._lock:
            h = self._hists.get(key)
            if h is None:
                h = self._hists[key] = Histogram(buckets or _BUCKETS)
            if exemplar is not None and exemplar_ts is None:
                exemplar_ts = time.time()
            h.observe(value, exemplar=exemplar, exemplar_ts=exemplar_ts)

    def observe_keyed_many(self, entries, buckets: Optional[Tuple] = None,
                           exemplar_ts: Optional[float] = None):
        """Batch of ``observe_keyed`` calls under one lock acquisition:
        ``entries`` is ``[(key, value, exemplar)]``.  All exemplars share
        ``exemplar_ts`` (one fleet step, one timestamp)."""
        with self._lock:
            for key, value, exemplar in entries:
                h = self._hists.get(key)
                if h is None:
                    h = self._hists[key] = Histogram(buckets or _BUCKETS)
                if exemplar is not None and exemplar_ts is None:
                    exemplar_ts = time.time()
                h.observe(value, exemplar=exemplar, exemplar_ts=exemplar_ts)

    def histogram_snapshot(self, name: str,
                           labels: Optional[Dict[str, str]] = None
                           ) -> Optional[Dict[str, object]]:
        """Point-in-time copy of one histogram series (buckets, per-bucket
        counts, count, sum) — the read seam the SLO autoscaler's windowed
        percentile math consumes (controlplane/slo.py delta-p99s two
        snapshots)."""
        with self._lock:
            h = self._hists.get((name, self._labels_key(labels)))
            if h is None:
                return None
            return {"buckets": list(h.buckets), "counts": list(h.counts),
                    "n": h.n, "sum": h.total,
                    "exemplars": list(h.exemplars)}

    def family_snapshot(self, name: str
                        ) -> List[Tuple[Dict[str, str], float]]:
        """All (labels, value) series of a counter or gauge family — the
        read seam the SLO alert engine's availability/goodput specs sum
        over (obs/alerts.py)."""
        out: List[Tuple[Dict[str, str], float]] = []
        with self._lock:
            for d in (self._counters, self._gauges):
                for (n, labels), v in d.items():
                    if n == name:
                        out.append((dict(labels), v))
        return out

    def histogram_names(self, prefix: str = "") -> List[str]:
        """Distinct histogram family names (optionally prefix-filtered)."""
        with self._lock:
            seen: Dict[str, None] = {}
            for (n, _labels) in self._hists:
                if n.startswith(prefix):
                    seen.setdefault(n, None)
        return list(seen)

    def drop_labeled(self, label_key: str, label_value: str):
        """Remove every series carrying label=value (CR deletion cleanup)."""
        with self._lock:
            for d in (self._counters, self._gauges, self._hists):
                for key in [k for k in d
                            if (label_key, label_value) in k[1]]:
                    del d[key]

    # -- exposition --------------------------------------------------------

    @staticmethod
    def _escape_label_value(value) -> str:
        """Prometheus text-format label-value escaping: backslash,
        double-quote and newline (in that order — escaping the escape
        character first, or a value containing ``\\"`` corrupts the
        exposition and the whole scrape fails to parse)."""
        return (str(value).replace("\\", "\\\\").replace('"', '\\"')
                .replace("\n", "\\n"))

    @staticmethod
    def _escape_help(text: str) -> str:
        """HELP lines escape backslash and newline (quotes are legal)."""
        return str(text).replace("\\", "\\\\").replace("\n", "\\n")

    @staticmethod
    def _fmt_labels(label_items: Tuple, extra: str = "") -> str:
        parts = [f'{k}="{MetricsRegistry._escape_label_value(v)}"'
                 for k, v in label_items]
        if extra:
            parts.append(extra)
        return "{" + ",".join(parts) + "}" if parts else ""

    def render(self) -> str:
        lines: List[str] = []
        with self._lock:
            seen = set()

            def header(name, mtype):
                if name not in seen:
                    seen.add(name)
                    if name in self._help:
                        lines.append(
                            f"# HELP {name} "
                            f"{self._escape_help(self._help[name])}")
                    lines.append(f"# TYPE {name} {mtype}")

            for (name, labels), v in sorted(self._counters.items()):
                header(name, "counter")
                lines.append(f"{name}{self._fmt_labels(labels)} {v}")
            for (name, labels), v in sorted(self._gauges.items()):
                header(name, "gauge")
                lines.append(f"{name}{self._fmt_labels(labels)} {v}")
            for (name, labels), h in sorted(self._hists.items()):
                header(name, "histogram")
                cum = 0
                for i, (b, c) in enumerate(zip(h.buckets, h.counts)):
                    cum += c
                    le = "+Inf" if b == float("inf") else str(b)
                    le_label = 'le="%s"' % le
                    line = (f"{name}_bucket"
                            f"{self._fmt_labels(labels, le_label)} {cum}")
                    ex = h.exemplars[i]
                    if ex is not None:
                        # OpenMetrics exemplar: attached to the le-line of
                        # the bucket the observation landed in.
                        tid, val, ts = ex
                        line += (' # {trace_id="%s"} %s %s'
                                 % (self._escape_label_value(tid), val, ts))
                    lines.append(line)
                lines.append(f"{name}_sum{self._fmt_labels(labels)} {h.total}")
                lines.append(f"{name}_count{self._fmt_labels(labels)} {h.n}")
        return "\n".join(lines) + "\n"


class ControlPlaneMetrics:
    """The typed facade controllers consume (matches the ``metrics``
    parameter of the controllers)."""

    def __init__(self, registry: Optional[MetricsRegistry] = None):
        self.registry = registry or MetricsRegistry()
        # (job, host) -> precomputed registry key for the per-heartbeat
        # step-duration histogram (the one metric on the hot path).
        self._train_keys: Dict[Tuple[str, str], Tuple] = {}
        r = self.registry
        r.describe("tpu_cluster_provisioned_duration_seconds",
                   "Seconds from TpuCluster creation to all slices ready")
        r.describe("tpu_job_execution_duration_seconds",
                   "Seconds from job start to terminal state")
        r.describe("tpu_cluster_state", "TpuCluster state gauge (1 = in state)")
        r.describe("tpu_reconcile_total", "Reconcile invocations per kind")
        r.describe("tpu_reconcile_duration_seconds", "Reconcile latency")
        r.describe("tpu_reconcile_conflicts_total",
                   "Reconciles lost to an optimistic-concurrency race "
                   "(Conflict) per kind; routine under contention, a spike "
                   "means a foreign writer is fighting a controller")
        r.describe("tpu_reconcile_errors_total",
                   "Reconciles that raised and were requeued with backoff, "
                   "per kind")
        r.describe("tpu_slice_ready_duration_seconds",
                   "Seconds from slice creation to all hosts running "
                   "(north-star metric)")
        r.describe("tpu_goodput_seconds_total",
                   "Wall-clock seconds attributed to each goodput phase "
                   "per CR kind (queued/provisioning/bootstrap/productive/"
                   "interrupted/recovery/teardown); fed by closed ledger "
                   "intervals, so phases sum to attributed lifetime")
        r.describe("tpu_goodput_ratio",
                   "Per-object goodput ratio: productive seconds over "
                   "total attributed lifetime (0..1)")
        r.describe("tpu_autoscaler_decisions_total",
                   "Autoscaler scale decisions per kind and direction "
                   "(up/down); the last-N decision audit ring at "
                   "/debug/autoscaler carries the input signals")
        r.describe("tpu_workqueue_depth",
                   "Keys waiting in the reconcile work queue (excludes "
                   "in-flight and timed requeues); sustained growth means "
                   "the workers can't keep up")
        r.describe("tpu_workqueue_latency_seconds",
                   "Seconds a key waited in the work queue from first "
                   "enqueue to worker pickup (dedup keeps the earliest "
                   "cause; includes promoted requeue backoff)")
        r.describe("tpu_watch_backlog_evictions_total",
                   "Watch-backlog events evicted past the resumable "
                   "window (--watch-backlog-max); a nonzero rate means "
                   "resuming informers will hit ExpiredError and pay a "
                   "full relist instead of an O(delta) replay")
        r.describe("tpu_preemption_notices_total",
                   "Advance preemption notices first observed on a live "
                   "slice, per cluster and group; each starts the "
                   "warned-recovery clock")
        r.describe("tpu_preemption_warned_recovery_seconds",
                   "Seconds from first sight of a preemption notice to "
                   "the group back at full readiness with the noticed "
                   "slice retired; the warned-vs-unwarned recovery gap "
                   "is the advance-notice dividend chaos_bench gates on")
        r.describe("tpu_warmpool_claims_total",
                   "Warm-slice claim attempts by outcome reason: "
                   "preemption / scale-up (adopted) or miss (no ready "
                   "warm slice; cold build instead)")
        r.describe("tpu_train_step_duration_seconds",
                   "Per-host training step wall time from coordinator "
                   "heartbeats (obs/steps.py); exemplars link tail "
                   "buckets to the offending heartbeat event id")
        r.describe("tpu_train_step_skew_ratio",
                   "Host windowed-median step time over the fleet "
                   "median (1.0 = lockstep); sustained > the straggler "
                   "ratio flags the host")
        r.describe("tpu_train_mfu",
                   "Model-FLOPs-utilization per job, estimated by the "
                   "step tracker from heartbeat tokens/s and the "
                   "model config (6*N*tok_s / devices / peak)")
        r.describe("tpu_train_stragglers_total",
                   "Straggler verdicts flagged per job (host exceeded "
                   "the fleet median by the configured ratio for K "
                   "consecutive steps)")
        r.describe("tpu_gang_admission_total",
                   "Gang admission verdicts (admitted/denied) evaluated "
                   "by the batch scheduler; denials are the evidence "
                   "behind the controllers' hold-off requeues")
        r.describe("tpu_quota_admissions_total",
                   "QuotaManager decisions per queue and verdict "
                   "(admitted/denied/resized/evicted); level-triggered, "
                   "so a pending gang re-counts a denial every requeue")
        r.describe("tpu_quota_chips_used",
                   "Chips currently claimed per tenant queue (evicting "
                   "claims still count until drained — conservation is "
                   "about capacity held)")
        r.describe("tpu_quota_chips_guaranteed",
                   "Configured guaranteed chip budget per tenant queue")
        r.describe("tpu_quota_chips_ceiling",
                   "Configured chip ceiling per tenant queue (pool total "
                   "when the queue sets none)")
        r.describe("tpu_quota_reclaim_evictions_total",
                   "Borrower gangs warned for reclaim per queue (each "
                   "gets the preemption-notice window to shrink or "
                   "checkpoint before teardown)")
        r.describe("tpu_quota_starvation_escalations_total",
                   "Pending gangs escalated past the starvation bound to "
                   "the front of their queue with a borrowed-capacity "
                   "override")
        r.describe("tpu_quota_pending_gangs",
                   "Gangs waiting for quota admission per queue")

    def observe_provisioned(self, cluster: str, seconds: float):
        self.registry.observe("tpu_cluster_provisioned_duration_seconds",
                              seconds, {"cluster": cluster})

    def observe_job_duration(self, job: str, result: str, seconds: float):
        self.registry.observe("tpu_job_execution_duration_seconds", seconds,
                              {"job": job, "result": result or "unknown"})

    def observe_slice_ready(self, cluster: str, group: str, seconds: float):
        self.registry.observe("tpu_slice_ready_duration_seconds", seconds,
                              {"cluster": cluster, "group": group})

    def set_cluster_state(self, cluster: str, state: str):
        for s in ("ready", "suspended", "failed", ""):
            self.registry.set_gauge(
                "tpu_cluster_state", 1.0 if s == state else 0.0,
                {"cluster": cluster, "state": s or "provisioning"})

    def goodput_seconds(self, kind: str, phase: str, seconds: float):
        self.registry.inc("tpu_goodput_seconds_total",
                          {"kind": kind, "phase": phase}, value=seconds)

    def set_goodput_ratio(self, kind: str, namespace: str, name: str,
                          ratio: float):
        self.registry.set_gauge("tpu_goodput_ratio", ratio,
                                {"kind": kind, "namespace": namespace,
                                 "name": name})

    def autoscaler_decision(self, kind: str, direction: str):
        self.registry.inc("tpu_autoscaler_decisions_total",
                          {"kind": kind, "direction": direction})

    def reconcile(self, kind: str, seconds: float):
        self.registry.inc("tpu_reconcile_total", {"kind": kind})
        self.registry.observe("tpu_reconcile_duration_seconds", seconds,
                              {"kind": kind})

    def workqueue_depth(self, queue: str, depth: int):
        self.registry.set_gauge("tpu_workqueue_depth", float(depth),
                                {"queue": queue})

    def workqueue_latency(self, queue: str, seconds: float):
        self.registry.observe("tpu_workqueue_latency_seconds", seconds,
                              {"queue": queue}, buckets=_FAST_BUCKETS)

    def watch_backlog_evictions(self, n: int = 1):
        self.registry.inc("tpu_watch_backlog_evictions_total", value=n)

    def preemption_notice(self, cluster: str, group: str):
        self.registry.inc("tpu_preemption_notices_total",
                          {"cluster": cluster, "group": group})

    def observe_warned_recovery(self, cluster: str, group: str,
                                seconds: float):
        self.registry.observe("tpu_preemption_warned_recovery_seconds",
                              seconds, {"cluster": cluster, "group": group})

    def warmpool_claim(self, reason: str):
        self.registry.inc("tpu_warmpool_claims_total", {"reason": reason})

    def gang_admission(self, verdict: str):
        self.registry.inc("tpu_gang_admission_total", {"verdict": verdict})

    def quota_admission(self, queue: str, verdict: str):
        self.registry.inc("tpu_quota_admissions_total",
                          {"queue": queue, "verdict": verdict})

    def quota_reclaim_eviction(self, queue: str):
        self.registry.inc("tpu_quota_reclaim_evictions_total",
                          {"queue": queue})

    def quota_starvation_escalation(self, queue: str):
        self.registry.inc("tpu_quota_starvation_escalations_total",
                          {"queue": queue})

    def quota_usage(self, tenant: str, queue: str, *, used: int,
                    guaranteed: int, ceiling: int):
        labels = {"tenant": tenant, "queue": queue}
        self.registry.set_gauge("tpu_quota_chips_used", float(used), labels)
        self.registry.set_gauge("tpu_quota_chips_guaranteed",
                                float(guaranteed), labels)
        self.registry.set_gauge("tpu_quota_chips_ceiling", float(ceiling),
                                labels)

    def quota_pending(self, queue: str, count: int):
        self.registry.set_gauge("tpu_quota_pending_gangs", float(count),
                                {"queue": queue})

    def observe_train_step(self, job: str, host: str, seconds: float,
                           exemplar: Optional[str] = None,
                           exemplar_ts: Optional[float] = None):
        key = self._train_keys.get((job, host))
        if key is None:
            if len(self._train_keys) > 4096:    # bounded memo
                self._train_keys.clear()
            key = self._train_keys[(job, host)] = (
                "tpu_train_step_duration_seconds",
                (("host", host), ("job", job)))   # sorted label order
        self.registry.observe_keyed(key, seconds,
                                    buckets=TRAIN_STEP_BUCKETS,
                                    exemplar=exemplar,
                                    exemplar_ts=exemplar_ts)

    def observe_train_steps(self, job: str, items, ts: Optional[float] = None):
        """Batched ``observe_train_step`` for one synchronous fleet step:
        ``items`` is ``[(host, seconds, exemplar)]`` sharing one timestamp.
        One registry lock for the whole fleet instead of one per host —
        the coordinator/sim hot path when every host beats at once."""
        entries = []
        for host, seconds, exemplar in items:
            key = self._train_keys.get((job, host))
            if key is None:
                if len(self._train_keys) > 4096:    # bounded memo
                    self._train_keys.clear()
                key = self._train_keys[(job, host)] = (
                    "tpu_train_step_duration_seconds",
                    (("host", host), ("job", job)))   # sorted label order
            entries.append((key, seconds, exemplar))
        self.registry.observe_keyed_many(entries, buckets=TRAIN_STEP_BUCKETS,
                                         exemplar_ts=ts)

    def set_train_skew(self, job: str, kind: str, namespace: str,
                       name: str, host: str, ratio: float):
        # kind/namespace/name mirror the job's goodput key so the alert
        # engine can deep-link the firing series to /debug/flight and
        # /debug/goodput (obs/alerts._links).
        self.registry.set_gauge("tpu_train_step_skew_ratio", ratio,
                                {"job": job, "kind": kind,
                                 "namespace": namespace, "name": name,
                                 "host": host})

    def set_train_mfu(self, job: str, kind: str, namespace: str,
                      name: str, value: float):
        self.registry.set_gauge("tpu_train_mfu", value,
                                {"job": job, "kind": kind,
                                 "namespace": namespace, "name": name})

    def train_straggler(self, job: str):
        self.registry.inc("tpu_train_stragglers_total", {"job": job})

    def reconcile_conflict(self, kind: str):
        self.registry.inc("tpu_reconcile_conflicts_total", {"kind": kind})

    def reconcile_error(self, kind: str):
        self.registry.inc("tpu_reconcile_errors_total", {"kind": kind})

    def forget_cluster(self, cluster: str):
        self.registry.drop_labeled("cluster", cluster)

    def render(self) -> str:
        return self.registry.render()
