"""Minimal 5-field cron parser + next-run math (no external deps).

Vixie-cron semantics matching robfig/cron (what the reference uses at
raycronjob_controller.go:93-135): ``*``, lists, ranges, steps, weekday 0-7
(both 0 and 7 are Sunday), and the day-of-month/day-of-week OR rule — when
both fields are restricted, a time matches if *either* matches.
"""

from __future__ import annotations

import dataclasses
import time
from typing import FrozenSet, List, Optional


class CronError(ValueError):
    pass


_FIELDS = [
    ("minute", 0, 59),
    ("hour", 0, 23),
    ("day", 1, 31),
    ("month", 1, 12),
    ("weekday", 0, 7),   # 0 and 7 both mean Sunday; normalized to 0 post-parse
]


@dataclasses.dataclass(frozen=True)
class CronSchedule:
    minute: FrozenSet[int]
    hour: FrozenSet[int]
    day: FrozenSet[int]
    month: FrozenSet[int]
    weekday: FrozenSet[int]
    day_restricted: bool      # day field was not "*"
    weekday_restricted: bool  # weekday field was not "*"


def _parse_field(expr: str, name: str, lo: int, hi: int,
                 step_hi: Optional[int] = None) -> FrozenSet[int]:
    """``step_hi``: implicit upper bound for 'N/step' expansion (robfig uses
    6 for day-of-week even though literal 7 is accepted as Sunday)."""
    vals = set()
    for part in expr.split(","):
        if part == "":
            raise CronError(f"{name}: empty list element in {expr!r}")
        step = 1
        if "/" in part:
            part, step_s = part.split("/", 1)
            try:
                step = int(step_s)
            except ValueError:
                raise CronError(f"{name}: bad step {step_s!r}") from None
            if step < 1:
                raise CronError(f"{name}: step must be >= 1")
        if part == "*":
            start, end = lo, hi if step == 1 else (step_hi or hi)
        elif "-" in part:
            a, b = part.split("-", 1)
            try:
                start, end = int(a), int(b)
            except ValueError:
                raise CronError(f"{name}: bad range {part!r}") from None
        else:
            try:
                start = end = int(part)
            except ValueError:
                raise CronError(f"{name}: bad value {part!r}") from None
            if step > 1:
                # Vixie/robfig: 'N/step' means the range N..max stepped.
                end = step_hi if step_hi is not None else hi
        if not (lo <= start <= hi and lo <= end <= hi and start <= end):
            raise CronError(f"{name}: {part!r} out of range [{lo},{hi}]")
        vals.update(range(start, end + 1, step))
    if not vals:
        raise CronError(f"{name}: empty field")
    return frozenset(vals)


def parse_cron(schedule: str) -> CronSchedule:
    parts = schedule.split()
    if len(parts) != 5:
        raise CronError(f"schedule must have 5 fields, got {len(parts)}: {schedule!r}")
    sets = [
        _parse_field(p, name, lo, hi, step_hi=6 if name == "weekday" else None)
        for p, (name, lo, hi) in zip(parts, _FIELDS)
    ]
    # Normalize weekday 7 -> 0 (both mean Sunday).
    weekday = frozenset(v % 7 for v in sets[4])
    # Vixie star-bit: a field beginning with '*' (incl. '*/N') keeps the
    # star bit, so the DOM/DOW OR rule does NOT apply to it (robfig compat).
    return CronSchedule(
        minute=sets[0], hour=sets[1], day=sets[2], month=sets[3],
        weekday=weekday,
        day_restricted=not parts[2].startswith("*"),
        weekday_restricted=not parts[4].startswith("*"),
    )


def matches(sched: CronSchedule, t: float) -> bool:
    st = time.localtime(t)
    if st.tm_min not in sched.minute or st.tm_hour not in sched.hour \
            or st.tm_mon not in sched.month:
        return False
    day_ok = st.tm_mday in sched.day
    # tm_wday: Monday=0; cron: Sunday=0.
    wday_ok = (st.tm_wday + 1) % 7 in sched.weekday
    # Vixie OR rule: both restricted -> either may match.
    if sched.day_restricted and sched.weekday_restricted:
        return day_ok or wday_ok
    return day_ok and wday_ok


def next_run_after(schedule: str, after: float, horizon_days: int = 366) -> Optional[float]:
    """First scheduled time strictly after ``after`` (minute resolution)."""
    sched = schedule if isinstance(schedule, CronSchedule) else parse_cron(schedule)
    t = (int(after) // 60 + 1) * 60
    end = after + horizon_days * 86400
    while t <= end:
        if matches(sched, t):
            return float(t)
        t += 60
    return None


def missed_runs(
    schedule: str,
    last: float,
    now: float,
    limit: int = 100,
    horizon_seconds: float = 86400.0,
) -> List[float]:
    """Scheduled times in (last, now] — the catch-up set
    (ref raycronjob_controller.go LastScheduleTime comparison).

    Single parse + single forward scan, capped at ``limit`` results and
    bounded below by ``now - horizon_seconds`` so an epoch-zero
    lastScheduleTime (a brand-new CR) cannot trigger a multi-decade scan —
    the CronJob-controller startingDeadlineSeconds pattern; pass that value
    here when the spec sets it.
    """
    sched = parse_cron(schedule)
    last = max(last, now - horizon_seconds)
    out: List[float] = []
    t = (int(last) // 60 + 1) * 60
    while t <= now and len(out) < limit:
        if matches(sched, t):
            out.append(float(t))
        t += 60
    return out
