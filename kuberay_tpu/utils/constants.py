"""Well-known labels, env vars, ports, and annotations.

TPU-native equivalent of the reference's utils/constant.go:38-48 (labels,
incl. the multi-host replica/host-index trio), :112-120 (ports), :136-182
(env names).  Names use the ``tpu.dev/`` prefix instead of ``ray.io/``; the
env-var surface is the union of what the reference's pod builder sets and
what GKE's external TPU webhook injects today (SURVEY.md §5.7) — injection
is native here.
"""

# --- API group ---------------------------------------------------------------
GROUP = "tpu.dev"
VERSION = "v1"
API_VERSION = f"{GROUP}/{VERSION}"

KIND_CLUSTER = "TpuCluster"
KIND_JOB = "TpuJob"
KIND_SERVICE = "TpuService"
KIND_CRONJOB = "TpuCronJob"
KIND_QUOTA_POOL = "QuotaPool"

# --- Labels (ref constant.go:38-48) ------------------------------------------
LABEL_CLUSTER = "tpu.dev/cluster"                 # ray.io/cluster
LABEL_NODE_TYPE = "tpu.dev/node-type"             # ray.io/node-type (head|worker)
LABEL_GROUP = "tpu.dev/group"                     # ray.io/group
LABEL_IDENTIFIER = "tpu.dev/identifier"           # <cluster>-<type>
LABEL_CREATED_BY = "tpu.dev/created-by"           # app.kubernetes.io/created-by
LABEL_ORIGINATED_FROM_CR_NAME = "tpu.dev/originated-from-cr-name"
LABEL_ORIGINATED_FROM_CRD = "tpu.dev/originated-from-crd"
# Multi-host slice identity trio (ref constant.go:46-48, pod.go:493-500):
LABEL_SLICE_NAME = "tpu.dev/slice-name"           # worker-group-replica-name
LABEL_SLICE_INDEX = "tpu.dev/slice-index"         # replica-index (int)
LABEL_HOST_INDEX = "tpu.dev/host-index"           # replica-host-index (int)
# Serving (ref rayservice_controller.go:2065 serve-label):
LABEL_SERVE = "tpu.dev/serve"                     # "true"|"false" on head pods

NODE_TYPE_HEAD = "head"
NODE_TYPE_WORKER = "worker"
CREATED_BY_OPERATOR = "kuberay-tpu-operator"

# SidecarMode submitter container injected into the head pod (ref
# SubmitterContainerName, common/job.go:95-158 BuildSidecarContainer role).
SUBMITTER_CONTAINER_NAME = "tpu-job-submitter"

# --- Annotations (ref constant.go:64-69) -------------------------------------
ANNOTATION_OVERWRITE_CONTAINER_CMD = "tpu.dev/overwrite-container-cmd"
ANNOTATION_FT_ENABLED = "tpu.dev/ft-enabled"
ANNOTATION_FT_DELETION_TIMEOUT = "tpu.dev/ft-deletion-timeout"
# Cleanup-Job deletion-timeout fallback clock for store backends that omit
# creationTimestamp (see cluster_controller._reconcile_deletion):
ANNOTATION_CLEANUP_OBSERVED_AT = "tpu.dev/cleanup-observed-at"
# Preemption lifecycle (docs/preemption.md): the advance warning a
# maintenance event / spot reclaim delivers (value = kill deadline,
# seconds), the drain acknowledgment the controller stamps once the
# checkpoint request fired, and the cross-slice DCN partition window end.
ANNOTATION_PREEMPTION_NOTICE = "tpu.dev/preemption-notice"
ANNOTATION_DRAINED_AT = "tpu.dev/drained-at"
ANNOTATION_DCN_PARTITION_UNTIL = "tpu.dev/dcn-partition-until"

# --- GKE TPU node selectors (ref kubectl-plugin/pkg/util/constant.go:13-19) --
NODE_SELECTOR_GKE_ACCELERATOR = "cloud.google.com/gke-tpu-accelerator"
NODE_SELECTOR_GKE_TOPOLOGY = "cloud.google.com/gke-tpu-topology"
RESOURCE_TPU = "google.com/tpu"
RESOURCE_NVIDIA_GPU = "nvidia.com/gpu"

# --- TPU runtime env (injected natively by the pod builder) ------------------
# Identity within the slice; consumed by libtpu/XLA to wire the ICI mesh.
ENV_TPU_WORKER_ID = "TPU_WORKER_ID"
ENV_TPU_WORKER_HOSTNAMES = "TPU_WORKER_HOSTNAMES"
ENV_TPU_TOPOLOGY = "TPU_TOPOLOGY"
ENV_TPU_CHIPS_PER_HOST_BOUNDS = "TPU_CHIPS_PER_HOST_BOUNDS"
ENV_TPU_ACCELERATOR_TYPE = "TPU_ACCELERATOR_TYPE"
# Multi-slice (DCN) coordination — JAX megascale (SURVEY.md §5.8):
ENV_MEGASCALE_COORDINATOR_ADDRESS = "MEGASCALE_COORDINATOR_ADDRESS"
ENV_MEGASCALE_NUM_SLICES = "MEGASCALE_NUM_SLICES"
ENV_MEGASCALE_SLICE_ID = "MEGASCALE_SLICE_ID"
# JAX distributed init (coordinator = head service, analogous RAY_ADDRESS):
ENV_COORDINATOR_ADDRESS = "TPU_COORDINATOR_ADDRESS"   # ~ RAY_ADDRESS
ENV_FQ_HEAD_IP = "FQ_TPU_HEAD_IP"                     # ~ FQ_RAY_IP
ENV_CLUSTER_NAME = "TPU_CLUSTER_NAME"                 # ~ RAY_CLUSTER_NAME
ENV_NUM_PROCESSES = "TPU_NUM_PROCESSES"
ENV_PROCESS_ID = "TPU_PROCESS_ID"

# --- Ports (ref constant.go:112-120) -----------------------------------------
PORT_COORDINATOR = 8476         # jax.distributed coordinator (~GCS 6379)
PORT_DASHBOARD = 8265           # runtime dashboard / job API (same as Ray's)
PORT_METRICS = 8080             # Prometheus metrics on every node
PORT_SERVE = 8000               # inference HTTP
PORT_GROUP_HEALTH = 8090        # serve-group heartbeat listener (host 0)

# --- Disaggregated serving tiers (TpuServiceSpec.serveTier) ------------------
SERVE_TIER_MIXED = "mixed"      # prefill+decode colocated (default)
SERVE_TIER_PREFILL = "prefill"  # prompt processing only (hop 1)
SERVE_TIER_DECODE = "decode"    # token generation off transferred KV (hop 2)
SERVE_TIERS = (SERVE_TIER_MIXED, SERVE_TIER_PREFILL, SERVE_TIER_DECODE)

# Kube PATCH MIME types, patch_type -> Content-Type (the one table the
# clients send from and the apiserver inverts; apply is +yaml on the
# wire, JSON being a YAML subset).
PATCH_CONTENT_TYPES = {
    "merge": "application/merge-patch+json",
    "strategic": "application/strategic-merge-patch+json",
    "json": "application/json-patch+json",
    "apply": "application/apply-patch+yaml",
}
PORT_MXLA = 8081                # MXLA coordinator (multi-slice samples)
PORT_CLIENT = 10001

DEFAULT_COORDINATOR_PORT_NAME = "coordinator"
DEFAULT_DASHBOARD_PORT_NAME = "dashboard"
DEFAULT_METRICS_PORT_NAME = "metrics"
DEFAULT_SERVE_PORT_NAME = "serve"

# --- Head service suffixes ---------------------------------------------------
HEAD_SVC_SUFFIX = "head-svc"
HEADLESS_SVC_SUFFIX = "headless"
SERVE_SVC_SUFFIX = "serve-svc"

# --- Finalizers --------------------------------------------------------------
FINALIZER_GCS_FT = f"{GROUP}/gcs-ft-finalizer"
FINALIZER_JOB = f"{GROUP}/tpujob-finalizer"
FINALIZER_SERVICE = f"{GROUP}/tpuservice-finalizer"

# --- Event reasons (ref constant.go EventType section) -----------------------
EVENT_CREATED_POD = "CreatedPod"
EVENT_DELETED_POD = "DeletedPod"
EVENT_CREATED_SLICE = "CreatedSlice"
EVENT_DELETED_SLICE = "DeletedSlice"
EVENT_CREATED_SERVICE = "CreatedService"
EVENT_FAILED_TO_CREATE = "FailedToCreate"
EVENT_UNHEALTHY_SLICE = "UnhealthySlice"
EVENT_INVALID_SPEC = "InvalidSpec"
EVENT_PREEMPTION_NOTICE = "PreemptionNotice"
EVENT_DRAINED_SLICE = "DrainedSlice"
EVENT_ADOPTED_WARM_SLICE = "AdoptedWarmSlice"
EVENT_QUOTA_HELD = "QuotaHeld"
EVENT_QUOTA_ADMITTED = "QuotaAdmitted"
EVENT_QUOTA_EVICTED = "QuotaEvicted"

# --- Behavior knobs (ref §5.6 env escape hatches) ----------------------------
ENV_ENABLE_RANDOM_POD_DELETE = "ENABLE_RANDOM_POD_DELETE"
ENV_DEFAULT_REQUEUE_SECONDS = "TPUCLUSTER_DEFAULT_REQUEUE_SECONDS"
DEFAULT_REQUEUE_SECONDS = 300
DEFAULT_RECONCILE_REQUEUE_SECONDS = 2.0


# --- REST plurals (shared by apiserver, REST store, clients) -----------------
CRD_PLURALS = {
    KIND_CLUSTER: "tpuclusters",
    KIND_JOB: "tpujobs",
    KIND_SERVICE: "tpuservices",
    KIND_CRONJOB: "tpucronjobs",
    "WarmSlicePool": "warmslicepools",
    "TrafficRoute": "trafficroutes",
    "ComputeTemplate": "computetemplates",
    KIND_QUOTA_POOL: "quotapools",
}
CORE_PLURALS = {
    "Pod": "pods", "Service": "services", "Event": "events",
    "PodGroup": "podgroups", "NetworkPolicy": "networkpolicies",
    "Job": "jobs", "Secret": "secrets", "Ingress": "ingresses",
    "Route": "routes",            # OpenShift head Route (openshift.go)
}
