"""Timeline export: cluster/job lifecycle as Chrome-trace JSON.

The reference's historyserver preserves Ray timeline/profile events for
post-mortem analysis (historyserver/pkg/eventserver/eventserver.go:838
handleTaskProfileEvent).  The TPU-native counterparts are two-level:

- ORCHESTRATION timeline (this module): K8s Events + CR
  ``stateTransitionTimes`` + job start/end times rendered as a
  chrome://tracing / Perfetto-loadable JSON document, built from the
  live store or from an archived history doc — "what did the control
  plane do and when" for a (possibly deleted) cluster.
- DEVICE profiles: ``jax.profiler`` traces captured on demand via the
  coordinator's /api/profile endpoints (runtime/coordinator_server.py)
  and archived by the history log collector like any other node file.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

_PHASE_COMPLETE = "X"
_PHASE_INSTANT = "i"


def _us(t: float) -> int:
    return int(t * 1e6)


def _event_rows(events: List[Dict[str, Any]], name: str,
                pid: str) -> List[Dict[str, Any]]:
    out = []
    for e in events:
        # Live Event objects carry involvedObject; history archives store
        # events already filtered to this object with involvedObject
        # stripped (HistoryCollector._archive) — treat absence as a match.
        if "involvedObject" in e and \
                e["involvedObject"].get("name") != name:
            continue
        ts = e.get("eventTime") or 0
        out.append({
            "name": f"{e.get('reason', 'Event')}",
            "cat": e.get("type", "Normal"),
            "ph": _PHASE_INSTANT, "s": "p",
            "ts": _us(ts), "pid": pid, "tid": "events",
            "args": {"message": e.get("message", "")},
        })
    return out


def task_event_rows(task_events: List[Dict[str, Any]],
                    pid: str) -> List[Dict[str, Any]]:
    """Structured task/step/profile events (the coordinator's /api/events
    stream, ref eventserver.go:838) as trace rows: events with ``dur``
    render as spans, others as instants; one lane per job id."""
    out = []
    for e in task_events:
        ts = e.get("ts") or 0
        tid = e.get("job_id") or e.get("type", "task")
        row = {
            "name": e.get("name", e.get("type", "task")),
            "cat": e.get("type", "task"),
            "ts": _us(ts), "pid": pid, "tid": f"tasks/{tid}",
            "args": e.get("args", {}),
        }
        dur = e.get("dur")
        if dur:
            row.update({"ph": _PHASE_COMPLETE, "dur": max(_us(dur), 1)})
        else:
            row.update({"ph": _PHASE_INSTANT, "s": "t"})
        out.append(row)
    return out


def cluster_timeline(cluster: Dict[str, Any],
                     events: Optional[List[Dict[str, Any]]] = None,
                     jobs: Optional[List[Dict[str, Any]]] = None,
                     task_events: Optional[List[Dict[str, Any]]] = None
                     ) -> Dict[str, Any]:
    """Chrome-trace document for one TpuCluster (live CR dict or an
    archived history doc — both carry metadata/status/events)."""
    md = cluster.get("metadata", {})
    st = cluster.get("status", {})
    name = md.get("name", "")
    pid = f"TpuCluster/{name}"
    trace: List[Dict[str, Any]] = []

    created = md.get("creationTimestamp") or 0
    transitions = sorted(
        ((t, state) for state, t in
         (st.get("stateTransitionTimes") or {}).items()),
        key=lambda x: x[0])
    # State spans: creation -> t1 -> t2 ... (last span open-ended: render
    # as an instant + zero-length span at the transition).
    prev_t, prev_state = created, "provisioning"
    for t, state in transitions:
        trace.append({
            "name": prev_state, "cat": "state", "ph": _PHASE_COMPLETE,
            "ts": _us(prev_t), "dur": max(_us(t) - _us(prev_t), 1),
            "pid": pid, "tid": "state",
        })
        prev_t, prev_state = t, state
    end = md.get("deletionTimestamp") or cluster.get("archivedAt")
    trace.append({
        "name": prev_state, "cat": "state", "ph": _PHASE_COMPLETE,
        "ts": _us(prev_t),
        "dur": max(_us(end) - _us(prev_t), 1) if end else 1,
        "pid": pid, "tid": "state",
    })

    # Condition transitions as instants.
    for cond in st.get("conditions", []):
        t = cond.get("lastTransitionTime") or 0
        trace.append({
            "name": f"{cond.get('type')}={cond.get('status')}",
            "cat": "condition", "ph": _PHASE_INSTANT, "s": "t",
            "ts": _us(t), "pid": pid, "tid": "conditions",
            "args": {"reason": cond.get("reason", "")},
        })

    trace.extend(_event_rows(events or cluster.get("events") or [], name,
                             pid))

    for job in jobs or []:
        jst = job.get("status", {})
        t0 = jst.get("startTime") or 0
        t1 = jst.get("endTime") or 0
        if t0:
            trace.append({
                "name": job.get("metadata", {}).get("name", "job"),
                "cat": "job", "ph": _PHASE_COMPLETE,
                "ts": _us(t0),
                "dur": max(_us(t1) - _us(t0), 1) if t1 else 1,
                "pid": pid, "tid": "jobs",
                "args": {"deployment": jst.get("jobDeploymentStatus", ""),
                         "job": jst.get("jobStatus", "")},
            })

    trace.extend(task_event_rows(task_events or [], pid))

    return {"traceEvents": sorted(trace, key=lambda e: e["ts"]),
            "displayTimeUnit": "ms"}
