"""Shared utilities: constants, hashing, validation, metrics, feature gates."""
