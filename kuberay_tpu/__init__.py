"""kuberay_tpu — a TPU-native pod-slice orchestration framework.

A brand-new framework with the capabilities of ray-project/kuberay, re-designed
TPU-first: the atomic unit of scheduling, scaling, and repair is the multi-host
TPU *slice* (not the pod), worker identity/topology env injection
(``TPU_WORKER_ID`` / ``TPU_WORKER_HOSTNAMES``) is native (not webhook-delegated),
and the runtime path is JAX/XLA/pjit/Pallas rather than GPU/NCCL.

Layout (mirrors the reference's layer map, SURVEY.md §1):

- ``api/``          CRD-equivalent typed specs (TpuCluster/TpuJob/TpuService/...)
- ``builders/``     pure functions spec -> pod/service/job objects
- ``controlplane/`` object store + level-triggered reconcilers
- ``scheduler/``    gang-admission plugin framework
- ``parallel/``     device-mesh / sharding / ring-attention machinery
- ``models/``       flagship model families (Llama, Mixtral)
- ``ops/``          Pallas TPU kernels with portable fallbacks
- ``train/``        pjit train step, checkpointing, data
- ``serve/``        continuous-batching inference engine
- ``utils/``        constants, validation, hashing, metrics, feature gates
"""

__version__ = "0.1.0"
