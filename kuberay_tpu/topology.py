"""TPU topology math: the core primitive of the framework.

The reference treats the pod as the scheduling unit and bolts multi-host
atomicity on top (``NumOfHosts`` at raycluster_types.go:414-417, atomic group
reconcile at raycluster_controller.go:1246-1410).  Here the *slice* is
first-class: a worker group declares an accelerator generation + ICI topology
(e.g. ``v5p`` / ``4x4x4``) and everything else — hosts per slice, chips per
host, ring order, node selectors, mesh shapes — is derived, never free-form.

Public data:
- ``TpuGeneration``: per-generation hardware facts (chips/host, ICI dims).
- ``SliceTopology``: parsed+validated topology with derived host math.

No JAX imports here: this module is shared by the control plane (which must
run without an accelerator) and the runtime.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional, Sequence, Tuple


class TopologyError(ValueError):
    """Raised for invalid accelerator/topology combinations."""


# GKE node-pool catalog for 2D generations (v5e/v6e), dims sorted ascending.
_VALID_2D_TOPOLOGIES = {
    (1, 1), (1, 2), (2, 2), (2, 4), (4, 4), (4, 8), (8, 8), (8, 16), (16, 16),
}


@dataclasses.dataclass(frozen=True)
class TpuGeneration:
    """Hardware facts for one TPU generation.

    ``max_chips_per_host`` is the VM-attachment unit: a multi-host slice is
    carved into hosts of exactly ``chips_per_host(topology)`` chips, so host
    count is always ``total_chips / chips_per_host`` — the quantum of
    scheduling the control plane must treat atomically.
    """

    name: str
    ici_dims: int                 # 2 => XxY topologies, 3 => XxYxZ
    max_chips_per_host: int       # largest single-host attachment
    cores_per_chip: int
    hbm_gib_per_chip: float
    bf16_tflops_per_chip: float   # peak dense MXU throughput
    # GKE node-selector value for gke-tpu-accelerator (what the builders stamp)
    gke_accelerator: str
    # Multi-host node pools attach 4 chips per VM on every generation
    # (ct5lp-hightpu-4t / ct6e-standard-4t / v4+v5p boards); only single-host
    # pools offer larger attachments (reference sample
    # ray-job.tpu-v6e-16-multihost.yaml: numOfHosts: 4, google.com/tpu: "4").
    multihost_chips_per_host: int = 4

    def chips_per_host(self, total_chips: int) -> int:
        """Chips attached to each host VM for a slice of ``total_chips``."""
        if total_chips <= self.max_chips_per_host:
            return total_chips
        return self.multihost_chips_per_host


# Generation table. bf16 TFLOPs from public spec sheets; v5litepod (v5e) has
# no 3D ICI, v4/v5p do. v6e (Trillium) is 2D like v5e.
GENERATIONS = {
    "v4": TpuGeneration("v4", 3, 4, 2, 32.0, 275.0, "tpu-v4-podslice"),
    "v5e": TpuGeneration("v5e", 2, 8, 1, 16.0, 197.0, "tpu-v5-lite-podslice"),
    "v5p": TpuGeneration("v5p", 3, 4, 2, 95.0, 459.0, "tpu-v5p-slice"),
    "v6e": TpuGeneration("v6e", 2, 8, 1, 32.0, 918.0, "tpu-v6e-slice"),
}

_ALIASES = {
    "v5litepod": "v5e",
    "v5lite": "v5e",
    "v5 lite": "v5e",
    "trillium": "v6e",
}


def get_generation(name: str) -> TpuGeneration:
    key = name.strip().lower()
    key = _ALIASES.get(key, key)
    gen = GENERATIONS.get(key)
    if gen is None:
        raise TopologyError(
            f"unknown TPU generation {name!r}; known: {sorted(GENERATIONS)}"
        )
    return gen


def parse_topology(topology: str) -> Tuple[int, ...]:
    """Parse ``"4x4"`` / ``"2x2x2"`` into an int tuple."""
    parts = topology.lower().replace(" ", "").split("x")
    try:
        dims = tuple(int(p) for p in parts)
    except ValueError:
        raise TopologyError(f"malformed topology {topology!r}") from None
    if not dims or any(d < 1 for d in dims):
        raise TopologyError(f"malformed topology {topology!r}")
    return dims


@dataclasses.dataclass(frozen=True)
class SliceTopology:
    """A validated (generation, topology) pair with all derived facts.

    This is what a worker group resolves to.  The reference exposes raw
    ``NumOfHosts`` and leaves topology to node selectors in samples
    (config/samples/ray-job.tpu-v6e-16-multihost.yaml); here ``num_hosts``
    is *derived* so a spec can never declare an impossible slice.
    """

    generation: TpuGeneration
    dims: Tuple[int, ...]

    @classmethod
    def create(cls, accelerator: str, topology: str) -> "SliceTopology":
        gen = get_generation(accelerator)
        dims = parse_topology(topology)
        if len(dims) != gen.ici_dims:
            raise TopologyError(
                f"{gen.name} uses {gen.ici_dims}D ICI topologies, got "
                f"{topology!r} ({len(dims)}D)"
            )
        chips = math.prod(dims)
        if chips > gen.max_chips_per_host:
            # Multi-host: chip count must divide into whole host VMs.
            if chips % gen.multihost_chips_per_host != 0:
                raise TopologyError(
                    f"{gen.name}-{chips} is not divisible into "
                    f"{gen.multihost_chips_per_host}-chip hosts"
                )
        if gen.ici_dims == 2:
            # 2D generations (v5e/v6e) ship a fixed GKE topology catalog;
            # orderings are canonical (ascending) — '8x4' matches no pool.
            if dims not in _VALID_2D_TOPOLOGIES:
                raise TopologyError(
                    f"{gen.name} has no {topology!r} node pool; valid: "
                    + ", ".join("x".join(map(str, t)) for t in sorted(_VALID_2D_TOPOLOGIES))
                )
        else:
            # 3D generations (v4/v5p): cuboids whose dims are 1, 2, or a
            # multiple of 4 (the board edge), per the GKE topology tables.
            for d in dims:
                if d not in (1, 2) and d % 4 != 0:
                    raise TopologyError(
                        f"{gen.name} topology dims must be 1, 2, or a "
                        f"multiple of 4; got {topology!r}"
                    )
        return cls(gen, dims)

    @property
    def topology_str(self) -> str:
        return "x".join(str(d) for d in self.dims)

    @property
    def num_chips(self) -> int:
        return math.prod(self.dims)

    @property
    def chips_per_host(self) -> int:
        return self.generation.chips_per_host(self.num_chips)

    @property
    def num_hosts(self) -> int:
        return self.num_chips // self.chips_per_host

    @property
    def is_multi_host(self) -> bool:
        return self.num_hosts > 1

    @property
    def short_name(self) -> str:
        return f"{self.generation.name}-{self.num_chips}"

    @property
    def bf16_tflops(self) -> float:
        return self.num_chips * self.generation.bf16_tflops_per_chip

    @property
    def hbm_gib(self) -> float:
        return self.num_chips * self.generation.hbm_gib_per_chip

    def host_block_dims(self) -> Tuple[int, ...]:
        """Per-host chip block within the slice topology.

        Multi-host attachments are physically square-ish boards: ct5lp/ct6e
        4-chip VMs own a 2x2 block of the 2D torus; v4/v5p boards are
        2x2x1 of the 3D torus.  Derived from
        ``generation.multihost_chips_per_host`` (so host-count math and
        block geometry cannot drift apart) by greedily doubling the block
        along the axes with the most room — this also places the block
        correctly on degenerate topologies with size-1 axes (e.g. v5p
        1x4x8 -> block 1x2x2, host grid 1x2x4).
        """
        if not self.is_multi_host:
            return self.dims
        cph = self.generation.multihost_chips_per_host
        if cph & (cph - 1):  # non-power-of-two board: pack innermost axis
            block = [1] * (len(self.dims) - 1) + [cph]
            return tuple(block)
        # Real boards are 2x2(x1): place factor-2s on DISTINCT even axes
        # first (ascending index — v5p 4x4x8 -> 2x2x1, matching hardware),
        # then double existing block axes only for degenerate shapes where
        # fewer than log2(cph) axes are even (e.g. 1x1x8 -> 1x1x4).
        block = [1] * len(self.dims)
        rem = cph
        for i, d in enumerate(self.dims):
            if rem <= 1:
                break
            if block[i] == 1 and (d // block[i]) % 2 == 0:
                block[i] = 2
                rem //= 2
        while rem > 1:
            grew = False
            for i, d in enumerate(self.dims):
                if rem > 1 and (d // block[i]) % 2 == 0 and block[i] < d:
                    block[i] *= 2
                    rem //= 2
                    grew = True
            if not grew:
                return tuple(block)  # irregular; caller falls back
        return tuple(block)

    def host_grid_dims(self) -> Tuple[int, ...]:
        """Host-grid shape: topology dims divided by the per-host chip
        block.  Falls back to a 1-D grid if packing is irregular."""
        n = self.num_hosts
        if not self.is_multi_host:
            return (1,)
        block = self.host_block_dims()
        host_dims = []
        for d, b in zip(self.dims, block):
            if d % b != 0:
                return (n,)
            host_dims.append(d // b)
        if math.prod(host_dims) != n:
            return (n,)
        return tuple(host_dims)

    def host_ring_order(self) -> Sequence[int]:
        """Deterministic ring order of host indices for SP/ring attention.

        A generalized boustrophedon (snake) path over the N-D host grid:
        every consecutive hop differs in exactly one grid coordinate by 1,
        i.e. is an ICI neighbor — what ring attention needs (SURVEY.md §5.7:
        ring order must be stable and neighbor-wise).  The closing wrap hop
        rides the torus wrap link where the hardware has one.
        """
        n = self.num_hosts
        if n <= 2:
            return list(range(n))
        host_dims = [d for d in self.host_grid_dims() if d > 1]
        if len(host_dims) <= 1:
            return list(range(n))

        # N-D snake: innermost axis sweeps forward/backward depending on the
        # parity of the sum of all outer coordinates, recursively — each step
        # changes exactly one coordinate by +/-1.
        def snake(dims):
            if len(dims) == 1:
                return [(i,) for i in range(dims[0])]
            outer = snake(dims[:-1])
            path = []
            for k, coord in enumerate(outer):
                inner = range(dims[-1]) if k % 2 == 0 else range(dims[-1] - 1, -1, -1)
                for i in inner:
                    path.append(coord + (i,))
            return path

        strides = [1] * len(host_dims)
        for i in range(len(host_dims) - 2, -1, -1):
            strides[i] = strides[i + 1] * host_dims[i + 1]
        return [sum(c * s for c, s in zip(coord, strides)) for coord in snake(host_dims)]


def mesh_shape_for(
    topo: SliceTopology,
    num_slices: int = 1,
    model_parallelism: Optional[int] = None,
) -> Tuple[int, int]:
    """Default (data, model) 2D logical mesh for a slice group.

    Model axis rides ICI within the slice, data axis spans slices over DCN —
    the scaling-book recipe.  ``model_parallelism`` defaults to the whole
    slice (pure TP/FSDP inside the slice).
    """
    if num_slices < 1:
        raise TopologyError(f"num_slices must be >= 1, got {num_slices}")
    chips = topo.num_chips
    mp = chips if model_parallelism is None else model_parallelism
    if mp < 1 or chips % mp != 0:
        raise TopologyError(f"model parallelism {mp} must divide {chips} chips")
    return (num_slices * (chips // mp), mp)
