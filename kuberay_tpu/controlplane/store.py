"""In-memory K8s-style object store: the control plane's substrate.

Plays the role the kube-apiserver + etcd play for the reference (and that
envtest plays in its tests, SURVEY.md §4 tier 2): typed objects with
metadata, resourceVersion-based optimistic concurrency, watch events,
finalizers, deletionTimestamps, and owner-reference cascading GC.

Controllers talk to this through the same verbs a K8s client exposes
(get/list/create/update/patch-status/delete/watch), so a real-cluster
backend can be slotted behind the same interface later.  Thread-safe:
reconcilers run on worker threads.

Hot-path design (docs/performance.md):

- **Committed objects are immutable.**  Every mutator builds a new
  object (sharing unchanged subtrees with the previous revision) and
  swaps it in under the lock.  Reads return copy-on-write snapshots
  (:mod:`~kuberay_tpu.controlplane.snapshot`) instead of deep copies;
  ``deep=True`` opts back into a plain private copy.
- **Indexed reads.**  Per-kind and per-(kind, namespace) key indexes
  back ``list``/``count``/``kinds`` (plus the label indexes that play
  the reference's scoped informer-cache role,
  internal/managercache/cache.go:18), and an ownerReference uid index
  makes cascade deletion O(dependents).
- **Nothing slow under the mutation lock.**  ``_notify`` only appends
  to the backlog and to per-subscriber bounded delivery queues; journal
  records queue the same way.  Watch fan-out and journal serialization +
  append run after the lock is released — inline on the mutating thread
  (``dispatch="sync"``, the deterministic default the simulation
  contract requires) or on a dispatcher thread (``dispatch="async"``,
  the live-operator mode) — so journal fsync and reconcile work no
  longer serialize every writer (analysis rule ``no-io-under-store-lock``).

``journal_path``: optional etcd-lite durability for the standalone
operator — every committed state change appends a CRC-framed record
via the journal engine (native group-commit C++ writer when the
toolchain is available, Python fallback otherwise — native/journal);
on construction the journal replays, so CRs (and the level-triggered
reconcile state they carry) survive operator restarts the same way CR
status in a real cluster does (SURVEY §5.4).  The journal compacts to
a snapshot when it grows past ``journal_compact_bytes``.
"""

from __future__ import annotations

# kuberay-lint: disable-file=transitive-blocking-under-lock -- compaction deliberately runs under the journal lock to exclude appenders (docstring above); the only sink the analyzer names is the once-per-process native-engine build, memoized behind native.journal._load's own lock

import bisect
import copy
import json
import logging
import os
import threading
import time
import uuid
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from kuberay_tpu.controlplane.snapshot import snapshot

_LOG = logging.getLogger("kuberay_tpu.store")


class StoreError(Exception):
    pass


class NotFound(StoreError):
    pass


class AlreadyExists(StoreError):
    pass


class Conflict(StoreError):
    """resourceVersion mismatch (optimistic concurrency failure)."""


class ExpiredError(StoreError):
    """The requested resourceVersion has fallen off the bounded watch
    backlog — the 410-Gone analogue (kube-apiserver ``Expired``): the
    client cannot be caught up by replay and must relist.  Carries the
    requested ``rv`` and the store's ``latest`` rv so callers can scope
    the relist and reset their resume point."""

    def __init__(self, rv: int, latest: int):
        super().__init__(
            f"resourceVersion {rv} is too old: the event backlog no "
            f"longer reaches it (latest {latest}); relist required")
        self.rv = rv
        self.latest = latest


class Invalid(StoreError):
    pass


def carry_rv(obj: Dict[str, Any], cur: Dict[str, Any]) -> Dict[str, Any]:
    """Stamp ``obj`` with ``cur``'s resourceVersion so the write carries
    an optimistic-concurrency precondition (SURVEY §5.2): a foreign
    write between the ``cur`` read and the update raises Conflict and
    the reconciler requeues instead of clobbering.

    ONLY valid when ``obj``'s payload was computed from ``cur`` itself
    (single read-modify-write).  Stamping a payload computed from an
    *earlier* snapshot with a *fresh* read's rv defeats the precondition
    — the clobber pattern the ``rv-precondition`` lint rule flags
    (docs/static-analysis.md); reconcilers instead carry the
    reconcile-start rv through the pass, threading bumps from their own
    writes' return values.

    Loud on a store that omits rv — a missing precondition would
    silently revert to last-writer-wins, which is exactly the bug class
    this helper exists to prevent.
    """
    rv = cur.get("metadata", {}).get("resourceVersion")
    if not rv:
        raise StoreError(
            f"{cur.get('kind')} {cur.get('metadata', {}).get('name')}: "
            "store returned no resourceVersion; refusing an unguarded "
            "status write")
    obj["metadata"]["resourceVersion"] = rv
    return obj


def _key(kind: str, namespace: str, name: str) -> Tuple[str, str, str]:
    return (kind, namespace, name)


class Event:
    ADDED = "ADDED"
    MODIFIED = "MODIFIED"
    DELETED = "DELETED"
    # Progress marker on the watch fan-out path (kube watch bookmarks):
    # carries only the high-water resourceVersion so subscribers can
    # advance their resume point across spans they saw no events in.
    # Never state, never journaled, never in the backlog.
    BOOKMARK = "BOOKMARK"

    __slots__ = ("type", "kind", "obj")

    def __init__(self, type_: str, kind: str, obj: Dict[str, Any]):
        self.type = type_
        self.kind = kind
        self.obj = obj


class _Subscription:
    """One watcher and its bounded delivery queue.  Entries are
    ``(seq, Event)``; ``seq`` is the store-wide delivery sequence so the
    drain can interleave multiple subscribers back into commit order."""

    __slots__ = ("fn", "queue", "dropped")

    def __init__(self, fn: Callable[[Event], None]):
        self.fn = fn
        self.queue: deque = deque()
        self.dropped = 0


class ObjectStore:
    """Objects are plain dicts with apiVersion/kind/metadata/spec/status —
    exactly the ``to_dict`` form of the api/ dataclasses.

    ``dispatch``: ``"sync"`` delivers watch events inline on the
    mutating thread after the lock is released (deterministic — what
    the chaos-sim replay contract and ``run_until_idle`` tests rely
    on); ``"async"`` hands delivery to a dispatcher thread so writers
    never wait on watcher work at all (the live-operator mode).
    """

    INDEXED_LABELS = ("tpu.dev/cluster", "tpu.dev/warm-pool",
                      "tpu.dev/originated-from-cr-name")

    def __init__(self, journal_path: str = "",
                 journal_compact_bytes: int = 64 * 1024 * 1024,
                 journal_engine: str = "auto",
                 uid_factory: Optional[Callable[[], str]] = None,
                 dispatch: str = "sync",
                 watch_queue_max: int = 10000,
                 backlog_max: int = 10000,
                 bookmark_interval: int = 0,
                 metrics=None):
        if dispatch not in ("sync", "async"):
            raise ValueError(f"dispatch must be 'sync' or 'async', "
                             f"got {dispatch!r}")
        if backlog_max < 1:
            raise ValueError(f"backlog_max must be >= 1, got {backlog_max}")
        self._lock = threading.RLock()
        self._objects: Dict[Tuple[str, str, str], Dict[str, Any]] = {}
        self._rv = 0
        # ``uid_factory``: override uid generation (default uuid4).  The
        # deterministic simulation passes a counter so replays-by-seed
        # assign identical uids across processes.
        self._uid_factory = uid_factory or (lambda: uuid.uuid4().hex)
        # Fault-injection interposer (kuberay_tpu.sim seam): when set,
        # consulted before every mutation (may raise Conflict to model a
        # lost rv race) and on every local watcher dispatch (may drop,
        # duplicate, or defer the event).  The streaming backlog always
        # records the true event — chaos applies to the informer path,
        # exactly where real watch streams lose/reorder.
        self._interposer = None
        # -- read indexes (all maintained by _reindex) --
        # (label_key, label_value) -> set of object keys
        self._label_index: Dict[Tuple[str, str], set] = {}
        # kind -> set of keys; (kind, namespace) -> set of keys
        self._kind_index: Dict[str, set] = {}
        self._kind_ns_index: Dict[Tuple[str, str], set] = {}
        # owner uid -> insertion-ORDERED dict-as-set of dependent keys.
        # Ordered on purpose: cascade deletion walks it, and its event
        # order is part of the deterministic-replay journal hash — the
        # bucket must preserve the same creation order the old
        # full-scan (dict iteration) delivered.
        self._owner_index: Dict[str, Dict[Tuple[str, str, str], None]] = {}
        # -- watch fan-out --
        self._dispatch_mode = dispatch
        self._watch_queue_max = watch_queue_max
        self._subs: List[_Subscription] = []
        self._seq = 0
        self._closed = False
        # Serializes sync-mode drains so concurrent writers deliver in
        # commit order; reentrant because a watcher may itself mutate
        # the store (its nested drain runs inline).
        self._dispatch_lock = threading.RLock()
        self._delivery_cond = threading.Condition(self._lock)
        # -- journal --
        self._journal = None
        self._journal_path = journal_path
        self._journal_engine = journal_engine
        self._journal_compact_bytes = journal_compact_bytes
        # Commit-ordered journal records, serialized + appended OUTSIDE
        # the mutation lock (committed objects are immutable, so the
        # late json.dumps sees exactly the committed revision).
        self._journal_queue: deque = deque()
        self._journal_lock = threading.Lock()
        # Bounded event backlog for streaming watches: (rv, Event); rv is
        # the post-commit resourceVersion so clients resume by rv.
        # Strictly rv-sorted — events_since/wait_for_events bisect to
        # the resume point instead of scanning.  ``backlog_max`` sizes
        # the resumable window: at the 10k-cluster rung a single
        # scale-up storm emits more events than the old hardcoded 10000,
        # silently forcing full relists on every resume — size it to the
        # expected event burst (operator --watch-backlog-max).  Evictions
        # are counted and surfaced (tpu_watch_backlog_evictions_total).
        self._backlog: List[Tuple[int, Event]] = []
        self._backlog_max = backlog_max
        self._backlog_evictions = 0
        self._backlog_evictions_reported = 0
        self._backlog_cond = threading.Condition(self._lock)
        # Watch bookmarks: every ``bookmark_interval`` committed rvs, a
        # BOOKMARK event (high-water rv only) goes to every subscriber
        # queue — never to the backlog or journal — so idle-ish
        # informers keep a fresh resume point (0 disables).
        self._bookmark_interval = bookmark_interval
        self._last_bookmark_rv = 0
        self._metrics = metrics
        self._last_snapshot_bytes = 0
        if journal_path:
            self._replay_journal()
            if self._journal is None:   # legacy migration already opened it
                self._open_journal()
        self._dispatcher: Optional[threading.Thread] = None
        if dispatch == "async":
            self._dispatcher = threading.Thread(
                target=self._dispatch_loop, daemon=True,
                name="store-dispatcher")
            self._dispatcher.start()

    # -- durability --------------------------------------------------------
    # CRC-framed binary journal via native/journal.py: the native engine
    # (journal.cpp) group-commits with fdatasync — crash-durable at
    # O(syncs/sec) instead of O(mutations/sec); the Python engine is the
    # no-toolchain fallback.  Round-1 journals were JSON text lines;
    # _replay_journal migrates them to frames on first open.

    def _open_journal(self, truncate_tail: bool = True):
        from kuberay_tpu.native.journal import open_journal, valid_prefix_len
        # Truncate a torn tail: frames appended AFTER a tear would be
        # unreachable to replay (it stops at the first bad frame).  Only
        # meaningful at construction — the post-compaction reopen passes
        # False (the snapshot was just written and synced by this
        # process; a full CRC re-scan would stall appenders for nothing).
        if truncate_tail:
            try:
                size = os.path.getsize(self._journal_path)
                good = valid_prefix_len(self._journal_path)
                if good < size:
                    with open(self._journal_path, "rb+") as f:
                        f.truncate(good)
            except OSError:
                pass
        self._journal = open_journal(self._journal_path,
                                     self._journal_engine)

    def _journal_entries(self):
        """Frame payloads -> dict entries; transparently replays (and
        flags for migration) a legacy text journal."""
        from kuberay_tpu.native.journal import replay
        frames = list(replay(self._journal_path,
                             engine=self._journal_engine))
        if not frames and os.path.getsize(self._journal_path) > 0:
            # Legacy text journal (round 1): JSON lines.
            self._legacy_journal = True
            with open(self._journal_path, errors="replace") as f:
                frames = [ln.strip().encode() for ln in f if ln.strip()]
        for raw in frames:
            try:
                yield json.loads(raw)
            except ValueError:
                continue   # torn tail write (legacy text only)

    def _replay_journal(self):
        if not os.path.exists(self._journal_path):
            return
        self._legacy_journal = False
        for entry in self._journal_entries():
            op = entry.get("op")
            if op == "put":
                obj = entry["obj"]
                md = obj.get("metadata", {})
                k = _key(obj.get("kind", ""), md.get("namespace", "default"),
                         md.get("name", ""))
                old = self._objects.get(k)
                self._objects[k] = obj
                self._reindex(k, old, obj)
                self._rv = max(self._rv, md.get("resourceVersion", 0))
            elif op == "del":
                k = tuple(entry["key"])
                old = self._objects.pop(k, None)
                if old is not None:
                    self._reindex(k, old, None)
            elif op == "snapshot":
                # Snapshot restarts the world (compaction marker); the
                # recorded rv counter prevents resourceVersion reuse
                # after deleted-object churn was compacted away.
                self._objects.clear()
                self._label_index.clear()
                self._kind_index.clear()
                self._kind_ns_index.clear()
                self._owner_index.clear()
                self._rv = max(self._rv, entry.get("rv", 0))
                for obj in entry["objects"]:
                    md = obj.get("metadata", {})
                    k = _key(obj.get("kind", ""),
                             md.get("namespace", "default"),
                             md.get("name", ""))
                    self._objects[k] = obj
                    self._reindex(k, None, obj)
                    self._rv = max(self._rv,
                                   md.get("resourceVersion", 0))

        if self._legacy_journal:
            # Rewrite the text journal as a framed snapshot before the
            # appender opens (mixed text+binary would be unreplayable).
            self._write_snapshot()

    def _journal_put(self, obj):
        if self._journal_path:
            self._journal_queue.append({"op": "put", "obj": obj})

    def _journal_del(self, k):
        if self._journal_path:
            self._journal_queue.append({"op": "del", "key": list(k)})

    def _drain_journal(self):
        """Serialize + append queued records, OUTSIDE the mutation lock.

        Records were queued in commit order under the mutation lock and
        the deque + journal lock preserve that order on disk; committed
        objects are immutable, so serializing them late is race-free.  A
        writer may drain (and thus persist) a concurrent writer's
        records — the ack barrier below still guarantees each mutator's
        own record is durable before its call returns.
        """
        if not self._journal_path:
            return
        with self._journal_lock:
            while True:
                try:
                    rec = self._journal_queue.popleft()
                except IndexError:
                    break
                j = self._journal
                if j is not None:
                    j.append(json.dumps(rec).encode())
            self._maybe_compact()

    def flush_journal(self):
        """Block until all acknowledged mutations are ON DISK (fdatasync
        via the native group-commit engine / fsync via the fallback)."""
        self._drain_journal()
        self._journal_ack()

    def _journal_ack(self):
        """Durable-ack barrier at the end of every public mutator, OUTSIDE
        the store lock: concurrent mutators' frames share one group
        commit.  Lock-free read of self._journal is safe — engines no-op
        flush() after close(), and a compaction swap only closes the old
        engine after draining+syncing it, so frames appended under the
        journal lock are durable on whichever engine the swap race hands
        us."""
        j = self._journal   # kuberay-lint: disable=lock-discipline -- snapshot read is deliberate (see docstring); worst case is one no-op flush on a just-swapped engine
        if j is not None:
            j.flush()

    def _write_snapshot(self):
        """Atomically replace the journal with one snapshot frame.
        Callers hold the journal lock (or are the single-threaded
        constructor); only the brief world-copy takes the mutation
        lock — the objects are immutable, a shallow list is a
        consistent snapshot."""
        from kuberay_tpu.native.journal import open_journal
        with self._lock:
            objects = list(self._objects.values())
            rv = self._rv
        tmp = self._journal_path + ".tmp"
        try:
            os.remove(tmp)
        except OSError:
            pass
        snap = open_journal(tmp, self._journal_engine)
        snap.append(json.dumps(
            {"op": "snapshot", "rv": rv, "objects": objects}).encode())
        snap.flush()
        snap.close()
        old = self._journal
        if old is not None:
            old.close()
        try:
            os.replace(tmp, self._journal_path)
            self._last_snapshot_bytes = os.path.getsize(self._journal_path)
            self._open_journal(truncate_tail=False)
        except OSError:
            # The old engine is closed (its append/flush silently no-op),
            # which would let mutations ack without being journaled —
            # reopen the surviving file so the journal stays live; if
            # even that fails, surface it rather than run ack-blind.
            self._journal = None
            self._open_journal()   # raises on failure: mutators error out

    def _maybe_compact(self):
        try:
            size = os.path.getsize(self._journal_path)
        except OSError:
            return
        # Require real growth past the last snapshot too — a live state
        # bigger than the threshold must not re-snapshot on every write.
        if size < max(self._journal_compact_bytes,
                      2 * self._last_snapshot_bytes):
            return
        self._write_snapshot()

    # -- indexes -----------------------------------------------------------

    @classmethod
    def _index_labels(cls, obj) -> List[Tuple[str, str]]:
        labels = obj.get("metadata", {}).get("labels", {}) or {}
        return [(lk, labels[lk]) for lk in cls.INDEXED_LABELS
                if labels.get(lk) is not None]

    @staticmethod
    def _index_owners(obj) -> List[str]:
        return [ref["uid"] for ref in
                (obj.get("metadata", {}).get("ownerReferences") or [])
                if ref.get("uid")]

    def _reindex(self, key, old, new):
        """Move ``key`` between index buckets to reflect ``old`` -> ``new``
        (either side may be None for create/delete).  Unchanged
        memberships are left in place, which both skips work on the
        common label-free update and preserves each owner bucket's
        insertion order (the cascade-delete determinism contract)."""
        if old is not None and new is None:
            bucket = self._kind_index.get(key[0])
            if bucket is not None:
                bucket.discard(key)
                if not bucket:
                    del self._kind_index[key[0]]
            ns_bucket = self._kind_ns_index.get((key[0], key[1]))
            if ns_bucket is not None:
                ns_bucket.discard(key)
                if not ns_bucket:
                    del self._kind_ns_index[(key[0], key[1])]
        elif old is None and new is not None:
            self._kind_index.setdefault(key[0], set()).add(key)
            self._kind_ns_index.setdefault((key[0], key[1]), set()).add(key)

        old_labels = set(self._index_labels(old)) if old else set()
        new_labels = set(self._index_labels(new)) if new else set()
        for lk, lv in old_labels - new_labels:
            bucket = self._label_index.get((lk, lv))
            if bucket is not None:
                bucket.discard(key)
                if not bucket:
                    del self._label_index[(lk, lv)]
        for lk, lv in new_labels - old_labels:
            self._label_index.setdefault((lk, lv), set()).add(key)

        old_owners = set(self._index_owners(old)) if old else set()
        new_owners = set(self._index_owners(new)) if new else set()
        for uid in old_owners - new_owners:
            bucket = self._owner_index.get(uid)
            if bucket is not None:
                bucket.pop(key, None)
                if not bucket:
                    del self._owner_index[uid]
        for uid in new_owners - old_owners:
            self._owner_index.setdefault(uid, {})[key] = None

    def _commit(self, key, old, new):
        """Swap the new immutable revision in and record it: indexes,
        journal queue.  Mutation lock held by the caller."""
        self._objects[key] = new
        self._reindex(key, old, new)
        self._journal_put(new)

    # -- watch fan-out -----------------------------------------------------

    def _next_rv(self) -> int:
        self._rv += 1
        return self._rv

    def _notify(self, ev: Event):
        """Record + enqueue one committed event.  Runs under the
        mutation lock but does NO delivery and NO I/O: it appends to the
        rv-sorted backlog and to each subscriber's bounded queue
        (drop-oldest on overflow — a level-triggered subscriber recovers
        via resync, and ``dropped`` counts the loss).  The actual
        callbacks run off-lock in :meth:`_drain_deliveries` or the
        dispatcher thread."""
        # Consumers get a CoW view, not the committed object: a watcher
        # (or /watch long-poller) that mutates ev.obj must never reach
        # committed state.  One shared wrapper per event, like the one
        # shared deepcopy the old fan-out handed every watcher.
        ev.obj = snapshot(ev.obj)
        self._backlog.append((self._rv, ev))
        if len(self._backlog) > self._backlog_max:
            evicted = len(self._backlog) - self._backlog_max
            del self._backlog[:evicted]
            # Counted under the lock, reported to metrics off-lock in
            # _finish_write: an eviction means some resume point just
            # expired — at scale this is the signal --watch-backlog-max
            # is undersized and restarts will pay full relists.
            self._backlog_evictions += evicted
        self._backlog_cond.notify_all()
        deliveries = [ev]
        if self._interposer is not None:
            # Pure computation (seeded rng draw) under the lock; the
            # interposer may return [] (drop), [ev] (pass), [ev, ev]
            # (duplicate) or stash the event for deferred redelivery.
            deliveries = self._interposer.on_event(ev)
        if self._bookmark_interval and \
                self._rv - self._last_bookmark_rv >= self._bookmark_interval:
            # Bookmarks ride the subscriber queues AFTER the interposer
            # (they are local progress markers, not chaos targets) and
            # never enter the backlog — the journal hash contract.
            self._last_bookmark_rv = self._rv
            deliveries = list(deliveries) + [Event(
                Event.BOOKMARK, "",
                {"metadata": {"resourceVersion": self._rv}})]
        for dev in deliveries:
            self._seq += 1
            seq = self._seq
            for sub in self._subs:
                if len(sub.queue) >= self._watch_queue_max:
                    sub.queue.popleft()
                    sub.dropped += 1
                sub.queue.append((seq, dev))
        if deliveries and self._subs:
            self._delivery_cond.notify_all()

    def _next_delivery(self):
        """Earliest queued (fn, event) across subscribers, or None.
        Mutation lock held by the caller; the global seq restores commit
        order across per-subscriber queues."""
        best_seq = None
        best_sub = None
        for sub in self._subs:
            if sub.queue:
                seq = sub.queue[0][0]
                if best_seq is None or seq < best_seq:
                    best_seq, best_sub = seq, sub
        if best_sub is None:
            return None
        _, ev = best_sub.queue.popleft()
        return best_sub.fn, ev

    def _deliver(self, fn, ev):
        try:
            fn(ev)
        except Exception:
            # Watcher errors never poison the store — but a watcher
            # that throws on every event is a wedged controller, so
            # it must show up in logs, not vanish.
            _LOG.exception("store watcher failed on %s %s",
                           ev.type, ev.kind)

    def _drain_deliveries(self):
        """Sync-dispatch delivery: the mutating thread drains every
        queued delivery in commit order, outside the mutation lock.  The
        dispatch lock is reentrant on purpose — a watcher that mutates
        the store drains its own events inline, preserving the exact
        nested delivery order the pre-fan-out store had."""
        if self._dispatch_mode != "sync":
            return
        with self._dispatch_lock:
            while True:
                with self._lock:
                    item = self._next_delivery()
                if item is None:
                    return
                self._deliver(*item)

    def _dispatch_loop(self):
        """Async-dispatch delivery thread."""
        while True:
            with self._lock:
                item = self._next_delivery()
                while item is None:
                    if self._closed:
                        return
                    self._delivery_cond.wait(timeout=1.0)
                    item = self._next_delivery()
            self._deliver(*item)

    def _finish_write(self):
        """Post-commit tail of every public mutator, outside the
        mutation lock: journal serialization + append, sync-mode watch
        delivery, eviction accounting, then the durable-ack barrier."""
        self._drain_journal()
        self._drain_deliveries()
        self._report_evictions()
        self._journal_ack()

    def _report_evictions(self):
        """Flush backlog-eviction counts to metrics, off the mutation
        lock (the metrics registry has its own lock — taking it under
        the store lock would be a lock-order hazard)."""
        with self._lock:
            m = self._metrics
            if m is None:
                return
            delta = self._backlog_evictions - self._backlog_evictions_reported
            self._backlog_evictions_reported = self._backlog_evictions
        if delta:
            m.watch_backlog_evictions(delta)

    def set_metrics(self, metrics) -> None:
        """Attach the ControlPlaneMetrics facade after construction (the
        operator owns the metrics registry but may receive a pre-built
        store)."""
        with self._lock:
            self._metrics = metrics

    def backlog_evictions_total(self) -> int:
        """Events evicted from the resumable backlog window so far."""
        with self._lock:
            return self._backlog_evictions

    def flush_watch(self, timeout: float = 5.0) -> bool:
        """Wait until every subscriber queue is empty (async-dispatch
        helper for tests/benchmarks); returns False on timeout."""
        deadline = time.time() + timeout
        while True:
            self._drain_deliveries()
            with self._lock:
                if not any(sub.queue for sub in self._subs):
                    return True
            if time.time() >= deadline:
                return False
            time.sleep(0.001)

    def watch_dropped_total(self) -> int:
        """Deliveries lost to subscriber-queue overflow (drop-oldest)."""
        with self._lock:
            return sum(sub.dropped for sub in self._subs)

    def close(self):
        """Stop the async dispatcher (no-op for sync stores)."""
        with self._lock:
            self._closed = True
            self._delivery_cond.notify_all()
        if self._dispatcher is not None:
            self._dispatcher.join(timeout=2.0)
            self._dispatcher = None

    def set_interposer(self, interposer) -> None:
        """Install (or clear, with None) the fault-injection interposer.

        The interposer contract (see kuberay_tpu.sim.faults.FaultPlan):
        ``on_mutation(verb, kind, name, namespace)`` may raise
        :class:`Conflict`; ``on_event(ev) -> List[Event]`` decides local
        watcher deliveries.  Both run synchronously on the mutating
        thread, so a deterministic plan yields deterministic histories.
        """
        with self._lock:
            self._interposer = interposer

    def _interpose(self, verb: str, kind: str, name: str, namespace: str):
        """Mutation seam: called at the top of every public mutator,
        before any state changes, so an injected Conflict models a write
        that lost the optimistic-concurrency race cleanly (nothing
        committed, no event emitted)."""
        with self._lock:
            ip = self._interposer
        if ip is not None:
            ip.on_mutation(verb, kind, name, namespace)

    def redeliver(self, ev: Event) -> None:
        """Dispatch a previously deferred watch event to current
        watchers (sim seam: delayed-delivery faults).  Bypasses the
        interposer and the delivery queues — a deferred event is
        redelivered exactly once, immediately."""
        with self._lock:
            fns = [sub.fn for sub in self._subs]
        for fn in fns:
            try:
                fn(ev)
            except Exception:
                _LOG.exception("store watcher failed on redelivered %s %s",
                               ev.type, ev.kind)

    def watch(self, fn: Callable[[Event], None]) -> Callable[[], None]:
        """Register a watcher; returns an unsubscribe function."""
        sub = _Subscription(fn)
        with self._lock:
            self._subs.append(sub)

        def cancel():
            with self._lock:
                if sub in self._subs:
                    self._subs.remove(sub)
        return cancel

    # -- verbs -------------------------------------------------------------

    def create(self, obj: Dict[str, Any]) -> Dict[str, Any]:
        obj = copy.deepcopy(obj)   # materialize caller input (may be a CoW view)
        kind = obj.get("kind")
        md = obj.setdefault("metadata", {})
        name, ns = md.get("name"), md.get("namespace", "default")
        if not kind or not name:
            raise Invalid("kind and metadata.name are required")
        md.setdefault("namespace", "default")
        self._interpose("create", kind, name, ns)
        with self._lock:
            k = _key(kind, ns, name)
            if k in self._objects:
                raise AlreadyExists(f"{kind} {ns}/{name} already exists")
            md["uid"] = md.get("uid") or self._uid_factory()
            md["creationTimestamp"] = md.get("creationTimestamp") or time.time()
            md["resourceVersion"] = self._next_rv()
            md.setdefault("generation", 1)
            self._commit(k, None, obj)
            self._notify(Event(Event.ADDED, kind, obj))
        self._finish_write()
        return snapshot(obj)

    def get(self, kind: str, name: str, namespace: str = "default", *,
            deep: bool = False) -> Dict[str, Any]:
        with self._lock:
            obj = self._objects.get(_key(kind, namespace, name))
            if obj is None:
                raise NotFound(f"{kind} {namespace}/{name} not found")
            return copy.deepcopy(obj) if deep else snapshot(obj)

    def try_get(self, kind: str, name: str, namespace: str = "default", *,
                deep: bool = False):
        try:
            return self.get(kind, name, namespace, deep=deep)
        except NotFound:
            return None

    def list(self, kind: str, namespace: Optional[str] = None,
             labels: Optional[Dict[str, str]] = None, *,
             deep: bool = False) -> List[Dict[str, Any]]:
        with self._lock:
            keys = None
            if labels:
                for lk, lv in labels.items():
                    if lk in self.INDEXED_LABELS:
                        keys = self._label_index.get((lk, lv), set())
                        break
            if keys is None:
                if namespace is not None:
                    keys = self._kind_ns_index.get((kind, namespace), set())
                else:
                    keys = self._kind_index.get(kind, set())
            out = []
            for k in keys:
                obj = self._objects.get(k)
                if obj is None or k[0] != kind:
                    continue
                md = obj.get("metadata", {})
                if namespace is not None and md.get("namespace") != namespace:
                    continue
                if labels:
                    obj_labels = md.get("labels", {}) or {}
                    if any(obj_labels.get(lk) != lv
                           for lk, lv in labels.items()):
                        continue
                out.append(copy.deepcopy(obj) if deep else snapshot(obj))
            out.sort(key=lambda o: (o["metadata"]["namespace"],
                                    o["metadata"]["name"]))
            return out

    def update(self, obj: Dict[str, Any], *, subresource: str = "") -> Dict[str, Any]:
        """Full-object update with optimistic concurrency.

        ``subresource='status'`` mimics the status subresource: spec changes
        are ignored and generation does not bump.  Spec updates bump
        ``metadata.generation`` (like the K8s generation contract).
        """
        obj = copy.deepcopy(obj)
        kind = obj.get("kind")
        md = obj.get("metadata", {})
        name, ns = md.get("name"), md.get("namespace", "default")
        self._interpose("update_status" if subresource == "status"
                        else "update", kind, name, ns)
        with self._lock:
            k = _key(kind, ns, name)
            cur = self._objects.get(k)
            if cur is None:
                raise NotFound(f"{kind} {ns}/{name} not found")
            cur_md = cur["metadata"]
            if md.get("resourceVersion") and md["resourceVersion"] != cur_md["resourceVersion"]:
                raise Conflict(
                    f"{kind} {ns}/{name}: resourceVersion {md.get('resourceVersion')} "
                    f"!= {cur_md['resourceVersion']}")
            # New revision shares untouched subtrees with the previous
            # one (both immutable); replaced sections come from the
            # entry deepcopy of the caller's object, so they are private.
            new = dict(cur)
            if subresource == "status":
                new["status"] = obj.get("status", {})
                new_md = dict(cur_md)
            else:
                # Immutable fields preserved; spec/metadata writable.
                spec_changed = obj.get("spec") != cur.get("spec")
                new["spec"] = obj.get("spec", cur.get("spec"))
                new_md = md
                for field in ("uid", "creationTimestamp", "generation",
                              "deletionTimestamp"):
                    new_md[field] = cur_md.get(field)
                if spec_changed:
                    new_md["generation"] = cur_md.get("generation", 1) + 1
                # status only via subresource
                new["status"] = cur.get("status", {})
            new_md["resourceVersion"] = self._next_rv()
            new["metadata"] = new_md
            self._commit(k, cur, new)
            self._notify(Event(Event.MODIFIED, kind, new))
        # Deleting an object is finalized outside the lock path; check here:
        self._maybe_finalize_delete(kind, name, ns)
        self._finish_write()
        return snapshot(new)

    def update_status(self, obj: Dict[str, Any]) -> Dict[str, Any]:
        return self.update(obj, subresource="status")

    def patch(self, kind: str, name: str, namespace: str = "default",
              body: Any = None, *, patch_type: str = "merge",
              subresource: str = "", field_manager: str = "",
              force: bool = False, validate=None) -> Dict[str, Any]:
        """PATCH verbs (kube parity — the reference's V2 surface proxies
        them all, apiserversdk/proxy.go:28-40):

        ``patch_type``: ``merge`` (RFC 7386) | ``strategic`` (merge-key
        lists) | ``json`` (RFC 6902 op list) | ``apply`` (Server-Side
        Apply upsert with managedFields ownership; requires
        ``field_manager``; ``force`` steals conflicting fields).

        A ``metadata.resourceVersion`` inside a dict patch body is an
        optimistic-concurrency precondition.  ``validate(old, new)``
        runs under the lock before commit and returns a list of errors
        (admission seam).  Raises Conflict on SSA field conflicts with
        the conflicting paths in the message.
        """
        from kuberay_tpu.controlplane import patch as P
        self._interpose("patch", kind, name, namespace)
        created = False
        with self._lock:
            k = _key(kind, namespace, name)
            cur = self._objects.get(k)
            if cur is None and patch_type != "apply":
                raise NotFound(f"{kind} {namespace}/{name} not found")
            if isinstance(body, dict):
                want_rv = body.get("metadata", {}).get("resourceVersion")
                if want_rv and cur is not None and \
                        want_rv != cur["metadata"]["resourceVersion"]:
                    raise Conflict(
                        f"{kind} {namespace}/{name}: resourceVersion "
                        f"{want_rv} != {cur['metadata']['resourceVersion']}")
            try:
                # The patch helpers never mutate their target: they
                # build new containers along patched paths and share
                # untouched subtrees — which is exactly the committed-
                # immutable discipline, so ``cur`` goes in as-is.
                if patch_type == "apply":
                    applied = copy.deepcopy(body) if body else {}
                    applied.setdefault("kind", kind)
                    amd = applied.setdefault("metadata", {})
                    amd.setdefault("name", name)
                    amd.setdefault("namespace", namespace)
                    amd.pop("resourceVersion", None)
                    new = P.apply_ssa(cur, applied, field_manager,
                                      force=force, subresource=subresource)
                elif patch_type == "merge":
                    new = P.json_merge_patch(cur, body)
                elif patch_type == "strategic":
                    new = P.strategic_merge_patch(cur, body)
                elif patch_type == "json":
                    new = P.json_patch(cur, body)
                else:
                    raise Invalid(f"unknown patch type {patch_type!r}")
            except P.ApplyConflict as e:
                raise Conflict(str(e)) from None
            except P.PatchError as e:
                raise Invalid(str(e)) from None
            if not isinstance(new, dict):
                # e.g. a merge patch body of null/"x"/[...] — valid JSON,
                # but the result of patching an object must be an object.
                raise Invalid("patch must produce an object, got "
                              f"{type(new).__name__}")

            # Identity and server-owned metadata are not patchable.  The
            # metadata dict may still BE the committed one (unpatched) —
            # shallow-copy before stamping server fields.
            new["kind"] = kind
            if cur is not None and cur.get("apiVersion") is not None:
                new["apiVersion"] = cur["apiVersion"]
            md = dict(new.get("metadata") or {})
            new["metadata"] = md
            md["name"], md["namespace"] = name, namespace
            if cur is not None:
                cur_md = cur["metadata"]
                for f in ("uid", "creationTimestamp", "generation",
                          "deletionTimestamp"):
                    if cur_md.get(f) is not None:
                        md[f] = cur_md[f]
                    else:
                        md.pop(f, None)
                if subresource == "status":
                    # Only status (plus ownership bookkeeping) lands.
                    kept = dict(cur)
                    kept["status"] = new.get("status", {})
                    kept["metadata"] = dict(cur_md)
                    if "managedFields" in md:
                        kept["metadata"]["managedFields"] = \
                            md["managedFields"]
                    new = kept
                    md = new["metadata"]
                else:
                    new["status"] = cur.get("status", {})
            else:
                created = True
                # Server-side-apply upsert: creation identity goes
                # through the same seams create() uses — the injected
                # uid factory and the (sim-shimmable) module clock — so
                # SSA-created objects replay deterministically.
                md["uid"] = self._uid_factory()
                md["creationTimestamp"] = time.time()
                md.setdefault("generation", 1)

            if patch_type != "apply" and field_manager and cur is not None \
                    and subresource != "status":
                P.claim_update(new, cur, new, field_manager, subresource)

            if validate is not None:
                errs = validate(cur, copy.deepcopy(new))
                if errs:
                    raise Invalid("; ".join(errs))

            if cur is not None and subresource != "status" and \
                    new.get("spec") != cur.get("spec"):
                md["generation"] = cur["metadata"].get("generation", 1) + 1
            md["resourceVersion"] = self._next_rv()
            self._commit(k, cur, new)
            self._notify(Event(Event.ADDED if created else Event.MODIFIED,
                               kind, new))
        if not created:
            self._maybe_finalize_delete(kind, name, namespace)
        self._finish_write()
        return snapshot(new)

    def patch_labels(self, kind: str, name: str, namespace: str,
                     labels: Dict[str, Optional[str]]) -> Dict[str, Any]:
        self._interpose("patch_labels", kind, name, namespace)
        with self._lock:
            key = _key(kind, namespace, name)
            cur = self._objects.get(key)
            if cur is None:
                raise NotFound(f"{kind} {namespace}/{name} not found")
            new = dict(cur)
            new_md = dict(cur["metadata"])
            lab = dict(new_md.get("labels") or {})
            for lk, lv in labels.items():
                if lv is None:
                    lab.pop(lk, None)
                else:
                    lab[lk] = lv
            new_md["labels"] = lab
            new_md["resourceVersion"] = self._next_rv()
            new["metadata"] = new_md
            self._commit(key, cur, new)
            self._notify(Event(Event.MODIFIED, kind, new))
        self._finish_write()
        return snapshot(new)

    def delete(self, kind: str, name: str, namespace: str = "default") -> None:
        """Graceful delete: sets deletionTimestamp; the object is removed
        once finalizers empty (the K8s finalizer contract)."""
        self._interpose("delete", kind, name, namespace)
        with self._lock:
            k = _key(kind, namespace, name)
            cur = self._objects.get(k)
            if cur is None:
                raise NotFound(f"{kind} {namespace}/{name} not found")
            if not cur["metadata"].get("deletionTimestamp"):
                new = dict(cur)
                new_md = dict(cur["metadata"])
                new_md["deletionTimestamp"] = time.time()
                new_md["resourceVersion"] = self._next_rv()
                new["metadata"] = new_md
                self._commit(k, cur, new)
                self._notify(Event(Event.MODIFIED, kind, new))
        self._maybe_finalize_delete(kind, name, namespace)
        self._finish_write()

    def remove_finalizer(self, kind: str, name: str, namespace: str,
                         finalizer: str,
                         rv: Optional[int] = None) -> Optional[Dict[str, Any]]:
        """Remove a finalizer; returns the updated object (None when the
        object is gone).  ``rv`` is an optional optimistic-concurrency
        precondition — pass the reconcile-start resourceVersion so a
        foreign write in the window raises Conflict instead of being
        silently raced."""
        self._interpose("remove_finalizer", kind, name, namespace)
        with self._lock:
            k = _key(kind, namespace, name)
            cur = self._objects.get(k)
            if cur is None:
                return None
            if rv is not None and cur["metadata"]["resourceVersion"] != rv:
                raise Conflict(
                    f"{kind} {namespace}/{name}: resourceVersion {rv} "
                    f"!= {cur['metadata']['resourceVersion']}")
            fins = cur["metadata"].get("finalizers", [])
            if finalizer in fins:
                new = dict(cur)
                new_md = dict(cur["metadata"])
                new_md["finalizers"] = [f for f in fins if f != finalizer]
                new_md["resourceVersion"] = self._next_rv()
                new["metadata"] = new_md
                self._commit(k, cur, new)
                self._notify(Event(Event.MODIFIED, kind, new))
                cur = new
            out = snapshot(cur)
        self._maybe_finalize_delete(kind, name, namespace)
        self._finish_write()
        return out

    def add_finalizer(self, kind: str, name: str, namespace: str,
                      finalizer: str,
                      rv: Optional[int] = None) -> Dict[str, Any]:
        """Add a finalizer; returns the updated object so callers can
        thread the bumped resourceVersion through the reconcile pass.
        ``rv``: optional precondition (see :meth:`remove_finalizer`)."""
        self._interpose("add_finalizer", kind, name, namespace)
        with self._lock:
            k = _key(kind, namespace, name)
            cur = self._objects.get(k)
            if cur is None:
                raise NotFound(f"{kind} {namespace}/{name} not found")
            if rv is not None and cur["metadata"]["resourceVersion"] != rv:
                raise Conflict(
                    f"{kind} {namespace}/{name}: resourceVersion {rv} "
                    f"!= {cur['metadata']['resourceVersion']}")
            fins = cur["metadata"].get("finalizers", [])
            if finalizer not in fins:
                new = dict(cur)
                new_md = dict(cur["metadata"])
                new_md["finalizers"] = list(fins) + [finalizer]
                new_md["resourceVersion"] = self._next_rv()
                new["metadata"] = new_md
                self._commit(k, cur, new)
                self._notify(Event(Event.MODIFIED, kind, new))
                cur = new
            out = snapshot(cur)
        self._finish_write()
        return out

    def _maybe_finalize_delete(self, kind: str, name: str, namespace: str):
        """Remove the object if it is terminating with no finalizers, then
        cascade-delete dependents (ownerReference GC)."""
        removed = None
        with self._lock:
            k = _key(kind, namespace, name)
            cur = self._objects.get(k)
            if (cur is not None and cur["metadata"].get("deletionTimestamp")
                    and not cur["metadata"].get("finalizers")):
                removed = self._objects.pop(k)
                self._reindex(k, removed, None)
                self._journal_del(k)
                # DELETED gets its own rv, stamped onto the emitted object
                # (kube-apiserver behavior): it must not share the
                # preceding MODIFIED's rv or resuming watchers skip it
                # forever, and clients that resume from the OBJECT's rv
                # must not regress behind the event and replay it.
                gone = dict(removed)
                gone["metadata"] = dict(removed["metadata"])
                gone["metadata"]["resourceVersion"] = self._next_rv()
                self._notify(Event(Event.DELETED, kind, gone))
        if removed is not None:
            self._cascade_delete(removed)

    def _cascade_delete(self, owner: Dict[str, Any]):
        uid = owner["metadata"].get("uid")
        ns = owner["metadata"].get("namespace", "default")
        with self._lock:
            # The owner-uid index bucket preserves creation order, so
            # dependents delete in the same order the old full scan
            # produced (part of the deterministic-replay event history).
            dependents = [(kind, name)
                          for (kind, ons, name) in
                          self._owner_index.get(uid, {})
                          if ons == ns]
        for kind, name in dependents:
            try:
                self.delete(kind, name, ns)
            except NotFound:
                pass

    # -- introspection -----------------------------------------------------

    def ensure(self, obj: Dict[str, Any], compare=None) -> bool:
        """Create-or-converge: create if absent; update spec when the
        compared projection differs.  ``compare`` extracts the comparable
        part (default: the whole spec).  Returns True when a write happened.
        """
        md = obj["metadata"]
        kind = obj["kind"]
        ns = md.get("namespace", "default")
        compare = compare or (lambda o: o.get("spec"))
        cur = self.try_get(kind, md["name"], ns)
        if cur is None:
            try:
                self.create(obj)
                return True
            except AlreadyExists:
                return False
        if compare(cur) != compare(obj):
            cur["spec"] = obj.get("spec", cur.get("spec"))
            self.update(cur)
            return True
        return False

    def kinds(self) -> List[str]:
        """Sorted kinds currently present (sim GC sweep + debugging)."""
        with self._lock:
            return sorted(k for k, bucket in self._kind_index.items()
                          if bucket)

    def count(self, kind: str) -> int:
        with self._lock:
            return len(self._kind_index.get(kind, ()))

    def resource_version(self) -> int:
        with self._lock:
            return self._rv

    def _backlog_since(self, rv: int, kinds):
        """Backlog entries with rv > given, via bisect (the backlog is
        strictly rv-sorted).  Mutation lock held by the caller."""
        start = bisect.bisect_right(self._backlog, rv, key=lambda e: e[0])
        if kinds is None:
            return self._backlog[start:]
        return [(erv, ev) for erv, ev in self._backlog[start:]
                if ev.kind in kinds]

    def wait_for_events(self, rv: int, kinds=None, timeout: float = 25.0):
        """Blocking events_since: waits on the store's condition variable
        until something lands past ``rv`` (or timeout) — zero idle work,
        immediate delivery for /watch long-polls."""
        deadline = time.time() + timeout
        with self._backlog_cond:
            while True:
                out = self._backlog_since(rv, kinds)
                truncated = ((bool(self._backlog)
                              and self._backlog[0][0] > rv + 1)
                             or (not self._backlog and rv < self._rv))
                if out or truncated:
                    return out, self._rv, truncated
                remaining = deadline - time.time()
                if remaining <= 0:
                    return [], self._rv, False
                self._backlog_cond.wait(remaining)

    def events_since(self, rv: int, kinds=None, *, strict: bool = False):
        """(events, latest_rv, truncated): backlog entries with rv > given.
        ``truncated`` True when the backlog no longer reaches back to
        ``rv`` — the client must relist (standard watch-resume contract).
        An empty backlog with rv behind the store (journal replay,
        restart) is also truncation: the missed span is unrecoverable.

        ``strict=True`` turns truncation into a typed
        :class:`ExpiredError` (the 410-Gone analogue) instead of a flag
        — the informer-resume path uses it so an expired resume point
        cannot be accidentally treated as an empty delta."""
        with self._lock:
            if rv >= self._rv:
                return [], self._rv, False     # idle fast path: no scan
            truncated = ((bool(self._backlog) and self._backlog[0][0] > rv + 1)
                         or (not self._backlog and rv < self._rv))
            if truncated and strict:
                raise ExpiredError(rv, self._rv)
            return self._backlog_since(rv, kinds), self._rv, truncated
