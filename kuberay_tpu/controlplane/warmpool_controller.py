"""Warm slice pools: pre-provisioned slices for slice-ready latency.

The podpool analogue (ref podpool/ — a virtual-kubelet keeping pre-warmed
pods to skip scheduling/image-pull/volume time; the reference's is an
early scaffold with CreatePod unimplemented, manager.go:63-70).  Here the
pool maintenance loop is functional and slice-granular, behind the
``WarmSlicePools`` alpha gate:

- a ``WarmSlicePool`` object declares (accelerator, topology, poolSize,
  template); the controller keeps exactly poolSize healthy warm slices
  standing (pods carry the pool label, full TPU env, no cluster identity);
- unhealthy/incomplete warm slices are replaced whole (same invariant as
  cluster slices);
- ``claim()`` hands a warm slice's pods to a consumer (returns the pod
  names and releases them from pool management) — the adoption protocol a
  virtual-kubelet/scheduler integration builds on; the north-star metric
  this exists for is slice-ready p50 (BASELINE.json).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

from kuberay_tpu.api.common import ObjectMeta, PodTemplateSpec, Serializable
from kuberay_tpu.api.tpucluster import TpuCluster, TpuClusterSpec, WorkerGroupSpec
from kuberay_tpu.builders.common import owner_reference
from kuberay_tpu.builders.pod import build_slice_pods
from kuberay_tpu.controlplane.events import EventRecorder
from kuberay_tpu.controlplane.store import (AlreadyExists, NotFound,
                                             ObjectStore)
from kuberay_tpu.obs.trace import NOOP_TRACER
from kuberay_tpu.topology import TopologyError
from kuberay_tpu.utils import constants as C
from kuberay_tpu.utils import features

KIND_WARM_POOL = "WarmSlicePool"
LABEL_WARM_POOL = "tpu.dev/warm-pool"
LABEL_WARM_CLAIMED = "tpu.dev/warm-claimed"


import dataclasses


@dataclasses.dataclass
class WarmSlicePoolSpec(Serializable):
    accelerator: str = "v5e"
    topology: str = "2x2"
    poolSize: int = 1
    template: PodTemplateSpec = dataclasses.field(default_factory=PodTemplateSpec)

    @classmethod
    def _nested_types(cls):
        return {"template": PodTemplateSpec}


class WarmSlicePoolController:
    KIND = KIND_WARM_POOL

    def __init__(self, store: ObjectStore,
                 recorder: Optional[EventRecorder] = None,
                 tracer=None):
        self.tracer = tracer or NOOP_TRACER
        self.store = store
        self.recorder = recorder or EventRecorder(store)
        # claim() serialization: two simultaneous preemption drains must
        # not adopt the same warm slice (one wins the warm claim, the
        # other falls back to a cold build).
        self._claim_lock = threading.Lock()

    def _pool_cluster(self, obj: Dict[str, Any]) -> TpuCluster:
        """A warm pool reuses the slice builders via a synthetic cluster
        shell (pure construction, nothing stored)."""
        spec = WarmSlicePoolSpec.from_dict(obj.get("spec", {}))
        group = WorkerGroupSpec(
            groupName="warm", accelerator=spec.accelerator,
            topology=spec.topology, replicas=spec.poolSize,
            maxReplicas=max(spec.poolSize, 1), template=spec.template)
        return TpuCluster(
            metadata=ObjectMeta(
                name=f"warmpool-{obj['metadata']['name']}",
                namespace=obj["metadata"].get("namespace", "default"),
                uid=obj["metadata"].get("uid", "")),
            spec=TpuClusterSpec(workerGroupSpecs=[group]))

    def _pool_pods(self, name: str, ns: str) -> Dict[int, List[dict]]:
        pods = self.store.list("Pod", ns, labels={LABEL_WARM_POOL: name})
        out: Dict[int, List[dict]] = {}
        for p in pods:
            if p["metadata"]["labels"].get(LABEL_WARM_CLAIMED):
                continue
            if p["metadata"].get("deletionTimestamp"):
                continue
            idx = int(p["metadata"]["labels"].get(C.LABEL_SLICE_INDEX, -1))
            out.setdefault(idx, []).append(p)
        return out

    def reconcile(self, name: str, namespace: str = "default") -> Optional[float]:
        # kuberay-lint: disable-next-line=reconcile-exception-escape -- FeatureGateError means a typo'd compile-time gate constant; crashing into backoff is the loudest correct behavior
        if not features.enabled("WarmSlicePools"):
            return None
        obj = self.store.try_get(self.KIND, name, namespace)
        if obj is None or obj["metadata"].get("deletionTimestamp"):
            return None
        try:
            shell = self._pool_cluster(obj)
            group = shell.spec.workerGroupSpecs[0]
            topo = group.slice_topology()
        except TopologyError as e:
            self.recorder.warning(obj, C.EVENT_INVALID_SPEC, str(e))
            return None

        spec = WarmSlicePoolSpec.from_dict(obj.get("spec", {}))
        slices = self._pool_pods(name, namespace)
        hosts = topo.num_hosts
        # Replace incomplete / unhealthy warm slices whole.
        for idx, plist in list(slices.items()):
            bad = (idx < 0 or len(plist) != hosts or any(
                p.get("status", {}).get("phase") in ("Failed", "Succeeded")
                for p in plist))
            if bad:
                for p in plist:
                    try:
                        self.store.delete("Pod", p["metadata"]["name"],
                                          namespace)
                    except NotFound:
                        pass
                del slices[idx]

        want = max(0, spec.poolSize)    # parsed spec: documented default 1
        have = len(slices)
        if have < want:
            used = set(slices)
            idx = 0
            while have < want:
                pods = build_slice_pods(shell, group, idx)
                # Claimed slices keep their (deterministic) pod names until
                # the adopter deletes them — an index is occupied while ANY
                # of its host names survives (partial teardown included).
                occupied = any(
                    self.store.try_get("Pod", p["metadata"]["name"],
                                       namespace) is not None
                    for p in pods)
                if idx in used or occupied:
                    idx += 1
                    continue
                for pod in pods:
                    pod["metadata"]["labels"][LABEL_WARM_POOL] = name
                    # Warm pods belong to the pool object, not a cluster.
                    pod["metadata"]["labels"].pop(C.LABEL_CLUSTER, None)
                    pod["metadata"]["ownerReferences"] = [owner_reference(
                        self.KIND, name, obj["metadata"].get("uid", ""))]
                    try:
                        self.store.create(pod)
                    except AlreadyExists:
                        pass
                self.recorder.normal(obj, "WarmedSlice",
                                     f"pre-provisioned warm slice {idx}")
                used.add(idx)
                have += 1
        elif have > want:
            for idx in sorted(slices, reverse=True)[:have - want]:
                for p in slices[idx]:
                    try:
                        self.store.delete("Pod", p["metadata"]["name"],
                                          namespace)
                    except NotFound:
                        pass

        # Status: warm/ready counts (one post-converge scan).
        final = self._pool_pods(name, namespace)
        ready = sum(1 for plist in final.values()
                    if len(plist) == hosts and all(
                        p.get("status", {}).get("phase") == "Running"
                        for p in plist))
        status = {"warmSlices": len(final),
                  "readySlices": ready, "hostsPerSlice": hosts}
        if obj.get("status") != status:
            obj["status"] = status
            # rv precondition = the reconcile-start snapshot already in
            # ``obj`` (no pre-write re-read): a foreign write in the
            # pass (leader-failover overlap) 409s and requeues instead
            # of clobbering (SURVEY §5.2).
            with self.tracer.span("store-write", kind=self.KIND, obj=name):
                try:
                    self.store.update_status(obj)
                except NotFound:
                    return None     # deleted mid-reconcile
        return None

    def claim(self, name: str, namespace: str = "default") -> Optional[List[str]]:
        """Claim one ready warm slice: marks its pods claimed and returns
        their names (the adopter takes over their lifecycle).  Only
        COMPLETE slices qualify — a partial slice has no ICI ring.

        Serialized: the lock plus a fresh per-pod re-read right before
        the claim stamp makes concurrent claimants (two preemption
        drains racing for a pool of one) resolve to exactly one winner;
        the loser gets None and cold-provisions instead."""
        obj = self.store.try_get(self.KIND, name, namespace)
        if obj is None:
            return None
        try:
            hosts = self._pool_cluster(obj).spec.workerGroupSpecs[0] \
                .slice_topology().num_hosts
        except TopologyError:
            return None
        with self._claim_lock:
            for idx, plist in sorted(self._pool_pods(name, namespace).items()):
                if idx < 0 or len(plist) != hosts:
                    continue
                # Re-read each pod under the lock: the listing above is a
                # snapshot, and a slice another claimant just stamped (or
                # a pod that failed/vanished meanwhile) must not be
                # handed out twice.
                fresh = [self.store.try_get("Pod", p["metadata"]["name"],
                                            namespace) for p in plist]
                if any(p is None
                       or p["metadata"]["labels"].get(LABEL_WARM_CLAIMED)
                       or p["metadata"].get("deletionTimestamp")
                       or p.get("status", {}).get("phase") != "Running"
                       for p in fresh):
                    continue
                names = []
                for p in fresh:
                    self.store.patch_labels(
                        "Pod", p["metadata"]["name"], namespace,
                        {LABEL_WARM_CLAIMED: "true"})
                    names.append(p["metadata"]["name"])
                return names
        return None
