"""Controller manager: watch -> work queue -> reconcile loops.

Level-triggered like controller-runtime (ref main.go:309-343 registration +
mgr.Start): store watch events map to (kind, namespace, name) keys, the
deduplicating per-key-serialized work queue
(:mod:`~kuberay_tpu.controlplane.workqueue`) feeds reconcilers,
requeue-after is honored.  Per-key serialization is what makes
``start(workers=N)`` safe: two workers never reconcile the same key
concurrently, and a key re-enqueued mid-flight coalesces into exactly
one more pass.

Two execution modes:
- ``run_until_idle()``: deterministic draining for tests and embedded use
  (the envtest analogue — no sleeping threads, reproducible order);
- ``start()/stop()``: background worker threads with timed requeues for
  live deployments.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Dict, List, Optional, Set, Tuple

from kuberay_tpu.controlplane.expectations import HEAD_GROUP, ScaleExpectations
from kuberay_tpu.controlplane.sharding import ShardedQueuePool, ShardSet
from kuberay_tpu.controlplane.store import (
    Conflict,
    Event,
    ExpiredError,
    ObjectStore,
)
from kuberay_tpu.obs.trace import NOOP_TRACER
from kuberay_tpu.utils import constants as C

Key = Tuple[str, str, str]  # (kind, namespace, name)

_LOG = logging.getLogger("kuberay_tpu.manager")


class Manager:
    def __init__(self, store: ObjectStore,
                 expectations: Optional[ScaleExpectations] = None,
                 clock=None, metrics=None, tracer=None, flight=None,
                 shards: int = 1, shard_of=None,
                 owned_shards: Optional[set] = None):
        self.store = store
        # ``clock`` is any object with ``.now() -> float`` (duck-typed so
        # controlplane does not depend on the sim package).  Timed
        # requeues schedule against it; the deterministic simulation
        # harness passes a virtual clock (kuberay_tpu.sim.clock) and
        # advances it to ``next_delayed_at()`` instead of sleeping.
        self._clock = clock
        self._now = clock.now if clock is not None else time.time
        # Optional ControlPlaneMetrics: counts requeue-causing Conflict /
        # Exception outcomes per kind (they were debug-log-only before)
        # and feeds the workqueue depth/latency series.
        self.metrics = metrics
        # Observability seams (kuberay_tpu.obs), both no-op-safe: the
        # tracer mints a TraceContext per reconcile-chain key as events
        # enter _on_event/enqueue and carries it through _pop/_process
        # (queue-wait + reconcile spans); the flight recorder keeps the
        # per-object ring of deliveries/conflicts/requeues.
        self.tracer = tracer or NOOP_TRACER
        self.flight = flight
        self.expectations = expectations or ScaleExpectations()
        self._reconcilers: Dict[str, Callable[[str, str], Optional[float]]] = {}
        # kinds whose owned objects (by label) map back to an owner kind:
        self._owned_maps: List[Callable[[Event], Optional[Key]]] = []
        # ``shards``: hash-partition reconcile keys across N worker
        # pools (sharding.py).  A key hashes to exactly one pool, so
        # per-key serialization holds globally; shards=1 is the
        # single-queue behavior (byte-identical processing order — the
        # chaos-replay contract).  ``shard_of`` overrides the hash for
        # tests/custom placement; ``owned_shards`` limits which shards
        # this process reconciles (per-shard lease mode — the others'
        # queues accumulate paused).
        self.shards = max(1, shards)
        self._pool = ShardedQueuePool(self.shards, now_fn=self._now,
                                      metrics=metrics,
                                      **({"shard_fn": shard_of}
                                         if shard_of is not None else {}))
        self._owned = ShardSet(self.shards, owned=owned_shards)
        for i in range(self.shards):
            if not self._owned.owns(i):
                self._pool.pause_shard(i)
        # High-water resourceVersion seen on the watch path (events and
        # bookmarks).  This is the informer's resume point: after a
        # disconnect, ``resume()`` replays only events past it — O(delta)
        # rejoin instead of relisting the world (docs/scaling.md).
        self._last_rv = 0
        self._threads: List[threading.Thread] = []
        self._stop = False
        self._stop_event = threading.Event()
        self._cancel_watch = store.watch(self._on_event)

    # -- registration ------------------------------------------------------

    def register(self, kind: str,
                 reconcile: Callable[[str, str], Optional[float]]):
        self._reconcilers[kind] = reconcile

    def map_owned(self, fn: Callable[[Event], Optional[Key]]):
        """Map events on owned objects (pods, services, jobs) to owner keys."""
        self._owned_maps.append(fn)

    # -- event plumbing ----------------------------------------------------

    def _on_event(self, ev: Event):
        if ev.type == Event.BOOKMARK:
            # Progress marker, not state: advance the resume point past
            # spans this informer saw nothing in (backlog-evicted or
            # filtered), so a later ``resume()`` stays O(delta).
            rv = ev.obj.get("metadata", {}).get("resourceVersion", 0)
            self._observe_rv(rv)
            return
        md = ev.obj.get("metadata", {})
        self._observe_rv(md.get("resourceVersion", 0))
        if self.flight is not None:
            self.flight.observe_event(ev)
        # Expectations observe pod churn (ref expectations consumption at
        # raycluster_controller.go:974,1035).
        if ev.kind == "Pod":
            labels = md.get("labels", {})
            cluster = labels.get(C.LABEL_CLUSTER)
            if cluster:
                group = (labels.get(C.LABEL_GROUP) or HEAD_GROUP)
                self.expectations.observe_pod_event(
                    md.get("namespace", "default"), cluster, group,
                    md.get("name", ""), ev.type)
        if ev.kind in self._reconcilers:
            self.enqueue((ev.kind, md.get("namespace", "default"),
                          md.get("name", "")))
        for fn in self._owned_maps:
            keys = fn(ev)
            if keys is None:
                continue
            # A mapper may fan one event out to several owners (e.g. a
            # ComputeTemplate change re-reconciles every referencing
            # cluster); a bare Key tuple means exactly one.
            if isinstance(keys, tuple):
                keys = [keys]
            for key in keys:
                if key[0] in self._reconcilers:
                    self.enqueue(key)

    def _observe_rv(self, rv) -> None:
        """Advance the watch high-water mark (single watch-delivery
        thread per dispatch mode; a stale concurrent write can only
        lower the resume point, never corrupt it — resume would just
        replay a few already-seen events, which level-triggered
        consumers absorb)."""
        if isinstance(rv, int) and rv > self._last_rv:
            self._last_rv = rv

    def enqueue(self, key: Key, after: float = 0.0):
        # Trace context attaches at scheduling time, delayed or not: the
        # eventual queue-wait span must cover requeue backoff (that wait
        # is real latency the slice-ready decomposition has to account
        # for).  queued() keeps the earliest pending instant on dedup.
        # The pool routes by the stable key hash — the ONLY enqueue
        # path (analysis rule shard-affinity), which is what keeps a
        # key in exactly one pool.
        self.tracer.queued(key, self._now(), delayed=after > 0)
        if after > 0:
            self._pool.add_after(key, after)
        else:
            self._pool.add(key)

    def shard_of(self, key: Key) -> int:
        return self._pool.shard_of(key)

    def _pop(self, block: bool) -> Optional[Key]:
        # Deterministic round-robin across pools (single-threaded
        # drain); worker threads use their pinned-shard get instead.
        del block
        return self._pool.get_any()

    # -- execution ---------------------------------------------------------

    def _process(self, key: Key):
        kind, ns, name = key
        fn = self._reconcilers.get(kind)
        if fn is None:
            self._pool.done(key)
            return
        self.tracer.dequeued(key, self._now())
        try:
            with self.tracer.reconcile(key, kind=kind, namespace=ns,
                                       name=name) as span:
                try:
                    requeue = fn(name, ns)
                except Conflict as e:
                    # Optimistic-concurrency loss (another writer won the rv
                    # race, e.g. leader-failover overlap): routine, not an
                    # error — requeue fast so the reconciler re-reads and
                    # recomputes from fresh state (SURVEY §5.2).
                    _LOG.debug("reconcile %s %s/%s conflicted, requeueing: %s",
                               kind, ns, name, e)
                    if self.metrics is not None:
                        self.metrics.reconcile_conflict(kind)
                    span.error(f"conflict: {e}")
                    if self.flight is not None:
                        self.flight.record(kind, ns, name, "conflict", str(e))
                    requeue = 0.05
                except Exception as e:   # reconcile errors requeue with backoff
                    _LOG.exception(
                        "reconcile %s %s/%s failed: %s", kind, ns, name, e)
                    if self.metrics is not None:
                        self.metrics.reconcile_error(kind)
                    span.error(f"{type(e).__name__}: {e}")
                    if self.flight is not None:
                        self.flight.record(kind, ns, name, "error",
                                           f"{type(e).__name__}: {e}")
                    requeue = 5.0
                if requeue:
                    span.set(requeue_after=requeue)
        finally:
            # Release the key BEFORE scheduling the requeue: done() may
            # immediately re-queue a dirty key, and an add_after racing
            # a still-processing key would coalesce into dirty and fire
            # too early.
            self._pool.done(key)
        if requeue:
            if self.flight is not None:
                self.flight.record(kind, ns, name, "requeue",
                                   f"after={requeue}")
            self.enqueue(key, after=requeue)

    def next_delayed_at(self) -> Optional[float]:
        """Earliest timed-requeue deadline (clock domain of ``clock.now``),
        or None when nothing is scheduled.  The sim harness advances its
        virtual clock exactly here, so backoffs fire at their true
        instants instead of being promoted en masse."""
        return self._pool.next_delayed_at()

    @property
    def _delayed(self) -> List[Tuple[float, Key]]:
        """Scheduled timed requeues as (deadline, key) — introspection
        for tests; the live heaps are the pools'."""
        return self._pool.delayed_items()

    def flush_delayed(self):
        """Promote ALL timed requeues immediately (tests: 'advance time')."""
        self._pool.flush_delayed()

    # -- informer resume (watch bookmark / 410 contract) -------------------

    @property
    def last_rv(self) -> int:
        """The watch high-water resourceVersion (events + bookmarks)."""
        return self._last_rv

    def disconnect_informer(self):
        """Detach from the store's watch stream (restart/failover seam —
        the sim's shard-restart scenario and the bookmark tests drive
        this; a real deployment gets here by crashing)."""
        self._cancel_watch()

    def reconnect_informer(self) -> Dict[str, object]:
        """Re-subscribe and catch up; returns the :meth:`resume` report."""
        self._cancel_watch = self.store.watch(self._on_event)
        return self.resume()

    def resume(self, rv: Optional[int] = None) -> Dict[str, object]:
        """Catch up after a watch gap, O(delta) when possible.

        Replays store events past ``rv`` (default: the last seen
        event/bookmark rv) through the normal event path.  When the
        span has fallen off the store's bounded backlog
        (:class:`ExpiredError` — the 410-Gone analogue), falls back to
        a **scoped relist**: only the registered kinds are listed and
        enqueued, never the whole store — owned objects (pods, …)
        re-derive from their owners' level-triggered reconciles, the
        same contract the startup resync uses.

        Returns ``{"mode": "delta"|"relist", "count": n, "rv": latest}``.
        """
        since = self._last_rv if rv is None else rv
        try:
            events, latest, _ = self.store.events_since(since, strict=True)
        except ExpiredError as e:
            n = self._relist_registered()
            self._observe_rv(e.latest)
            return {"mode": "relist", "count": n, "rv": self._last_rv}
        for _, ev in events:
            self._on_event(ev)
        self._observe_rv(latest)
        return {"mode": "delta", "count": len(events), "rv": self._last_rv}

    def _relist_registered(self, shard: Optional[int] = None) -> int:
        """Enqueue every object of every registered kind (optionally only
        keys hashing to ``shard``); returns keys enqueued."""
        n = 0
        for kind in sorted(self._reconcilers):
            try:
                objs = self.store.list(kind)
            except Exception:
                _LOG.exception("relist of %s failed; resync will retry",
                               kind)
                continue
            for o in objs:
                md = o.get("metadata", {})
                key = (kind, md.get("namespace", "default"),
                       md.get("name", ""))
                if shard is not None and self._pool.shard_of(key) != shard:
                    continue
                self.enqueue(key)
                n += 1
        return n

    # -- shard ownership (per-shard lease handoff) -------------------------

    def owned_shards(self) -> set:
        return self._owned.snapshot()

    def acquire_shard(self, shard: int) -> int:
        """Take ownership: resume the pool and relist this shard's slice
        of the registered kinds (level-triggered catch-up for events
        that accumulated while unowned).  Returns keys enqueued, -1 if
        already owned."""
        if not self._owned.add(shard):
            return -1
        self._pool.resume_shard(shard)
        return self._relist_registered(shard=shard)

    def release_shard(self, shard: int, drain_timeout: float = 5.0) -> bool:
        """Give up ownership: pause the pool (events keep accumulating,
        deduplicated) and wait for in-flight keys to finish, so a
        successor never overlaps our reconciles.  Returns False when the
        drain timed out (in-flight work still running)."""
        if not self._owned.discard(shard):
            return True
        self._pool.pause_shard(shard)
        return self._pool.drain_shard(shard, timeout=drain_timeout)

    def run_until_idle(self, max_iterations: int = 1000) -> int:
        """Drain the queue deterministically; returns iterations used.

        Due delayed items are promoted while draining; items scheduled in
        the future are NOT waited for — tests advance state and call again
        (or use ``flush_delayed``), like envtest's Eventually loops.
        """
        n = 0
        while n < max_iterations:
            key = self._pop(block=False)
            if key is None:
                return n
            self._process(key)
            n += 1
        return n

    def _resync_until_complete(self):
        """Enqueue every existing object of every registered kind — the
        informer initial-ADD pass, retried until each kind lists once.
        Makes operator restart resume free (pre-existing CRs reconcile
        without waiting for a change) and closes the remote-store startup
        window where an object predating watch sync would otherwise sit
        unreconciled (for remote stores this pass is the ONLY thing that
        reconciles pre-existing objects)."""
        pending = set(self._reconcilers)
        delay = 0.5
        while pending and not self._stop:
            still: Set[str] = set()
            for kind in pending:
                try:
                    objs = self.store.list(kind)
                except Exception:
                    still.add(kind)
                    continue
                for o in objs:
                    md = o.get("metadata", {})
                    self.enqueue((kind, md.get("namespace", "default"),
                                  md.get("name", "")))
            pending = still
            if pending:
                self._sleep(delay)
                delay = min(delay * 2, 30.0)

    def _sleep(self, seconds: float):
        """Retry backoff that honors the injected clock: a virtual clock
        (sim) advances instead of stalling the thread, and a real-time
        wait is interruptible by stop()."""
        if self._clock is not None and hasattr(self._clock, "sleep"):
            self._clock.sleep(seconds)
        else:
            self._stop_event.wait(seconds)

    def start(self, workers: int = 1):
        """Start ``workers`` reconcile threads PER SHARD, each pinned to
        its pool (a pinned worker can never pull a foreign shard's key,
        so shard ownership is enforced by construction).  With shards=1
        this is exactly the historical worker count."""
        self._stop = False
        self._stop_event.clear()
        self._pool.restart()
        threading.Thread(target=self._resync_until_complete, daemon=True,
                         name="manager-resync").start()
        for shard in range(self.shards):
            for i in range(workers):
                t = threading.Thread(
                    target=self._worker, args=(shard,), daemon=True,
                    name=(f"reconciler-{i}" if self.shards == 1
                          else f"reconciler-s{shard}-{i}"))
                t.start()
                self._threads.append(t)

    def _worker(self, shard: int):
        while not self._stop:
            key = self._pool.get(shard, block=True)
            if key is not None:
                self._process(key)

    def stop(self):
        self._stop = True
        self._stop_event.set()
        self._pool.shutdown()
        for t in self._threads:
            t.join(timeout=2.0)
        self._threads.clear()
        self._pool.restart()  # run_until_idle and a later start() still work


def owned_pod_mapper(ev: Event) -> Optional[Key]:
    """Pods carry the cluster label -> reconcile the owning TpuCluster
    (ref Owns(Pod) in SetupWithManager)."""
    if ev.kind != "Pod":
        return None
    md = ev.obj.get("metadata", {})
    cluster = md.get("labels", {}).get(C.LABEL_CLUSTER)
    if not cluster:
        return None
    return (C.KIND_CLUSTER, md.get("namespace", "default"), cluster)


def originated_from_mapper(owner_kind: str) -> Callable[[Event], Optional[Key]]:
    """Objects stamped with originated-from labels reconcile their creating
    CR (ref RayJob Owns(RayCluster/Job), RayService Owns(RayCluster):
    main.go:319 registration)."""
    def mapper(ev: Event) -> Optional[Key]:
        md = ev.obj.get("metadata", {})
        labels = md.get("labels", {})
        if labels.get(C.LABEL_ORIGINATED_FROM_CRD) != owner_kind:
            return None
        name = labels.get(C.LABEL_ORIGINATED_FROM_CR_NAME)
        if not name:
            return None
        return (owner_kind, md.get("namespace", "default"), name)
    return mapper
