"""TpuService reconciler: zero-downtime serving on TPU slices.

Mirrors the reference's RayService orchestration
(rayservice_controller.go): an *active* cluster serves traffic while a
*pending* cluster with the new spec warms up; promotion repoints the
stable serve/head services only when the pending cluster's serve apps are
healthy (reconcileServicesToReadyCluster :559).  Spec-hash comparison
(:1370/:1400) decides in-place update vs new-cluster upgrade — scale-only
changes (slice counts) never trigger a roll.

TPU twist ("roll slices without breaking ICI rings", SURVEY.md §7.7): a
serving slice is never partially replaced — upgrades only ever create
whole new clusters/slices behind the traffic switch; the incremental mode
(feature-gated) steps traffic weights while target capacity moves in
whole-slice quanta.
"""

from __future__ import annotations

import copy
import time
from typing import Callable, Dict, Optional

from kuberay_tpu.api.common import Condition, set_condition
from kuberay_tpu.api.tpucluster import ClusterState, TpuCluster
from kuberay_tpu.api.tpuservice import (
    ServiceClusterStatus,
    ServiceConditionType,
    ServiceStatusName,
    ServiceUpgradeType,
    TpuService,
    UpgradeState,
    UpgradeStatus,
)
from kuberay_tpu.builders.common import attach_cluster_auth, owner_reference
from kuberay_tpu.builders.service import build_serve_service
from kuberay_tpu.controlplane.events import EventRecorder
from kuberay_tpu.controlplane.store import (AlreadyExists, NotFound,
                                             ObjectStore)
from kuberay_tpu.controlplane.upgrade import (
    ABORT,
    PREWARM,
    PROMOTE,
    ROLLBACK,
    STEP,
    WAIT_DRAIN,
    UpgradeObservation,
    UpgradeOrchestrator,
    regression_note,
)
from kuberay_tpu.obs.profile import diff_profiles
from kuberay_tpu.obs.goodput import NOOP_TRANSITIONS
from kuberay_tpu.obs.trace import NOOP_TRACER
from kuberay_tpu.runtime.coordinator_client import CoordinatorError
from kuberay_tpu.utils import constants as C
from kuberay_tpu.utils import features
from kuberay_tpu.utils.names import serve_service_name, spec_hash_without_scale, truncate_name
from kuberay_tpu.utils.validation import validate_service, waive_create_only


def _fmt_secs(seconds: float) -> str:
    """Event-message formatting; a degraded group reports inf (act-now)."""
    return "DEGRADED (acting immediately)" if seconds == float("inf") \
        else f"{int(seconds)}s"


class TpuServiceController:
    KIND = C.KIND_SERVICE

    def __init__(self, store: ObjectStore,
                 recorder: Optional[EventRecorder] = None,
                 client_provider: Optional[Callable] = None,
                 tracer=None,
                 transitions=None,
                 clock=None,
                 upgrade_gate=None,
                 flight=None,
                 metrics_registry=None,
                 profiler=None,
                 audit=None):
        self.store = store
        self.recorder = recorder or EventRecorder(store)
        self.client_provider = client_provider
        # Span annotations — no-op by default, passed like ``metrics``.
        self.tracer = tracer or NOOP_TRACER
        # State-transition seam (obs.goodput): every serviceStatus write
        # routes through it (rule phase-transition-recorded).
        self.transitions = transitions or NOOP_TRANSITIONS
        # Injectable clock, same idiom as the other controllers: every
        # threshold/ramp/retirement timer reads this, so upgrade
        # scenarios replay virtual-clock exact under the sim.
        self._now: Callable[[], float] = (clock.now if clock is not None
                                          else time.time)
        # Burn-rate gate over the green fleet (controlplane.upgrade
        # .BurnRateGate or anything with .verdict(backend) / .forget);
        # None = vacuously healthy, keeping the open-loop semantics.
        self.upgrade_gate = upgrade_gate
        # Flight ring (obs.FlightRecorder): rollback forensics land next
        # to the watch/event history of the service.
        self.flight = flight
        # MetricsRegistry for the tpu_upgrade_* families; optional.
        self._metrics = metrics_registry
        # obs.RequestProfiler (fed by the gateway): the blue-vs-green
        # critical-path diff source for promote/rollback audits.
        self.profiler = profiler
        # DecisionAudit ring: ramp verdicts land next to the scale
        # decisions at /debug/autoscaler, trace diff attached.
        self.audit = audit
        self._orchestrator = UpgradeOrchestrator()
        # service name -> time the blue drain was requested (bounds
        # WAIT_DRAIN by drainTimeoutSeconds).
        self._drain_started: Dict[str, float] = {}
        # serve config cache per cluster (ref cacheServeConfig): avoids
        # re-PUTting an unchanged config every pass.
        self._submitted: Dict[str, str] = {}
        # cluster name -> first time its serve apps were observed unhealthy
        # (drives the serviceUnhealthySecondThreshold /
        # deploymentUnhealthySecondThreshold timers, ref rayservice spec).
        self._unhealthy_since: Dict[str, float] = {}

    # ------------------------------------------------------------------

    def reconcile(self, name: str, namespace: str = "default") -> Optional[float]:
        raw = self.store.try_get(self.KIND, name, namespace)
        if raw is None:
            return None
        svc = TpuService.from_dict(raw)
        # Snapshot status for the update throttle + the snapshot rv
        # contract: writes in this pass carry the reconcile-start
        # resourceVersion (bumped only by our own writes' return
        # values), so a foreign write 409s instead of being clobbered.
        svc._orig_status = copy.deepcopy(raw.get("status", {}))

        if svc.metadata.deletionTimestamp:
            return self._reconcile_deletion(svc)

        # kuberay-lint: disable-next-line=reconcile-exception-escape -- FeatureGateError means a typo'd compile-time gate constant; crashing into backoff is the loudest correct behavior
        errs = waive_create_only(validate_service(svc))
        if errs:
            self.recorder.warning(raw, C.EVENT_INVALID_SPEC, "; ".join(errs))
            return None

        if C.FINALIZER_SERVICE not in svc.metadata.finalizers:
            out = self.store.add_finalizer(self.KIND, name, namespace,
                                           C.FINALIZER_SERVICE,
                                           rv=svc.metadata.resourceVersion)
            svc.metadata.finalizers.append(C.FINALIZER_SERVICE)
            svc.metadata.resourceVersion = \
                out["metadata"]["resourceVersion"]

        if svc.spec.suspend:
            return self._reconcile_suspend(svc)

        requeue = self._reconcile_clusters(svc)
        self._reconcile_serve_config(svc)
        self._reconcile_unhealthy_thresholds(svc)
        r2 = self._reconcile_promotion(svc)
        self._reconcile_stable_services(svc)
        self._update_status(svc)
        candidates = [r for r in (requeue, r2) if r]
        return min(candidates) if candidates else 2.0

    def _reconcile_unhealthy_thresholds(self, svc: TpuService):
        """Self-healing on persistent serve unhealthiness.

        - pending stuck beyond deploymentUnhealthySecondThreshold: abandon
          it (a fresh attempt is prepared on the next pass);
        - active unhealthy beyond serviceUnhealthySecondThreshold: prepare
          a same-spec replacement cluster that takes over via the normal
          promotion path (whole-cluster repair — slices are never patched
          in place).
        """
        now = self._now()
        st = svc.status

        def degraded_apps(cs):
            if cs is None:
                return []
            return [a for a in cs.applications
                    if a.status == ServiceStatusName.DEGRADED]

        # ServeGroupDegraded condition: a DEGRADED app means the slice's
        # lockstep group lost a member — it can never heal in place, so
        # the condition both surfaces the failure and makes the
        # unhealthy clock fire IMMEDIATELY (no threshold wait).
        all_degraded = (degraded_apps(st.activeServiceStatus)
                        + degraded_apps(st.pendingServiceStatus))
        if all_degraded:
            msg = "; ".join(f"{a.name}: {a.message}" for a in all_degraded)
            set_condition(st.conditions, Condition(
                type=ServiceConditionType.SERVE_GROUP_DEGRADED,
                status="True", reason="ServeGroupFailure", message=msg))
        else:
            set_condition(st.conditions, Condition(
                type=ServiceConditionType.SERVE_GROUP_DEGRADED,
                status="False", reason="GroupsHealthy"))

        def track(cs) -> float:
            """Returns seconds-unhealthy for the cluster (0 when healthy).

            The clock starts only once app status has actually been
            observed — a cluster still provisioning (no serve config
            submitted yet) is pending, not unhealthy."""
            if cs is None:
                return 0.0
            if self._serve_ready(cs):
                self._unhealthy_since.pop(cs.clusterName, None)
                return 0.0
            if not cs.applications:
                return 0.0
            if degraded_apps(cs):
                return float("inf")         # unrecoverable: act now
            first = self._unhealthy_since.setdefault(cs.clusterName, now)
            return now - first

        pending_bad = track(st.pendingServiceStatus)
        if st.pendingServiceStatus is not None and \
                pending_bad > svc.spec.deploymentUnhealthySecondThreshold:
            self.recorder.warning(
                svc.to_dict(), "PendingUnhealthy",
                f"pending cluster {st.pendingServiceStatus.clusterName} not "
                f"serving after {_fmt_secs(pending_bad)}; recreating")
            self._unhealthy_since.pop(st.pendingServiceStatus.clusterName, None)
            self._abandon_pending(svc)
            return

        active_bad = track(st.activeServiceStatus)
        if st.activeServiceStatus is not None and \
                st.pendingServiceStatus is None and \
                svc.spec.upgradeStrategy != ServiceUpgradeType.NONE and \
                active_bad > svc.spec.serviceUnhealthySecondThreshold:
            # Fresh, never-used name: reusing a name would silently adopt a
            # still-retiring (possibly annotated-for-deletion) cluster.
            base = f"{svc.metadata.name}-cluster-{svc.metadata.generation}-heal"
            cname = truncate_name(base)
            n = 2
            while self.store.try_get(C.KIND_CLUSTER, cname,
                                     svc.metadata.namespace) is not None or \
                    cname == st.activeServiceStatus.clusterName:
                cname = truncate_name(f"{base}{n}")
                n += 1
            self.recorder.warning(
                svc.to_dict(), "ActiveUnhealthy",
                f"active cluster {st.activeServiceStatus.clusterName} "
                f"unhealthy for {_fmt_secs(active_bad)}; preparing "
                f"replacement {cname}")
            self._unhealthy_since.pop(st.activeServiceStatus.clusterName, None)
            self._create_cluster(svc, cname)
            st.pendingServiceStatus = ServiceClusterStatus(
                clusterName=cname,
                specHash=spec_hash_without_scale(svc.spec.clusterSpec.to_dict()))
            set_condition(st.conditions, Condition(
                type=ServiceConditionType.UPGRADE_IN_PROGRESS, status="True",
                reason="UnhealthyActive"))

    # ------------------------------------------------------------------
    # cluster pair management (ref reconcileRayCluster :1191)
    # ------------------------------------------------------------------

    def _cluster_name(self, svc: TpuService, generation: int) -> str:
        return truncate_name(f"{svc.metadata.name}-cluster-{generation}")

    def _get_cluster(self, svc: TpuService, cname: str) -> Optional[TpuCluster]:
        raw = self.store.try_get(C.KIND_CLUSTER, cname, svc.metadata.namespace)
        return TpuCluster.from_dict(raw) if raw else None

    def _create_cluster(self, svc: TpuService, cname: str,
                        wave_slices: int = 0):
        spec = svc.spec.clusterSpec.to_dict()
        if wave_slices > 0:
            # First ICI-atomic wave: the green cluster starts with at
            # most ``waveSlices`` slices per group; _stage_waves raises
            # replicas toward the spec as whole rings come Ready.
            for g in spec.get("workerGroupSpecs", []):
                cap = min(int(g.get("replicas", 0) or 0), wave_slices)
                g["replicas"] = cap
                if int(g.get("minReplicas", 0) or 0) > cap:
                    g["minReplicas"] = cap
        obj = {
            "apiVersion": C.API_VERSION,
            "kind": C.KIND_CLUSTER,
            "metadata": {
                "name": cname,
                "namespace": svc.metadata.namespace,
                "labels": {
                    C.LABEL_ORIGINATED_FROM_CR_NAME: svc.metadata.name,
                    C.LABEL_ORIGINATED_FROM_CRD: C.KIND_SERVICE,
                },
                "ownerReferences": [owner_reference(
                    C.KIND_SERVICE, svc.metadata.name, svc.metadata.uid)],
            },
            "spec": spec,
            "status": {},
        }
        try:
            self.store.create(obj)
            self.recorder.normal(svc.to_dict(), "CreatedCluster",
                                 f"created cluster {cname}")
        except AlreadyExists:
            pass

    def _reconcile_clusters(self, svc: TpuService) -> Optional[float]:
        desired_hash = spec_hash_without_scale(svc.spec.clusterSpec.to_dict())
        st = svc.status
        active = (self._get_cluster(svc, st.activeServiceStatus.clusterName)
                  if st.activeServiceStatus else None)
        pending = (self._get_cluster(svc, st.pendingServiceStatus.clusterName)
                   if st.pendingServiceStatus else None)

        if active is None and pending is None:
            # First rollout: everything starts as pending; promotion makes
            # it active once serving.
            cname = self._cluster_name(svc, svc.metadata.generation)
            self._create_cluster(svc, cname)
            st.pendingServiceStatus = ServiceClusterStatus(
                clusterName=cname, specHash=desired_hash)
            return 1.0

        if active is not None and st.activeServiceStatus is not None:
            if st.activeServiceStatus.specHash == desired_hash:
                # In-place: scale-only changes flow through (ref
                # isClusterSpecHashEqual -> update replicas).
                self._sync_scale_fields(svc, active)
                # A pending cluster from an ABANDONED upgrade (stale hash)
                # is rolled back (ref reconcileRollbackState :2321); a
                # same-hash pending is a legitimate self-heal replacement
                # and must survive to promotion.
                if pending is not None and \
                        st.pendingServiceStatus.specHash != desired_hash:
                    self._abandon_pending(svc)
                return None
            if svc.spec.upgradeStrategy == ServiceUpgradeType.NONE:
                return None
            # Abort latch: a spec hash whose gated ramp exhausted its
            # rollback budget is not retried — the operator must change
            # the spec (or revert) to clear it.
            if st.upgrade is not None and \
                    st.upgrade.abortedSpecHash == desired_hash:
                return None
            # Spec changed: prepare a pending cluster with the new spec
            # (ref shouldPrepareNewCluster :1400).
            if pending is None or st.pendingServiceStatus.specHash != desired_hash:
                if pending is not None:
                    self._abandon_pending(svc)
                cname = self._cluster_name(svc, svc.metadata.generation)
                if cname == st.activeServiceStatus.clusterName:
                    cname = truncate_name(
                        f"{svc.metadata.name}-cluster-{svc.metadata.generation}-r")
                wave = 0
                if svc.spec.upgradeStrategy == ServiceUpgradeType.INCREMENTAL \
                        and features.enabled("TpuServiceIncrementalUpgrade") \
                        and svc.spec.upgradeOptions is not None:
                    wave = svc.spec.upgradeOptions.waveSlices
                self._create_cluster(svc, cname, wave_slices=wave)
                st.pendingServiceStatus = ServiceClusterStatus(
                    clusterName=cname, specHash=desired_hash)
                # Fresh ramp, fresh budgets: the new pending starts with
                # a clean rollback count and hold clock.
                st.upgrade = None
                self._drain_started.pop(svc.metadata.name, None)
                set_condition(svc.status.conditions, Condition(
                    type=ServiceConditionType.UPGRADE_IN_PROGRESS,
                    status="True", reason="SpecChanged"))
                return 1.0
        return None

    def _sync_scale_fields(self, svc: TpuService, cluster: TpuCluster):
        obj = self.store.try_get(C.KIND_CLUSTER, cluster.metadata.name,
                                 svc.metadata.namespace)
        if obj is None:
            return
        desired_groups = {g.groupName: g for g in svc.spec.clusterSpec.workerGroupSpecs}
        changed = False
        for g in obj["spec"].get("workerGroupSpecs", []):
            want = desired_groups.get(g.get("groupName"))
            if want is None:
                continue
            for field, val in (("replicas", want.replicas),
                               ("minReplicas", want.minReplicas),
                               ("maxReplicas", want.maxReplicas)):
                if g.get(field) != val:
                    g[field] = val
                    changed = True
        if changed:
            self.store.update(obj)

    def _abandon_pending(self, svc: TpuService):
        st = svc.status
        if st.pendingServiceStatus is None:
            return
        cname = st.pendingServiceStatus.clusterName
        try:
            self.store.delete(C.KIND_CLUSTER, cname, svc.metadata.namespace)
        except NotFound:
            pass
        self._submitted.pop(cname, None)
        self._unhealthy_since.pop(cname, None)
        st.pendingServiceStatus = None
        set_condition(svc.status.conditions, Condition(
            type=ServiceConditionType.ROLLING_BACK, status="True",
            reason="PendingAbandoned"))

    # ------------------------------------------------------------------
    # serve config (ref updateServeDeployment :1563 + getAndCheckServeStatus)
    # ------------------------------------------------------------------

    def _client_for(self, svc: TpuService, cluster: TpuCluster):
        if self.client_provider is None:
            return None
        client = self.client_provider(cluster.metadata.name,
                                      cluster.status.to_dict())
        attach_cluster_auth(client, self.store, cluster)
        return client

    @staticmethod
    def _effective_serve_config(svc: TpuService) -> dict:
        """serveConfig with ``spec.kvTiers`` folded into every
        application block (docs/kv-tiers.md): the engine-side tier
        sizes ride the same serveConfig-to-engine wire as any other
        app knob, so replicas mount the hierarchy at boot.  A per-app
        explicit ``host_blocks``/``spill_blocks`` wins over the
        service-wide default."""
        cfg = svc.spec.serveConfig
        kv = svc.spec.kvTiers
        if kv is None or not (kv.hostBlocks or kv.spillBlocks):
            return cfg
        cfg = copy.deepcopy(cfg)
        for app in cfg.get("applications", []) or []:
            if not isinstance(app, dict):
                continue
            app.setdefault("host_blocks", kv.hostBlocks)
            app.setdefault("spill_blocks", kv.spillBlocks)
        return cfg

    def _reconcile_serve_config(self, svc: TpuService):
        st = svc.status
        serve_cfg = self._effective_serve_config(svc)
        # Hash the EFFECTIVE config: flipping kvTiers must re-push even
        # though spec.serveConfig itself is unchanged.
        cfg_hash = spec_hash_without_scale({"serve": serve_cfg})
        for cs in (st.pendingServiceStatus, st.activeServiceStatus):
            if cs is None:
                continue
            cluster = self._get_cluster(svc, cs.clusterName)
            if cluster is None or cluster.status.state != ClusterState.READY:
                continue
            client = self._client_for(svc, cluster)
            if client is None:
                continue
            if self._submitted.get(cs.clusterName) != cfg_hash:
                try:
                    client.update_serve_apps(serve_cfg)
                    self._submitted[cs.clusterName] = cfg_hash
                except CoordinatorError as e:
                    self.tracer.record_error("coordinator",
                                             f"serve config push failed: {e}")
                    self.recorder.warning(svc.to_dict(), "ServeConfigFailed",
                                          str(e))
                    continue
            # Poll app health.  A transient poll failure keeps the previous
            # observation — one blip must not flip a healthy service to
            # not-ready and churn conditions.
            try:
                apps = client.get_serve_apps()
            except CoordinatorError:
                continue
            from kuberay_tpu.api.tpuservice import ServeApplicationStatus
            prev = {a.name: a for a in cs.applications}
            cs.applications = []
            for app_name, info in sorted(apps.items()):
                status = info.get("status", "NOT_STARTED")
                message = info.get("message", "")
                old = prev.get(app_name)
                # Only move the timestamp on actual transitions — a fresh
                # timestamp every poll would make status updates churn and
                # re-trigger reconciles forever.
                if old and old.status == status and old.message == message:
                    ts = old.lastUpdateTime
                else:
                    ts = self._now()
                cs.applications.append(ServeApplicationStatus(
                    name=app_name, status=status, message=message,
                    lastUpdateTime=ts))

    def _serve_ready(self, cs: Optional[ServiceClusterStatus]) -> bool:
        return bool(cs and cs.applications and
                    all(a.status == ServiceStatusName.RUNNING
                        for a in cs.applications))

    # ------------------------------------------------------------------
    # promotion + traffic (ref :286-301, :559; incremental :976-1190)
    # ------------------------------------------------------------------

    def _reconcile_promotion(self, svc: TpuService) -> Optional[float]:
        st = svc.status
        if st.pendingServiceStatus is None:
            return None
        if not self._serve_ready(st.pendingServiceStatus):
            return 2.0

        incremental = (
            svc.spec.upgradeStrategy == ServiceUpgradeType.INCREMENTAL
            and features.enabled("TpuServiceIncrementalUpgrade")
            and st.activeServiceStatus is not None)
        if incremental:
            return self._reconcile_gated_upgrade(svc)
        # Full promotion.
        self._promote(svc)
        return None

    # ------------------------------------------------------------------
    # burn-rate-gated incremental ramp (controlplane.upgrade)
    # ------------------------------------------------------------------

    def _upgrade_status(self, svc: TpuService) -> UpgradeStatus:
        if svc.status.upgrade is None:
            svc.status.upgrade = UpgradeStatus(state=UpgradeState.RAMPING)
        return svc.status.upgrade

    def _whole_rings(self, svc: TpuService, cname: str) -> Dict[str, int]:
        """Group name -> count of slices whose whole multi-host ICI ring
        is Running in ``cname``.  A slice with any member missing or not
        yet Running is not a ring — it carries no weight."""
        want_hosts = {g.groupName: g.num_hosts
                      for g in svc.spec.clusterSpec.workerGroupSpecs}
        slices: Dict[tuple, list] = {}
        for p in self.store.list(
                "Pod", svc.metadata.namespace,
                labels={C.LABEL_CLUSTER: cname,
                        C.LABEL_NODE_TYPE: C.NODE_TYPE_WORKER}):
            lbl = p["metadata"].get("labels", {})
            key = (lbl.get(C.LABEL_GROUP), lbl.get(C.LABEL_SLICE_NAME))
            slices.setdefault(key, []).append(p)
        ready = {g: 0 for g in want_hosts}
        for (group, _sname), ps in slices.items():
            want = want_hosts.get(group, 0)
            if want > 0 and len(ps) >= want and all(
                    p.get("status", {}).get("phase") == "Running"
                    for p in ps):
                ready[group] += 1
        return ready

    def _ring_progress(self, svc: TpuService, cname: str):
        """(ready, desired) whole-ring slice counts for the green
        cluster, measured against the FULL desired spec — weight never
        outruns ready/desired even while waves are still staging."""
        desired = sum(int(g.replicas)
                      for g in svc.spec.clusterSpec.workerGroupSpecs)
        ready = sum(self._whole_rings(svc, cname).values())
        return ready, desired

    def _stage_waves(self, svc: TpuService, wave: int):
        """ICI-atomic waves: the pending cluster's replicas climb
        ``wave`` slices past the currently-whole rings, so green
        capacity provisions slice-by-slice instead of all at once."""
        cname = svc.status.pendingServiceStatus.clusterName
        obj = self.store.try_get(C.KIND_CLUSTER, cname,
                                 svc.metadata.namespace)
        if obj is None:
            return
        ready = self._whole_rings(svc, cname)
        desired = {g.groupName: int(g.replicas)
                   for g in svc.spec.clusterSpec.workerGroupSpecs}
        changed = False
        for g in obj["spec"].get("workerGroupSpecs", []):
            gname = g.get("groupName")
            target = min(desired.get(gname, 0),
                         ready.get(gname, 0) + wave)
            if target > int(g.get("replicas", 0) or 0):
                g["replicas"] = target
                changed = True
        if changed:
            self.store.update(obj)

    def _route_acks(self, svc: TpuService) -> Dict:
        """Gateway handshake state carried on the TrafficRoute's status
        (store.ensure converges spec only, so acks survive our writes)."""
        raw = self.store.try_get(
            "TrafficRoute", truncate_name(f"{svc.metadata.name}-route"),
            svc.metadata.namespace)
        return (raw or {}).get("status") or {}

    def _reconcile_gated_upgrade(self, svc: TpuService) -> Optional[float]:
        st = svc.status
        cs = st.pendingServiceStatus
        opts = svc.spec.upgradeOptions
        step = opts.stepSizePercent if opts else 10
        interval = opts.intervalSeconds if opts else 30
        max_rollbacks = opts.maxRollbacks if opts else 2
        hold_s = opts.holdSeconds if opts else 60
        wave = opts.waveSlices if opts else 0
        prewarm_n = opts.prewarmPrompts if opts else 0
        drain_timeout = opts.drainTimeoutSeconds if opts else 0

        up = self._upgrade_status(svc)
        if wave > 0:
            self._stage_waves(svc, wave)
        ready, desired = self._ring_progress(svc, cs.clusterName)
        up.readySlices, up.desiredSlices = ready, desired

        green_svc = serve_service_name(cs.clusterName)
        blue_svc = (serve_service_name(st.activeServiceStatus.clusterName)
                    if st.activeServiceStatus else "")
        if self.upgrade_gate is not None:
            healthy, alert = self.upgrade_gate.verdict(green_svc)
        else:
            healthy, alert = True, None

        acks = self._route_acks(svc)
        drain_requested = (drain_timeout > 0
                           and st.activeServiceStatus is not None)
        now = self._now()
        if drain_requested and cs.trafficWeightPercent >= 100:
            self._drain_started.setdefault(svc.metadata.name, now)
        obs = UpgradeObservation(
            now=now,
            green_weight=cs.trafficWeightPercent,
            step_size=step,
            interval_s=float(interval),
            last_step_time=st.lastUpgradeStepTime,
            ready_slices=ready,
            desired_slices=desired,
            gate_healthy=healthy,
            firing_alert=alert,
            rollbacks=up.rollbacks,
            max_rollbacks=max_rollbacks,
            hold_seconds=float(hold_s),
            last_rollback_time=up.lastRollbackTime,
            prewarm_requested=prewarm_n > 0,
            prewarm_done=green_svc in (acks.get("prewarmed") or {}),
            drain_requested=drain_requested,
            drain_done=blue_svc in (acks.get("drained") or {}),
            drain_started_at=self._drain_started.get(svc.metadata.name, 0.0),
            drain_timeout_s=float(drain_timeout))
        decision = self._orchestrator.decide(obs)
        return self._apply_upgrade_decision(svc, decision, obs, green_svc)

    def _upgrade_profile_diff(self, svc: TpuService,
                              green_svc: str) -> Optional[Dict]:
        """Old-build vs new-build serve profile diff: the blue
        backend's critical-path profile as baseline, the green
        candidate's as candidate.  None without a profiler or an
        active (blue) fleet.  min_count=3: a ramp sees minutes of
        sampled traffic, not a bench's thousands of requests."""
        if self.profiler is None:
            return None
        st = svc.status
        if st.activeServiceStatus is None:
            return None
        blue_svc = serve_service_name(st.activeServiceStatus.clusterName)
        if not blue_svc or blue_svc == green_svc:
            return None
        baseline = self.profiler.snapshot(backend=blue_svc)
        candidate = self.profiler.snapshot(backend=green_svc)
        return diff_profiles(baseline, candidate, min_count=3)

    def _audit_upgrade(self, svc: TpuService, action: str,
                       green_weight: int, reason: str,
                       alert=None, profile_diff=None) -> None:
        if self.audit is None:
            return
        self.audit.record_upgrade(
            svc.metadata.namespace, svc.metadata.name, action,
            green_weight=green_weight, reason=reason, alert=alert,
            profile_diff=profile_diff)

    def _apply_upgrade_decision(self, svc: TpuService, decision, obs,
                                green_svc: str) -> Optional[float]:
        """THE weight-write seam: every trafficWeightPercent mutation of
        the incremental ramp happens here (or in _promote), downstream
        of one orchestrator decision — analysis rule
        traffic-weight-through-gate holds the controller to it."""
        st = svc.status
        up = st.upgrade
        cs = st.pendingServiceStatus
        name = svc.metadata.name
        ns = svc.metadata.namespace

        if decision.action == ABORT:
            pdiff = self._upgrade_profile_diff(svc, green_svc)
            self._audit_upgrade(svc, "abort", 0, decision.reason,
                                alert=decision.alert, profile_diff=pdiff)
            up.state = UpgradeState.ABORTED
            up.lastAlert = dict(decision.alert or {})
            up.abortedSpecHash = cs.specHash
            if st.activeServiceStatus is not None:
                st.activeServiceStatus.trafficWeightPercent = 100
            self._drain_started.pop(name, None)
            self._count_step(name, "abort")
            self._record_weights(svc)
            self.recorder.warning(
                svc.to_dict(), "UpgradeAborted",
                f"abandoning {cs.clusterName}: {decision.reason}")
            if self.flight is not None:
                self.flight.record(
                    self.KIND, ns, name, "upgrade", detail=decision.reason,
                    action=decision.action,
                    alert=(decision.alert or {}).get("name", ""))
            if self.upgrade_gate is not None:
                self.upgrade_gate.forget(green_svc)
            self._abandon_pending(svc)
            try:
                self.store.delete("TrafficRoute",
                                  truncate_name(f"{name}-route"), ns)
            except NotFound:
                pass
            return None

        if decision.action == ROLLBACK:
            # Diff BEFORE touching weights: the profile is a read-only
            # snapshot, but the audit should reflect what the ramp saw
            # when it decided.
            pdiff = self._upgrade_profile_diff(svc, green_svc)
            note = regression_note(pdiff)
            self._audit_upgrade(svc, "rollback", 0, decision.reason,
                                alert=decision.alert, profile_diff=pdiff)
            cs.trafficWeightPercent = 0
            if st.activeServiceStatus is not None:
                st.activeServiceStatus.trafficWeightPercent = 100
            up.state = UpgradeState.ROLLED_BACK
            up.rollbacks += 1
            up.lastRollbackTime = self._now()
            up.lastAlert = dict(decision.alert or {})
            st.lastUpgradeStepTime = self._now()
            self._drain_started.pop(name, None)
            self._reconcile_weighted_services(svc)
            if self._metrics is not None:
                self._metrics.inc("tpu_upgrade_rollbacks_total",
                                  {"service": name})
            self._count_step(name, "down")
            self._record_weights(svc)
            self.recorder.warning(
                svc.to_dict(), "UpgradeRolledBack",
                f"green weight snapped to 0: {decision.reason}"
                + (f"; {note}" if note else ""))
            if self.flight is not None:
                self.flight.record(
                    self.KIND, ns, name, "upgrade", detail=decision.reason,
                    action=decision.action,
                    alert=(decision.alert or {}).get("name", ""))
            return decision.requeue_after

        if decision.action == PROMOTE:
            self._finish_gated(svc, green_svc)
            return None

        if decision.action == STEP:
            prev = cs.trafficWeightPercent
            cs.trafficWeightPercent = decision.green_weight
            if st.activeServiceStatus is not None:
                st.activeServiceStatus.trafficWeightPercent = \
                    100 - decision.green_weight
            st.lastUpgradeStepTime = self._now()
            up.state = UpgradeState.RAMPING
            self._count_step(
                name, "up" if decision.green_weight >= prev else "down")
            self._record_weights(svc)
            if decision.green_weight >= 100:
                if obs.drain_requested and not obs.drain_done:
                    # Hold promotion until blue acks an empty in-flight
                    # set (or the drain timeout expires).
                    self._drain_started.setdefault(name, self._now())
                    up.state = UpgradeState.DRAINING
                    self._reconcile_weighted_services(svc)
                    return 0.5
                # Open-loop parity: a step that lands on 100 with no
                # drain requested promotes in the same reconcile — the
                # route still sees the terminal weights first.
                self._reconcile_weighted_services(svc)
                self._finish_gated(svc, green_svc)
                return None
            self._reconcile_weighted_services(svc)
            return float(obs.interval_s)

        # PREWARM / WAIT_DRAIN / HOLD / WAIT_RING: no weight change,
        # surface the phase and keep the route (with its prewarm/drain
        # flags) converged so the gateway sees the request.
        if decision.action == PREWARM:
            up.state = UpgradeState.PREWARMING
        elif decision.action == WAIT_DRAIN:
            self._drain_started.setdefault(name, self._now())
            up.state = UpgradeState.DRAINING
        elif cs.trafficWeightPercent == 0 and up.rollbacks > 0:
            up.state = (UpgradeState.ROLLED_BACK
                        if not obs.gate_healthy else UpgradeState.HOLDING)
        else:
            up.state = UpgradeState.RAMPING
        self._reconcile_weighted_services(svc)
        return decision.requeue_after

    def _finish_gated(self, svc: TpuService, green_svc: str):
        name = svc.metadata.name
        # Snapshot the blue-vs-green diff before _promote flips the
        # active fleet; a clean candidate audits an empty regression
        # list — the "did it help" half of the ramp's paper trail.
        pdiff = self._upgrade_profile_diff(svc, green_svc)
        self._audit_upgrade(svc, "promote", 100, "ramp complete",
                            profile_diff=pdiff)
        self._promote(svc)
        self.transitions.record(self.KIND, svc.metadata.namespace, name,
                                UpgradeState.PROMOTED,
                                old_state=svc.status.upgrade.state)
        svc.status.upgrade.state = UpgradeState.PROMOTED
        self._drain_started.pop(name, None)
        if self.upgrade_gate is not None:
            self.upgrade_gate.forget(green_svc)
        self._count_step(name, "promote")
        self._record_weights(svc)

    def _count_step(self, service: str, direction: str):
        if self._metrics is not None:
            self._metrics.inc("tpu_upgrade_steps_total",
                              {"service": service, "direction": direction})

    def _record_weights(self, svc: TpuService):
        if self._metrics is None:
            return
        st = svc.status
        green = (st.pendingServiceStatus.trafficWeightPercent
                 if st.pendingServiceStatus else 0)
        blue = (st.activeServiceStatus.trafficWeightPercent
                if st.activeServiceStatus else 0)
        self._metrics.set_gauge("tpu_upgrade_weight_percent", float(green),
                                {"service": svc.metadata.name,
                                 "role": "green"})
        self._metrics.set_gauge("tpu_upgrade_weight_percent", float(blue),
                                {"service": svc.metadata.name,
                                 "role": "blue"})

    def _promote(self, svc: TpuService):
        st = svc.status
        old = st.activeServiceStatus
        st.activeServiceStatus = st.pendingServiceStatus
        st.activeServiceStatus.trafficWeightPercent = 100
        st.pendingServiceStatus = None
        # Steady state needs no weighted route; per-cluster serve Services
        # GC with their clusters, the route object is ours to clean up.
        try:
            self.store.delete("TrafficRoute",
                              truncate_name(f"{svc.metadata.name}-route"),
                              svc.metadata.namespace)
        except NotFound:
            pass
        set_condition(st.conditions, Condition(
            type=ServiceConditionType.UPGRADE_IN_PROGRESS, status="False",
            reason="Promoted"))
        self.recorder.normal(svc.to_dict(), "Promoted",
                             f"cluster {st.activeServiceStatus.clusterName} "
                             "now serving")
        if old is not None and old.clusterName != st.activeServiceStatus.clusterName:
            # Retire the old cluster after the grace delay (ref
            # cleanUpRayClusterInstance :1247).
            self._schedule_retirement(svc, old.clusterName)
            self._submitted.pop(old.clusterName, None)

    def _schedule_retirement(self, svc: TpuService, cname: str):
        obj = self.store.try_get(C.KIND_CLUSTER, cname, svc.metadata.namespace)
        if obj is None:
            return
        retire_at = self._now() + svc.spec.clusterDeletionDelaySeconds
        obj["metadata"].setdefault("annotations", {})[
            "tpu.dev/retire-at"] = str(retire_at)
        self.store.update(obj)

    def reap_retired_clusters(self, namespace: Optional[str] = None) -> int:
        """Delete clusters whose retire-at has passed; called on requeue."""
        n = 0
        for obj in self.store.list(C.KIND_CLUSTER, namespace):
            at = obj["metadata"].get("annotations", {}).get("tpu.dev/retire-at")
            if at and self._now() >= float(at):
                try:
                    self.store.delete(C.KIND_CLUSTER, obj["metadata"]["name"],
                                      obj["metadata"]["namespace"])
                    n += 1
                except NotFound:
                    pass
        return n

    # ------------------------------------------------------------------
    # stable services (ref per-cluster serve services :2269 + selector flip)
    # ------------------------------------------------------------------

    def _reconcile_stable_services(self, svc: TpuService):
        st = svc.status
        if st.activeServiceStatus is None:
            return
        cluster = self._get_cluster(svc, st.activeServiceStatus.clusterName)
        if cluster is None:
            return
        stable_name = serve_service_name(svc.metadata.name)
        desired = build_serve_service(cluster, service_name=stable_name)
        # The stable service is owned by the TpuService, not the cluster —
        # it must outlive cluster replacement.
        desired["metadata"]["ownerReferences"] = [owner_reference(
            C.KIND_SERVICE, svc.metadata.name, svc.metadata.uid)]
        self.store.ensure(desired,
                          compare=lambda o: o.get("spec", {}).get("selector"))
        # Head serve-label: heads receive serve traffic unless excluded
        # (ref updateHeadPodServeLabel :2065).
        serve_val = "false" if svc.spec.excludeHeadPodFromServe else "true"
        for pod in self.store.list("Pod", svc.metadata.namespace,
                                   labels={C.LABEL_CLUSTER: cluster.metadata.name,
                                           C.LABEL_NODE_TYPE: C.NODE_TYPE_HEAD}):
            if pod["metadata"]["labels"].get(C.LABEL_SERVE) != serve_val:
                self.store.patch_labels("Pod", pod["metadata"]["name"],
                                        svc.metadata.namespace,
                                        {C.LABEL_SERVE: serve_val})

    def _reconcile_weighted_services(self, svc: TpuService):
        """Incremental mode: per-cluster serve services exist for both
        clusters; an HTTPRoute-equivalent object records the weights (the
        Gateway-API analogue, ref reconcileGateway :920)."""
        st = svc.status
        route = {
            "apiVersion": C.API_VERSION, "kind": "TrafficRoute",
            "metadata": {"name": truncate_name(f"{svc.metadata.name}-route"),
                         "namespace": svc.metadata.namespace,
                         "labels": {C.LABEL_ORIGINATED_FROM_CR_NAME:
                                    svc.metadata.name}},
            "spec": {"backends": []},
            "status": {},
        }
        for cs in (st.activeServiceStatus, st.pendingServiceStatus):
            if cs is None:
                continue
            cluster = self._get_cluster(svc, cs.clusterName)
            if cluster is None:
                continue
            per_cluster = build_serve_service(cluster)
            try:
                self.store.create(per_cluster)
            except AlreadyExists:
                pass
            # Disaggregation role rides along with the weight: the gateway
            # two-hop-schedules routes whose backends span prefill+decode
            # tiers (serve/gateway.py) and ignores the field otherwise.
            tier = svc.spec.serveTier
            if tier not in C.SERVE_TIERS:
                tier = C.SERVE_TIER_MIXED
            backend = {
                "service": per_cluster["metadata"]["name"],
                "weight": cs.trafficWeightPercent,
                "tier": tier,
            }
            # Gated-ramp handshakes the gateway acts on and acks via the
            # route's STATUS (which store.ensure preserves): replay the
            # hottest prefixes into the cold green backend; drain the
            # blue backend's in-flight set before promotion retires it.
            opts = svc.spec.upgradeOptions
            if cs is st.pendingServiceStatus and opts is not None \
                    and opts.prewarmPrompts > 0:
                backend["prewarm"] = opts.prewarmPrompts
            if cs is st.activeServiceStatus and st.upgrade is not None \
                    and st.upgrade.state == UpgradeState.DRAINING:
                backend["drain"] = True
            route["spec"]["backends"].append(backend)
        self.store.ensure(route)

    # ------------------------------------------------------------------

    def _reconcile_suspend(self, svc: TpuService) -> Optional[float]:
        st = svc.status
        for cs in (st.activeServiceStatus, st.pendingServiceStatus):
            if cs is None:
                continue
            try:
                self.store.delete(C.KIND_CLUSTER, cs.clusterName,
                                  svc.metadata.namespace)
            except NotFound:
                pass
            self._submitted.pop(cs.clusterName, None)
            self._unhealthy_since.pop(cs.clusterName, None)
        st.activeServiceStatus = None
        st.pendingServiceStatus = None
        if st.serviceStatus != "Suspended":
            self.transitions.record(self.KIND, svc.metadata.namespace,
                                    svc.metadata.name, "Suspended",
                                    old_state=st.serviceStatus)
        st.serviceStatus = "Suspended"
        self._update_status(svc)
        return None

    def _reconcile_deletion(self, svc: TpuService) -> Optional[float]:
        st = svc.status
        for cs in (st.activeServiceStatus, st.pendingServiceStatus):
            if cs is None:
                continue
            try:
                self.store.delete(C.KIND_CLUSTER, cs.clusterName,
                                  svc.metadata.namespace)
            except NotFound:
                pass
            self._submitted.pop(cs.clusterName, None)
            self._unhealthy_since.pop(cs.clusterName, None)
        self.store.remove_finalizer(self.KIND, svc.metadata.name,
                                    svc.metadata.namespace, C.FINALIZER_SERVICE)
        return None

    def _update_status(self, svc: TpuService):
        st = svc.status
        st.observedGeneration = svc.metadata.generation
        ready = self._serve_ready(st.activeServiceStatus)
        if not svc.spec.suspend:
            nxt = "Running" if ready else "WaitForServeDeploymentReady"
            if nxt != st.serviceStatus:
                self.transitions.record(self.KIND, svc.metadata.namespace,
                                        svc.metadata.name, nxt,
                                        old_state=st.serviceStatus)
            st.serviceStatus = nxt
        set_condition(st.conditions, Condition(
            type=ServiceConditionType.READY,
            status="True" if ready else "False",
            reason="ServeAppsRunning" if ready else "ServeAppsNotReady",
            observedGeneration=svc.metadata.generation))
        st.numServeEndpoints = 0
        if st.activeServiceStatus is not None:
            pods = self.store.list(
                "Pod", svc.metadata.namespace,
                labels={C.LABEL_CLUSTER: st.activeServiceStatus.clusterName})
            st.numServeEndpoints = sum(
                1 for p in pods
                if p.get("status", {}).get("phase") == "Running"
                and p["metadata"]["labels"].get(C.LABEL_SERVE) == "true")
        obj = svc.to_dict()
        # Status is recomputed idempotently from the reconcile-start
        # snapshot, so the write carries the SNAPSHOT rv (plus our own
        # threaded bumps — finalizer add).  NO pre-write re-read: a
        # foreign write anywhere in the pass (leader-failover overlap)
        # 409s and requeues instead of being clobbered (SURVEY §5.2).
        if obj.get("status") != getattr(svc, "_orig_status", None):
            with self.tracer.span("store-write", kind=self.KIND,
                                  obj=svc.metadata.name):
                try:
                    out = self.store.update_status(obj)
                except NotFound:
                    return      # deleted mid-reconcile
            svc.metadata.resourceVersion = \
                out["metadata"]["resourceVersion"]
            svc._orig_status = copy.deepcopy(out.get("status", {}))

        self.reap_retired_clusters(svc.metadata.namespace)
