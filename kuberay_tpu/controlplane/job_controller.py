"""TpuJob reconciler: the job lifecycle state machine.

Mirrors the reference's RayJob state machine (rayjob_controller.go:165-462):

    New -> Initializing -> (Waiting | Running) -> Complete | Failed
               |                |
               +-- Suspending <-+        (suspend flips mid-flight)
                      v
                  Suspended -> (resume) -> New
    Failed attempt + backoffLimit left  -> Retrying -> New (fresh cluster)

plus deadlines (active/preRunning), the deletion-rules engine
(handleDeletionRules :1413 / selectMostImpactfulRule :1685), and the
submitter (K8s-Job mode first; HTTP mode talks straight to the
coordinator — ref createK8sJobIfNeed :560 / checkSubmitterAndUpdateStatus
:1062).
"""

from __future__ import annotations

import copy
import time
from typing import Callable, Optional

from kuberay_tpu.api.tpucluster import ClusterState, TpuCluster
from kuberay_tpu.api.tpujob import (
    DeletionPolicyType,
    JobDeploymentStatus,
    JobFailedReason,
    JobStatus,
    JobSubmissionMode,
    TpuJob,
)
from kuberay_tpu.builders.common import attach_cluster_auth, owner_reference
from kuberay_tpu.builders.job import (
    build_sidecar_submitter_container,
    build_submitter_job,
)
from kuberay_tpu.controlplane.events import EventRecorder
from kuberay_tpu.controlplane.store import (AlreadyExists, Conflict,
                                             NotFound, ObjectStore)
from kuberay_tpu.controlplane.warmpool_controller import KIND_WARM_POOL
from kuberay_tpu.obs.goodput import NOOP_TRANSITIONS
from kuberay_tpu.obs.trace import NOOP_TRACER
from kuberay_tpu.runtime.coordinator_client import CoordinatorError
from kuberay_tpu.utils import constants as C
from kuberay_tpu.utils.names import (
    cluster_name_for_job,
    head_pod_name,
    submitter_job_name,
)
from kuberay_tpu.utils.validation import validate_job, waive_create_only


class TpuJobController:
    KIND = C.KIND_JOB

    def __init__(self, store: ObjectStore,
                 recorder: Optional[EventRecorder] = None,
                 client_provider: Optional[Callable] = None,
                 scheduler=None,
                 metrics=None,
                 tracer=None,
                 transitions=None):
        self.store = store
        self.recorder = recorder or EventRecorder(store)
        self.client_provider = client_provider
        self.scheduler = scheduler
        self.metrics = metrics
        # Span annotations — no-op by default, passed like ``metrics``.
        self.tracer = tracer or NOOP_TRACER
        # State-transition seam (obs.goodput): every jobDeploymentStatus
        # write routes through it (rule phase-transition-recorded).
        self.transitions = transitions or NOOP_TRANSITIONS

    # ------------------------------------------------------------------

    def reconcile(self, name: str, namespace: str = "default") -> Optional[float]:
        raw = self.store.try_get(self.KIND, name, namespace)
        if raw is None:
            return None
        job = TpuJob.from_dict(raw)
        # Snapshot status for the update throttle + the snapshot rv
        # contract: every write in this pass carries the reconcile-start
        # resourceVersion (threaded through job.metadata by our own
        # writes' return values), so a foreign write 409s instead of
        # being clobbered (SURVEY §5.2).
        job._orig_status = copy.deepcopy(raw.get("status", {}))

        if job.spec.managedBy and job.spec.managedBy != C.CREATED_BY_OPERATOR:
            return None
        if job.metadata.deletionTimestamp:
            return self._reconcile_deletion(job)

        status = job.status.jobDeploymentStatus
        handler = {
            JobDeploymentStatus.NEW: self._state_new,
            JobDeploymentStatus.INITIALIZING: self._state_initializing,
            JobDeploymentStatus.WAITING: self._state_waiting,
            JobDeploymentStatus.RUNNING: self._state_running,
            JobDeploymentStatus.SUSPENDING: self._state_suspending,
            JobDeploymentStatus.SUSPENDED: self._state_suspended,
            JobDeploymentStatus.RETRYING: self._state_retrying,
            JobDeploymentStatus.COMPLETE: self._state_terminal,
            JobDeploymentStatus.FAILED: self._state_terminal,
        }.get(status)
        if handler is None:
            return None
        return handler(job)

    # ------------------------------------------------------------------
    # states
    # ------------------------------------------------------------------

    def _state_new(self, job: TpuJob) -> Optional[float]:
        errs = waive_create_only(validate_job(job))
        if errs:
            self.recorder.warning(job.to_dict(), C.EVENT_INVALID_SPEC,
                                  "; ".join(errs))
            return self._fail(job, JobFailedReason.VALIDATION_FAILED,
                              "; ".join(errs)[:500])
        # rv precondition = the reconcile-start snapshot; the returned
        # object threads the bump (no post-write re-read, which would
        # adopt a foreign writer's rv and mask the conflict).
        out = self.store.add_finalizer(self.KIND, job.metadata.name,
                                       job.metadata.namespace,
                                       C.FINALIZER_JOB,
                                       rv=job.metadata.resourceVersion)
        orig_status = job._orig_status
        job = TpuJob.from_dict(out)
        job._orig_status = orig_status
        # Attempt-suffixed id: each retry is a distinct submission against a
        # fresh cluster (ref JobId init :887; suffix disambiguates attempts).
        attempt = int(job.status.failed)
        job.status.jobId = job.status.jobId or (
            f"{job.metadata.name}-{job.metadata.uid[:8]}"
            + (f"-r{attempt}" if attempt else ""))
        if job.spec.clusterSelector:
            matches = self.store.list(C.KIND_CLUSTER, job.metadata.namespace,
                                      labels=job.spec.clusterSelector)
            if not matches:
                self._set_message(job, "no cluster matches clusterSelector")
                self._update(job)
                return 5.0
            job.status.clusterName = matches[0]["metadata"]["name"]
        else:
            job.status.clusterName = cluster_name_for_job(
                job.metadata.name, int(job.status.failed))
        nxt = (JobDeploymentStatus.SUSPENDED if job.spec.suspend
               else JobDeploymentStatus.INITIALIZING)
        self.transitions.record(self.KIND, job.metadata.namespace,
                                job.metadata.name, nxt,
                                old_state=JobDeploymentStatus.NEW)
        job.status.jobDeploymentStatus = nxt
        if not job.spec.suspend:
            job.status.startTime = job.status.startTime or time.time()
        self._update(job)
        return 0.1

    def _state_initializing(self, job: TpuJob) -> Optional[float]:
        if job.spec.suspend:
            return self._to(job, JobDeploymentStatus.SUSPENDING, requeue=0.1)
        # preRunning deadline (ref :180-190).
        if job.spec.preRunningDeadlineSeconds and job.status.startTime and \
                time.time() - job.status.startTime > job.spec.preRunningDeadlineSeconds:
            return self._fail(job, JobFailedReason.DEADLINE_EXCEEDED,
                              "did not reach Running before preRunningDeadlineSeconds")

        # Gang reservation before any pod exists (ref :192-200).  The
        # quota verdict's reason lands in status.message so "why is my
        # job Initializing" is answerable from the CR; the scheduler
        # counts the denial in tpu_gang_admission_total (the hold-off
        # requeue's observability evidence).
        if self.scheduler is not None and job.spec.clusterSpec is not None:
            verdict = self.scheduler.on_job_submission(job.to_dict())
            if not verdict:
                reason = getattr(verdict, "reason", "") or "capacity-hold"
                self._set_message(job, f"gang admission held: {reason}")
                self._update(job)
                return 5.0

        cluster = self._get_or_create_cluster(job)
        if cluster is None:
            return 2.0
        job.status.clusterStatus = cluster.status.to_dict()
        if cluster.status.state != ClusterState.READY:
            self._update(job)
            return 2.0

        mode = job.spec.submissionMode
        if mode == JobSubmissionMode.INTERACTIVE:
            return self._to(job, JobDeploymentStatus.WAITING)
        if mode == JobSubmissionMode.K8S_JOB:
            self._ensure_submitter(job, cluster)
        elif mode == JobSubmissionMode.HTTP:
            client = self._client(job, cluster)
            if client is None:
                return 2.0
            try:
                client.submit_job(job.status.jobId, job.spec.entrypoint,
                                  job.spec.runtimeEnv, job.spec.metadata)
            except CoordinatorError as e:
                self.tracer.record_error("coordinator",
                                         f"submission failed: {e}")
                self._set_message(job, f"submission failed: {e}")
                self._update(job)
                return 2.0
        # SIDECAR: the submitter container was injected into the head pod
        # at cluster creation (_get_or_create_cluster); nothing to do here.
        job.status.jobStatus = JobStatus.PENDING
        return self._to(job, JobDeploymentStatus.RUNNING, requeue=1.0)

    def _state_waiting(self, job: TpuJob) -> Optional[float]:
        # Interactive: user submits with the job id; once the coordinator
        # reports it, move to Running (ref Waiting :166 area).
        if job.spec.suspend:
            return self._to(job, JobDeploymentStatus.SUSPENDING, requeue=0.1)
        cluster = self._cluster(job)
        client = self._client(job, cluster) if cluster else None
        if client is not None:
            try:
                client.get_job_info(job.status.jobId)
                return self._to(job, JobDeploymentStatus.RUNNING, requeue=1.0)
            except CoordinatorError:
                pass
        return 2.0

    def _state_running(self, job: TpuJob) -> Optional[float]:
        if job.spec.suspend:
            return self._to(job, JobDeploymentStatus.SUSPENDING, requeue=0.1)
        if job.spec.activeDeadlineSeconds and job.status.startTime and \
                time.time() - job.status.startTime > job.spec.activeDeadlineSeconds:
            return self._fail(job, JobFailedReason.DEADLINE_EXCEEDED,
                              "activeDeadlineSeconds exceeded")

        cluster = self._cluster(job)
        if cluster is None:
            return self._fail(job, JobFailedReason.APP_FAILED,
                              "cluster disappeared while running")
        job.status.clusterStatus = cluster.status.to_dict()

        r = self._reconcile_elastic(job, cluster)
        if r is not None:
            self._update(job)
            return r

        app_status = None
        # Submitter (K8s Job) status (ref checkSubmitterAndUpdateStatus :1062).
        if job.spec.submissionMode == JobSubmissionMode.K8S_JOB:
            sub = self.store.try_get("Job", submitter_job_name(job.metadata.name),
                                     job.metadata.namespace)
            if sub is not None:
                st = sub.get("status", {})
                if st.get("succeeded"):
                    app_status = JobStatus.SUCCEEDED
                elif st.get("failed", 0) > job.spec.submitterConfig.backoffLimit:
                    app_status = JobStatus.FAILED
        elif job.spec.submissionMode == JobSubmissionMode.SIDECAR:
            # The submitter container's terminal state in the head pod is
            # the outcome signal (ref rayjob_controller.go:279,337).
            head = self.store.try_get(
                "Pod", head_pod_name(cluster.metadata.name),
                job.metadata.namespace)
            for cs in (head or {}).get("status", {}) \
                    .get("containerStatuses", []):
                if cs.get("name") != C.SUBMITTER_CONTAINER_NAME:
                    continue
                term = (cs.get("state") or {}).get("terminated")
                if term is not None:
                    app_status = (JobStatus.SUCCEEDED
                                  if term.get("exitCode", 1) == 0
                                  else JobStatus.FAILED)

        client = self._client(job, cluster)
        if client is not None:
            try:
                info = client.get_job_info(job.status.jobId)
                job.status.jobStatus = info.status
                if info.status in JobStatus.TERMINAL:
                    app_status = info.status
                job.status.message = info.message
            except CoordinatorError as e:
                if app_status is None:
                    self.tracer.record_error("coordinator",
                                             f"job info poll failed: {e}")
                    self._update(job)
                    return 2.0

        if app_status == JobStatus.SUCCEEDED:
            job.status.jobStatus = JobStatus.SUCCEEDED
            job.status.succeeded = 1
            job.status.endTime = time.time()
            self._emit_duration(job)
            return self._to(job, JobDeploymentStatus.COMPLETE, requeue=0.1)
        if app_status == JobStatus.STOPPED:
            # Deliberately stopped by the user: terminal, never retried
            # (the reference retries only on FAILED).
            job.status.jobStatus = JobStatus.STOPPED
            job.status.endTime = time.time()
            self._emit_duration(job)
            return self._fail(job, "AppStopped", "job was stopped")
        if app_status == JobStatus.FAILED:
            job.status.jobStatus = app_status
            job.status.endTime = time.time()
            # backoffLimit retries with fresh clusters (ref :518).
            if int(job.status.failed) < job.spec.backoffLimit:
                job.status.failed = int(job.status.failed) + 1
                self._emit_duration(job)
                return self._to(job, JobDeploymentStatus.RETRYING, requeue=0.1)
            self._emit_duration(job)
            return self._fail(job, JobFailedReason.APP_FAILED,
                              job.status.message or "application failed")
        self._update(job)
        return 2.0

    def _state_suspending(self, job: TpuJob) -> Optional[float]:
        # Delete cluster + submitter, keep the CR (ref :366-418).
        self._teardown(job)
        job.status.jobStatus = JobStatus.STOPPED
        return self._to(job, JobDeploymentStatus.SUSPENDED)

    def _state_suspended(self, job: TpuJob) -> Optional[float]:
        if not job.spec.suspend:
            # Resume: back to New with a fresh cluster (ref requeue-to-New).
            self.transitions.record(self.KIND, job.metadata.namespace,
                                    job.metadata.name,
                                    JobDeploymentStatus.NEW,
                                    old_state=JobDeploymentStatus.SUSPENDED)
            job.status.jobDeploymentStatus = JobDeploymentStatus.NEW
            job.status.jobStatus = ""
            job.status.startTime = 0.0
            self._update(job)
            return 0.1
        return None

    def _state_retrying(self, job: TpuJob) -> Optional[float]:
        self._teardown(job)
        self.transitions.record(self.KIND, job.metadata.namespace,
                                job.metadata.name, JobDeploymentStatus.NEW,
                                old_state=JobDeploymentStatus.RETRYING)
        job.status.jobDeploymentStatus = JobDeploymentStatus.NEW
        job.status.jobStatus = ""
        job.status.jobId = ""       # fresh submission id for the new attempt
        self._update(job)
        return 0.1

    def _state_terminal(self, job: TpuJob) -> Optional[float]:
        return self._handle_deletion_policy(job)

    # ------------------------------------------------------------------
    # elastic capacity (spec.elastic, docs/preemption.md)
    # ------------------------------------------------------------------

    def _reconcile_elastic(self, job: TpuJob,
                           cluster: TpuCluster) -> Optional[float]:
        """``shrink`` mode: when preemption takes slice capacity away
        (a live pod carries a notice, or a slice host already Failed)
        and no warm replacement stands ready, step the job's own
        cluster down to the surviving slice count (DP world-size
        shrink, floored at minReplicas) instead of stalling; restore
        the original replica count once a ready warm slice returns.
        Selector-targeted (shared) clusters are never resized."""
        pol = job.spec.elastic
        if pol is None or pol.mode != "shrink" or job.spec.clusterSelector:
            return None
        ns = job.metadata.namespace
        raw = self.store.try_get(C.KIND_CLUSTER, cluster.metadata.name, ns)
        if raw is None or not raw["spec"].get("workerGroupSpecs"):
            return None
        group = raw["spec"]["workerGroupSpecs"][0]
        desired = int(group.get("replicas", 0))
        gs = cluster.status.groups[0] if cluster.status.groups else None
        ready = int(gs.readySlices) if gs else 0
        pods = self.store.list("Pod", ns,
                               labels={C.LABEL_CLUSTER: cluster.metadata.name})
        lost = any(
            p["metadata"].get("annotations", {}).get(
                C.ANNOTATION_PREEMPTION_NOTICE)
            or p.get("status", {}).get("phase") == "Failed"
            for p in pods if not p["metadata"].get("deletionTimestamp"))
        warm_ready = sum(
            int((o.get("status") or {}).get("readySlices", 0))
            for o in self.store.list(KIND_WARM_POOL, ns))
        orig = int(job.status.elasticOriginalReplicas)
        try:
            if lost and warm_ready == 0 and ready < desired:
                floor = max(1, int(pol.minReplicas))
                shrunk = max(floor, ready)
                if shrunk < desired:
                    if not orig:
                        job.status.elasticOriginalReplicas = desired
                    group["replicas"] = shrunk
                    self.store.update(raw)
                    self.recorder.normal(
                        job.to_dict(), "ElasticShrink",
                        f"no replacement capacity: shrank "
                        f"{cluster.metadata.name} to {shrunk} slice(s) "
                        f"(was {desired})")
                    return 1.0
            elif orig and desired < orig and warm_ready > 0:
                group["replicas"] = orig
                self.store.update(raw)
                job.status.elasticOriginalReplicas = 0
                self.recorder.normal(
                    job.to_dict(), "ElasticRestore",
                    f"capacity returned: restored {cluster.metadata.name} "
                    f"to {orig} slice(s)")
                return 1.0
        except Conflict:
            if self.metrics is not None:
                self.metrics.reconcile_conflict(self.KIND)
            return 1.0
        return None

    # ------------------------------------------------------------------
    # deletion engine (ref handleDeletionRules :1413)
    # ------------------------------------------------------------------

    def _handle_deletion_policy(self, job: TpuJob) -> Optional[float]:
        now = time.time()
        end = job.status.endTime or now
        succeeded = job.status.jobDeploymentStatus == JobDeploymentStatus.COMPLETE

        if job.spec.deletionStrategy is not None and job.spec.deletionStrategy.rules:
            cond = "Succeeded" if succeeded else "Failed"
            due = [r for r in job.spec.deletionStrategy.rules
                   if r.condition == cond and now - end >= r.ttlSeconds]
            pending = [r for r in job.spec.deletionStrategy.rules
                       if r.condition == cond and now - end < r.ttlSeconds]
            if due:
                # Most impactful rule wins (ref selectMostImpactfulRule :1685).
                rank = {DeletionPolicyType.DELETE_SELF: 3,
                        DeletionPolicyType.DELETE_CLUSTER: 2,
                        DeletionPolicyType.DELETE_WORKERS: 1,
                        DeletionPolicyType.DELETE_NONE: 0}
                rule = max(due, key=lambda r: rank.get(r.policy, 0))
                self._apply_deletion_policy(job, rule.policy)
            if pending:
                return max(0.5, min(r.ttlSeconds - (now - end) for r in pending))
            return None

        if job.spec.shutdownAfterJobFinishes:
            ttl = job.spec.ttlSecondsAfterFinished
            if now - end >= ttl:
                self._apply_deletion_policy(job, DeletionPolicyType.DELETE_CLUSTER)
                return None
            return max(0.5, ttl - (now - end))
        return None

    def _apply_deletion_policy(self, job: TpuJob, policy: str):
        ns = job.metadata.namespace
        if policy == DeletionPolicyType.DELETE_CLUSTER:
            self._delete_cluster(job)
        elif policy == DeletionPolicyType.DELETE_WORKERS:
            cluster = self.store.try_get(C.KIND_CLUSTER, job.status.clusterName, ns)
            if cluster is not None and not job.spec.clusterSelector:
                for g in cluster["spec"].get("workerGroupSpecs", []):
                    g["replicas"] = 0
                    g["minReplicas"] = 0
                self.store.update(cluster)
        elif policy == DeletionPolicyType.DELETE_SELF:
            try:
                self.store.delete(self.KIND, job.metadata.name, ns)
            except NotFound:
                pass

    # ------------------------------------------------------------------
    # helpers
    # ------------------------------------------------------------------

    def _get_or_create_cluster(self, job: TpuJob) -> Optional[TpuCluster]:
        """Ref getOrCreateRayClusterInstance :947."""
        ns = job.metadata.namespace
        raw = self.store.try_get(C.KIND_CLUSTER, job.status.clusterName, ns)
        if raw is not None:
            return TpuCluster.from_dict(raw)
        if job.spec.clusterSelector:
            return None
        spec = job.spec.clusterSpec.to_dict()
        if job.spec.submissionMode == JobSubmissionMode.SIDECAR:
            # Inject the submitter container into the head pod template
            # (ref common/job.go:95-158): it rides the head pod, submits
            # over localhost, and its terminal container state is the
            # outcome signal _state_running watches.
            head_spec = spec.setdefault("headGroupSpec", {}) \
                .setdefault("template", {}).setdefault("spec", {})
            containers = head_spec.setdefault("containers", [])
            head_image = (containers[0].get("image", "")
                          if containers else "")
            if not any(c.get("name") == C.SUBMITTER_CONTAINER_NAME
                       for c in containers):
                containers.append(build_sidecar_submitter_container(
                    job, head_image))
            # Pod-level Never (ref rayjob_controller.go:1035): the exited
            # submitter must surface as state.terminated, not be
            # restarted by the kubelet; head-loss repair is the cluster
            # controller's job either way.
            head_spec["restartPolicy"] = "Never"
        obj = {
            "apiVersion": C.API_VERSION,
            "kind": C.KIND_CLUSTER,
            "metadata": {
                "name": job.status.clusterName,
                "namespace": ns,
                "labels": {
                    C.LABEL_ORIGINATED_FROM_CR_NAME: job.metadata.name,
                    C.LABEL_ORIGINATED_FROM_CRD: C.KIND_JOB,
                },
                "ownerReferences": [owner_reference(
                    C.KIND_JOB, job.metadata.name, job.metadata.uid)],
            },
            "spec": spec,
            "status": {},
        }
        if job.spec.schedulerName:
            obj["spec"]["schedulerName"] = job.spec.schedulerName
        if job.spec.gangSchedulingQueue:
            obj["spec"]["gangSchedulingQueue"] = job.spec.gangSchedulingQueue
        if job.spec.tenant:
            obj["spec"]["tenant"] = job.spec.tenant
        if job.spec.priority:
            obj["spec"]["priority"] = job.spec.priority
        try:
            self.store.create(obj)
        except AlreadyExists:
            pass
        return TpuCluster.from_dict(self.store.get(
            C.KIND_CLUSTER, job.status.clusterName, ns))

    def _ensure_submitter(self, job: TpuJob, cluster: TpuCluster):
        sub = build_submitter_job(job, cluster)
        try:
            self.store.create(sub)
            self.recorder.normal(job.to_dict(), "CreatedSubmitter",
                                 f"created submitter {sub['metadata']['name']}")
        except AlreadyExists:
            pass

    def _cluster(self, job: TpuJob) -> Optional[TpuCluster]:
        raw = self.store.try_get(C.KIND_CLUSTER, job.status.clusterName,
                                 job.metadata.namespace)
        return TpuCluster.from_dict(raw) if raw else None

    def _client(self, job: TpuJob, cluster: Optional[TpuCluster]):
        if self.client_provider is None or cluster is None:
            return None
        client = self.client_provider(cluster.status.to_dict())
        attach_cluster_auth(client, self.store, cluster)
        return client

    def _teardown(self, job: TpuJob):
        ns = job.metadata.namespace
        sub_name = submitter_job_name(job.metadata.name)
        try:
            self.store.delete("Job", sub_name, ns)
        except NotFound:
            pass
        self._delete_cluster(job)

    def _delete_cluster(self, job: TpuJob):
        # Never delete a selector-targeted (shared) cluster (ref selector
        # semantics).
        if job.spec.clusterSelector:
            return
        try:
            self.store.delete(C.KIND_CLUSTER, job.status.clusterName,
                              job.metadata.namespace)
        except NotFound:
            pass

    def _reconcile_deletion(self, job: TpuJob) -> Optional[float]:
        # StopJob finalizer: stop the app, tear down resources (ref New :166
        # finalizer + deletion path).
        cluster = self._cluster(job)
        client = self._client(job, cluster)
        if client is not None and job.status.jobStatus == JobStatus.RUNNING:
            try:
                client.stop_job(job.status.jobId)
            except CoordinatorError:
                pass
        self._teardown(job)
        if self.scheduler is not None:
            self.scheduler.cleanup(job.to_dict())
        self.store.remove_finalizer(self.KIND, job.metadata.name,
                                    job.metadata.namespace, C.FINALIZER_JOB)
        return None

    def _to(self, job: TpuJob, state: str, requeue: Optional[float] = None
            ) -> Optional[float]:
        self.transitions.record(self.KIND, job.metadata.namespace,
                                job.metadata.name, state,
                                old_state=job.status.jobDeploymentStatus)
        job.status.jobDeploymentStatus = state
        self._update(job)
        return requeue

    def _fail(self, job: TpuJob, reason: str, message: str) -> Optional[float]:
        self.transitions.record(self.KIND, job.metadata.namespace,
                                job.metadata.name, JobDeploymentStatus.FAILED,
                                old_state=job.status.jobDeploymentStatus)
        job.status.jobDeploymentStatus = JobDeploymentStatus.FAILED
        job.status.jobStatus = job.status.jobStatus or JobStatus.FAILED
        job.status.reason = reason
        job.status.message = message
        job.status.endTime = job.status.endTime or time.time()
        self._update(job)
        self.recorder.warning(job.to_dict(), reason, message)
        return 0.1

    def _set_message(self, job: TpuJob, message: str):
        job.status.message = message

    def _emit_duration(self, job: TpuJob):
        if self.metrics is not None and job.status.startTime:
            self.metrics.observe_job_duration(
                job.metadata.name,
                job.status.jobStatus,
                (job.status.endTime or time.time()) - job.status.startTime)

    def _update(self, job: TpuJob):
        obj = job.to_dict()
        # Throttle against the snapshot status, then write under the
        # reconcile-start rv (threaded through job.metadata by our own
        # earlier writes).  NO pre-write re-read: this status was
        # computed from the snapshot, so a foreign write anywhere in
        # the pass (leader-failover overlap) 409s and requeues instead
        # of being clobbered (SURVEY §5.2).
        if obj.get("status") == getattr(job, "_orig_status", None):
            return
        with self.tracer.span("store-write", kind=self.KIND,
                              obj=job.metadata.name):
            try:
                out = self.store.update_status(obj)
            except NotFound:
                return  # deleted mid-reconcile; deletion path owns cleanup
        job.metadata.resourceVersion = out["metadata"]["resourceVersion"]
        job._orig_status = copy.deepcopy(out.get("status", {}))
