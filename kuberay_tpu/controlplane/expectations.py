"""Scale expectations: don't act on a stale cache (ref
controllers/ray/expectations/scale_expectations.go:37-44).

After issuing a create/delete the reconciler records an expectation; until
the corresponding watch event arrives (or the 30 s timeout expires) further
scale decisions for that (cluster, group) are skipped.  This is the
mechanism that prevents double slice creation during informer lag — with
slice-atomic groups a double create wastes an entire multi-host slice, so
the stakes are higher than the reference's single-pod case.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Tuple

EXPECTATIONS_TIMEOUT_SECONDS = 30.0

HEAD_GROUP = "__head__"


class ScaleExpectations:
    def __init__(self, timeout: float = EXPECTATIONS_TIMEOUT_SECONDS):
        self._lock = threading.Lock()
        self._timeout = timeout
        # (ns, cluster, group) -> {pod_name -> (op, deadline)}
        self._pending: Dict[Tuple[str, str, str], Dict[str, Tuple[str, float]]] = {}

    def expect_create(self, ns: str, cluster: str, group: str, pod: str):
        self._expect(ns, cluster, group, pod, "create")

    def expect_delete(self, ns: str, cluster: str, group: str, pod: str):
        self._expect(ns, cluster, group, pod, "delete")

    def _expect(self, ns, cluster, group, pod, op):
        with self._lock:
            self._pending.setdefault((ns, cluster, group), {})[pod] = (
                op, time.time() + self._timeout)

    def observe_pod_event(self, ns: str, cluster: str, group: str,
                          pod: str, event_type: str):
        """Call on watch events: ADDED satisfies creates, DELETED deletes."""
        want = {"ADDED": "create", "DELETED": "delete"}.get(event_type)
        if want is None:
            return
        with self._lock:
            bucket = self._pending.get((ns, cluster, group))
            if not bucket:
                return
            cur = bucket.get(pod)
            if cur and cur[0] == want:
                del bucket[pod]
                if not bucket:
                    del self._pending[(ns, cluster, group)]

    def satisfied(self, ns: str, cluster: str, group: str) -> bool:
        """True when no live expectations remain (expired ones are dropped —
        the reconcile falls back to observed state, ref 30 s timeout)."""
        now = time.time()
        with self._lock:
            bucket = self._pending.get((ns, cluster, group))
            if not bucket:
                return True
            live = {p: v for p, v in bucket.items() if v[1] > now}
            if live:
                self._pending[(ns, cluster, group)] = live
                return False
            del self._pending[(ns, cluster, group)]
            return True

    def forget(self, ns: str, cluster: str, group: str, pod: str):
        """Roll back an expectation whose create/delete call failed."""
        with self._lock:
            bucket = self._pending.get((ns, cluster, group))
            if bucket and pod in bucket:
                del bucket[pod]
                if not bucket:
                    del self._pending[(ns, cluster, group)]

    def forget_cluster(self, ns: str, cluster: str):
        with self._lock:
            for key in [k for k in self._pending if k[0] == ns and k[1] == cluster]:
                del self._pending[key]
