"""K8s-style Event recording (ref EventRecorder + typed reasons,
utils/constant.go EventType section).  Events land in the store as
``Event`` objects so clients/CLI can list them alongside CRs."""

from __future__ import annotations

import time
import uuid
from typing import Any, Dict

from kuberay_tpu.controlplane.store import ObjectStore


class EventRecorder:
    def __init__(self, store: ObjectStore):
        self._store = store

    def event(self, obj: Dict[str, Any], etype: str, reason: str, message: str):
        """etype: 'Normal' | 'Warning'."""
        md = obj.get("metadata", {})
        name = md.get("name", "unknown")
        self._store.create({
            "apiVersion": "v1",
            "kind": "Event",
            "metadata": {
                "name": f"{name}.{uuid.uuid4().hex[:10]}",
                "namespace": md.get("namespace", "default"),
            },
            "type": etype,
            "reason": reason,
            "message": message,
            "involvedObject": {
                "kind": obj.get("kind"),
                "name": name,
                "namespace": md.get("namespace", "default"),
                "uid": md.get("uid"),
            },
            "eventTime": time.time(),
        })

    def normal(self, obj, reason, message):
        self.event(obj, "Normal", reason, message)

    def warning(self, obj, reason, message):
        self.event(obj, "Warning", reason, message)
