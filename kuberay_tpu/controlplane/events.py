"""K8s-style Event recording (ref EventRecorder + typed reasons,
utils/constant.go EventType section).  Events land in the store as
``Event`` objects so clients/CLI can list them alongside CRs.

Determinism seams (the chaos sim's replay contract): ``clock`` overrides
the ``eventTime`` source and ``name_factory`` overrides the uuid4 name
suffix — the sim harness passes its virtual clock and a counter-based
factory so controller event emission is a pure function of the run
(identical names/timestamps per (scenario, seed), across processes),
instead of perturbing timelines with wall time and OS randomness.
Production keeps the uuid default: names must not collide across
operator replicas sharing a store.
"""

from __future__ import annotations

import time
import uuid
from typing import Any, Callable, Dict, Optional

from kuberay_tpu.controlplane.store import ObjectStore


class EventRecorder:
    def __init__(self, store: ObjectStore, clock=None,
                 name_factory: Optional[Callable[[str], str]] = None):
        self._store = store
        # Duck-typed .now(); falls back to module-level time.time at CALL
        # time so the sim's patch_time shim also covers recorders built
        # before the clock was threaded through.
        self._clock = clock
        self._name_factory = name_factory

    def _event_name(self, base: str) -> str:
        if self._name_factory is not None:
            return self._name_factory(base)
        return f"{base}.{uuid.uuid4().hex[:10]}"

    def event(self, obj: Dict[str, Any], etype: str, reason: str, message: str):
        """etype: 'Normal' | 'Warning'."""
        md = obj.get("metadata", {})
        name = md.get("name", "unknown")
        now = self._clock.now() if self._clock is not None else time.time()
        self._store.create({
            "apiVersion": "v1",
            "kind": "Event",
            "metadata": {
                "name": self._event_name(name),
                "namespace": md.get("namespace", "default"),
            },
            "type": etype,
            "reason": reason,
            "message": message,
            "involvedObject": {
                "kind": obj.get("kind"),
                "name": name,
                "namespace": md.get("namespace", "default"),
                "uid": md.get("uid"),
            },
            "eventTime": now,
        })

    def normal(self, obj, reason, message):
        self.event(obj, "Normal", reason, message)

    def warning(self, obj, reason, message):
        self.event(obj, "Warning", reason, message)
