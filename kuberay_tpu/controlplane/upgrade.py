"""Closed-loop blue/green upgrade decisions: the burn-rate-gated ramp.

The service controller's INCREMENTAL strategy used to be an open-loop
timer: shift ``stepSizePercent`` of traffic every ``intervalSeconds``
and hope the green build holds.  This module closes the loop.  The
:class:`UpgradeOrchestrator` is a pure decision core — it looks at one
:class:`UpgradeObservation` (green weight, ICI-ring readiness, gate
verdict, budgets) and returns one :class:`UpgradeDecision`; it never
touches the store, the clock, or the registry, so the service
controller, the sim harness, and the serve benchmark all drive the SAME
ramp logic and a decision table is unit-testable without a control
plane.

Three properties the decisions enforce (docs/upgrades.md):

- **Gated steps**: weight only advances while the green fleet's
  fast-window burn rate is clean (:class:`BurnRateGate` wraps a
  green-scoped :class:`~kuberay_tpu.obs.alerts.AlertEngine`); a firing
  fast-burn alert snaps green weight to 0 (ROLLBACK) and, past
  ``maxRollbacks``, abandons the pending cluster whole (ABORT).
- **ICI-ring atomicity**: weight never outruns the fully-Ready ring
  fraction of the green cluster — a slice becomes weight-eligible only
  when its whole multi-host ring is up, so no TrafficRoute ever points
  traffic at a partially-provisioned slice (the sim's
  ``weighted-ring-atomicity`` checker holds the line).
- **Warm starts, drained exits**: the first step waits for the
  gateway's prefix-cache pre-warm ack (PREWARM), and promotion waits
  for the blue backend's in-flight drain ack (WAIT_DRAIN) bounded by
  ``drainTimeoutSeconds``.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

from kuberay_tpu.obs.alerts import AlertEngine, SloSpec
from kuberay_tpu.obs.profile import describe_regression, worst_regression

# Decision actions, in rough lifecycle order.
PREWARM = "prewarm"          # hold at 0 until the gateway acks the replay
STEP = "step"                # advance (or ring-degrade) green weight
HOLD = "hold"                # interval / post-rollback backoff not elapsed
WAIT_RING = "wait-ring"      # weight at the ready-ring cap; more rings due
ROLLBACK = "rollback"        # fast burn fired: snap green weight to 0
ABORT = "abort"              # rollback budget exhausted: abandon pending
WAIT_DRAIN = "wait-drain"    # green at 100; blue finishing in-flight work
PROMOTE = "promote"          # ramp complete and drained: flip the fleets


@dataclasses.dataclass(frozen=True)
class UpgradeObservation:
    """Everything one ramp decision needs, sampled by the caller."""

    now: float
    green_weight: int
    step_size: int = 10
    interval_s: float = 30.0
    last_step_time: float = 0.0
    # ICI-ring wave progress of the green cluster: slices whose whole
    # multi-host ring is Running vs. slices the spec wants.
    ready_slices: int = 0
    desired_slices: int = 0
    # Burn-rate gate verdict over the green backend (vacuously healthy
    # when no gate is wired — the open-loop tests keep their semantics).
    gate_healthy: bool = True
    firing_alert: Optional[Dict[str, Any]] = None
    # Rollback/retry budgets (spec.upgradeOptions).
    rollbacks: int = 0
    max_rollbacks: int = 2
    hold_seconds: float = 60.0
    last_rollback_time: float = 0.0
    # Prefix-cache pre-warm handshake (gateway ack via TrafficRoute).
    prewarm_requested: bool = False
    prewarm_done: bool = False
    # Blue-session drain handshake.
    drain_requested: bool = False
    drain_done: bool = False
    drain_started_at: float = 0.0
    drain_timeout_s: float = 0.0


@dataclasses.dataclass(frozen=True)
class UpgradeDecision:
    action: str
    green_weight: int
    reason: str = ""
    alert: Optional[Dict[str, Any]] = None
    requeue_after: float = 2.0


class UpgradeOrchestrator:
    """Pure ramp-decision core; one :meth:`decide` call per reconcile."""

    def ring_cap(self, ready_slices: int, desired_slices: int) -> int:
        """Max green weight the fully-Ready ring fraction supports.  A
        green cluster with 1 of 2 rings whole may carry at most 50% —
        pointing more weight at it would route traffic into a
        partially-provisioned slice."""
        if desired_slices <= 0:
            return 100
        return (100 * min(ready_slices, desired_slices)) // desired_slices

    def decide(self, obs: UpgradeObservation) -> UpgradeDecision:
        cap = self.ring_cap(obs.ready_slices, obs.desired_slices)

        # Gate breach outranks everything: snap to 0, or abandon whole
        # once the retry budget is spent.
        if not obs.gate_healthy:
            if obs.green_weight > 0:
                if obs.rollbacks >= obs.max_rollbacks:
                    return UpgradeDecision(
                        ABORT, 0, alert=obs.firing_alert,
                        reason=f"fast burn after {obs.rollbacks} rollbacks "
                               f"(maxRollbacks={obs.max_rollbacks})")
                return UpgradeDecision(
                    ROLLBACK, 0, alert=obs.firing_alert,
                    reason="fast-window burn rate over threshold on the "
                           "green fleet")
            return UpgradeDecision(
                HOLD, 0, alert=obs.firing_alert,
                reason="green burn still firing at weight 0",
                requeue_after=max(2.0, obs.interval_s))

        # Post-rollback backoff: stay at 0 until holdSeconds of clean
        # burn have passed since the last rollback.
        if obs.green_weight == 0 and obs.rollbacks > 0:
            held = obs.now - obs.last_rollback_time
            if held < obs.hold_seconds:
                return UpgradeDecision(
                    HOLD, 0,
                    reason=f"holding {obs.hold_seconds - held:.0f}s more "
                           "after rollback",
                    requeue_after=max(0.5, obs.hold_seconds - held))

        # Cold green fleet: wait for the gateway's prefix replay ack
        # before the first real request lands.
        if obs.green_weight == 0 and obs.prewarm_requested \
                and not obs.prewarm_done:
            return UpgradeDecision(
                PREWARM, 0, reason="waiting for prefix-cache pre-warm ack")

        # Ramp complete: drain blue sessions, bounded, then promote.
        if obs.green_weight >= 100:
            if obs.drain_requested and not obs.drain_done:
                waited = obs.now - obs.drain_started_at
                if waited < obs.drain_timeout_s:
                    return UpgradeDecision(
                        WAIT_DRAIN, 100,
                        reason="blue backend finishing in-flight requests",
                        requeue_after=min(2.0, max(
                            0.5, obs.drain_timeout_s - waited)))
                return UpgradeDecision(
                    PROMOTE, 100,
                    reason=f"drain timeout ({obs.drain_timeout_s:.0f}s) "
                           "expired")
            return UpgradeDecision(PROMOTE, 100, reason="ramp complete")

        # A ring the weight depends on fell apart (pod kill mid-wave):
        # retreat to what whole rings can carry, immediately.
        if cap < obs.green_weight:
            return UpgradeDecision(
                STEP, cap,
                reason=f"ring degraded: {obs.ready_slices}/"
                       f"{obs.desired_slices} whole rings support "
                       f"{cap}%")

        # Timer leg of the ramp (unchanged from the open-loop stepper).
        since_step = obs.now - obs.last_step_time
        if since_step < obs.interval_s:
            return UpgradeDecision(
                HOLD, obs.green_weight, reason="step interval not elapsed",
                requeue_after=max(0.5, obs.interval_s - since_step))

        target = min(100, obs.green_weight + obs.step_size, cap)
        if target <= obs.green_weight:
            return UpgradeDecision(
                WAIT_RING, obs.green_weight,
                reason=f"at ring cap {cap}% ({obs.ready_slices}/"
                       f"{obs.desired_slices} whole rings); next wave "
                       "still provisioning")
        return UpgradeDecision(STEP, target,
                               reason=f"gate clean: {obs.green_weight}% "
                                      f"-> {target}%")


def green_slos(backend: str, ttft_target_s: float = 0.5,
               availability: float = 0.99,
               fast_window_s: float = 300.0,
               fast_burn: float = 14.0,
               min_samples: int = 5) -> List[SloSpec]:
    """Burn-rate specs scoped to ONE backend service — the green fleet
    under upgrade.  Availability counts ATTEMPTS, not client responses:
    a green connect failure that fails over to blue returns 200 to the
    client yet still lands an attempt + error on green's own series
    (gateway._note_attempt), so the gate sees the bad build even while
    retries keep users whole.  Latency reads the per-backend gateway
    histogram (``tpu_gateway_backend_latency_seconds{backend=...}``)."""
    scope = (("backend", backend),)
    return [
        SloSpec(name="upgrade-green-ttft", kind="latency",
                metric="tpu_gateway_backend_latency_seconds",
                labels=scope, threshold_s=ttft_target_s,
                fast_window_s=fast_window_s, fast_burn=fast_burn,
                min_samples=min_samples),
        SloSpec(name="upgrade-green-availability", kind="availability",
                total_family="tpu_gateway_backend_attempts_total",
                bad_families=("tpu_gateway_backend_errors_total",),
                series_labels=scope, objective=availability,
                fast_window_s=fast_window_s, fast_burn=fast_burn,
                min_samples=min_samples),
    ]


def regression_note(profile_diff: Optional[Dict[str, Any]]) -> str:
    """The ramp's one-line verdict on a build-vs-build trace diff —
    appended to rollback events and audit reasons so the message names
    WHERE the candidate got slower ("candidate slower in decode (...)"),
    not just that the burn-rate gate fired.  Empty when there is no
    diff or no gated regression survived the noise gate."""
    worst = worst_regression(profile_diff)
    if worst is None:
        return ""
    return f"candidate slower in {worst['kind']} " \
           f"({describe_regression(worst)})"


class BurnRateGate:
    """Green-fleet health verdicts for the ramp, one private
    :class:`AlertEngine` per backend under upgrade.

    Observational like the engine it wraps: reads registry snapshots and
    the clock only, so mounting it in the sim leaves replay hashes
    untouched.  ``verdict`` evaluates and answers whether any
    fast-window alert is firing on the backend's scoped specs — the
    slow window intentionally does not gate (a ramp holds minutes, not
    the hours a slow leak needs; the fleet-wide engine still watches
    it)."""

    def __init__(self, registry, clock=None, ttft_target_s: float = 0.5,
                 availability: float = 0.99, fast_window_s: float = 300.0,
                 fast_burn: float = 14.0, min_samples: int = 5):
        self.registry = registry
        self._clock = clock
        self._ttft_target_s = ttft_target_s
        self._availability = availability
        self._fast_window_s = fast_window_s
        self._fast_burn = fast_burn
        self._min_samples = min_samples
        self._engines: Dict[str, AlertEngine] = {}

    def _engine(self, backend: str) -> AlertEngine:
        engine = self._engines.get(backend)
        if engine is None:
            engine = AlertEngine(
                self.registry,
                specs=green_slos(backend,
                                 ttft_target_s=self._ttft_target_s,
                                 availability=self._availability,
                                 fast_window_s=self._fast_window_s,
                                 fast_burn=self._fast_burn,
                                 min_samples=self._min_samples),
                clock=self._clock)
            self._engines[backend] = engine
        return engine

    def verdict(self, backend: str
                ) -> Tuple[bool, Optional[Dict[str, Any]]]:
        """(healthy, firing_alert): healthy iff no fast-window alert is
        active on the backend's green-scoped specs after one evaluation
        pass."""
        engine = self._engine(backend)
        engine.evaluate()
        fast = [a for a in engine.active() if a["window"] == "fast"]
        if fast:
            return False, fast[0]
        return True, None

    def forget(self, backend: str) -> None:
        """Drop a backend's engine (after promote/abort) so a later
        upgrade of the same service starts with fresh windows."""
        self._engines.pop(backend, None)
