"""SLO-driven autoscaling signal: serve latency histograms -> demand.

Closes the serving loop the resource-driven :mod:`autoscaler` can't see:
``SliceAutoscaler`` scales on queued-TpuJob demand and slice idleness,
which says nothing about an inference fleet whose job set is static but
whose TTFT p99 just blew through its SLO.  :class:`ServeSloSignal` reads
the ``tpu_serve_request_duration_seconds{phase="ttft"}`` histogram the
engines observe (serve/engine.py) plus a pluggable queue-depth source
(the gateway's ``total_queue_depth``), evaluates a windowed p99 against
the target, and emits a **demand floor** the autoscaler merges with job
demand:

- sustained breach (>= ``breach_seconds``, outside ``cooldown_seconds``
  of the last scale verdict) -> floor = current + 1: `decide()` steps
  one slice up exactly as a queued job would ask it to;
- breach present but not yet sustained, or clear but not yet for
  ``clear_seconds`` -> floor = current: the group reads as *claimed*, so
  the idle reaper can't shrink it mid-recovery (this is the hysteresis:
  flapping latency never yields scale-down/scale-up oscillation);
- sustained clear -> floor = 0: the signal releases the group and the
  existing idle-timeout machinery reaps surplus slices.

Windowed p99 comes from **bucket deltas** between evaluations — the
histogram is cumulative, so subtracting the previous snapshot isolates
the requests observed since the last pass; the percentile interpolates
within the crossing bucket (the same inclusive-style estimate the bench
quantiles use, quantized to bucket edges).

Everything is clock-injectable (``clock.now``) so the hysteresis state
machine runs under the sim VirtualClock byte-identically.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable, Dict, Optional, Tuple

from kuberay_tpu.utils.quantiles import histogram_quantile

TTFT_METRIC = "tpu_serve_request_duration_seconds"


@dataclasses.dataclass
class SloPolicy:
    group: str = "workers"          # worker group the signal scales
    ttft_p99_target_s: float = 0.5  # the SLO
    queue_depth_high: int = 16      # fleet queue depth that alone breaches
    min_samples: int = 5            # window p99 needs this many requests
    breach_seconds: float = 15.0    # sustained breach before scale-up
    clear_seconds: float = 60.0     # sustained clear before release
    cooldown_seconds: float = 30.0  # min gap between scale-up verdicts
    # KV-capacity breach: scale up when the fleet's worst KV tier
    # (device or host, gateway.kv_tier_headroom) has less than this
    # fraction of blocks free.  A saturated hierarchy evicts session
    # blocks, which turns cheap resumes back into full prefills — a
    # latency cliff the TTFT window only sees after the fact.  0 = off.
    kv_headroom_low: float = 0.0


def histogram_delta_p99(prev: Optional[Dict], cur: Optional[Dict]
                        ) -> Tuple[float, int]:
    """(p99 seconds, samples) of the observations BETWEEN two snapshots
    of one cumulative histogram (utils.metrics histogram_snapshot
    layout).  No new samples -> (0.0, 0)."""
    if cur is None:
        return 0.0, 0
    counts = list(cur["counts"])
    if prev is not None and prev["buckets"] == cur["buckets"]:
        counts = [c - p for c, p in zip(counts, prev["counts"])]
    p99, n = histogram_quantile(cur["buckets"], counts, 0.99)
    return p99, int(n)


class ServeSloSignal:
    """Hysteresis state machine from serve latency to a demand floor.

    ``registry`` is the MetricsRegistry the serve engines/gateway
    observe into; ``queue_depth_fn`` (e.g. ``gateway.total_queue_depth``)
    contributes the load half of the breach predicate.  Thread-safe: the
    operator's background loop and debug handlers may race.
    """

    def __init__(self, registry, policy: Optional[SloPolicy] = None,
                 queue_depth_fn: Optional[Callable[[], int]] = None,
                 clock=None, phase: str = "ttft",
                 labels: Optional[Dict[str, str]] = None,
                 kv_headroom_fn: Optional[
                     Callable[[], Dict[str, float]]] = None):
        """``labels`` overrides the histogram series the signal windows
        (default ``{"phase": phase}``).  A disaggregated fleet runs one
        signal per tier — e.g. ``{"phase": "gateway-prefill"}`` with
        ``queue_depth_fn=lambda: gw.tier_queue_depth("prefill")`` scaling
        the prefill worker group, and the decode twin likewise — so a
        prompt-heavy burst raises only the tier that is actually
        breaching."""
        self.registry = registry
        self.policy = policy or SloPolicy()
        self.queue_depth_fn = queue_depth_fn
        # e.g. ``gateway.kv_tier_headroom`` -> {"device": frac, "host":
        # frac}; only consulted when policy.kv_headroom_low > 0.
        self.kv_headroom_fn = kv_headroom_fn
        self.phase = phase
        self.labels = dict(labels) if labels is not None else {"phase": phase}
        self._now = clock.now if clock is not None else time.time
        self._lock = threading.Lock()
        self._prev_snapshot: Optional[Dict] = None
        self._breach_since: Optional[float] = None
        self._clear_since: Optional[float] = None
        self._last_scale_up = float("-inf")

    def _sample_locked(self) -> Tuple[float, int, int]:
        cur = self.registry.histogram_snapshot(TTFT_METRIC, self.labels)
        p99, n = histogram_delta_p99(self._prev_snapshot, cur)
        self._prev_snapshot = cur
        qd = int(self.queue_depth_fn()) if self.queue_depth_fn else 0
        return p99, n, qd

    def demand_floor(self, current: int) -> Tuple[int, Dict]:
        """Evaluate once; returns (demand floor for the policy group,
        signal record for the DecisionAudit ring)."""
        pol = self.policy
        now = self._now()
        kv_headroom: Dict[str, float] = {}
        kv_breach = False
        if pol.kv_headroom_low > 0 and self.kv_headroom_fn is not None:
            kv_headroom = dict(self.kv_headroom_fn())
            kv_breach = bool(kv_headroom) and \
                min(kv_headroom.values()) < pol.kv_headroom_low
        with self._lock:
            p99, n, qd = self._sample_locked()
            latency_breach = n >= pol.min_samples and \
                p99 > pol.ttft_p99_target_s
            queue_breach = qd >= pol.queue_depth_high
            if latency_breach or queue_breach or kv_breach:
                self._clear_since = None
                if self._breach_since is None:
                    self._breach_since = now
                sustained = now - self._breach_since >= pol.breach_seconds
                cooled = now - self._last_scale_up >= pol.cooldown_seconds
                if sustained and cooled:
                    self._last_scale_up = now
                    state, floor = "scale_up", current + 1
                else:
                    state, floor = "breaching", current
            else:
                self._breach_since = None
                if self._clear_since is None:
                    self._clear_since = now
                if now - self._clear_since >= pol.clear_seconds:
                    state, floor = "clear", 0
                else:
                    state, floor = "holding", current
            breach_for = (now - self._breach_since
                          if self._breach_since is not None else 0.0)
            clear_for = (now - self._clear_since
                         if self._clear_since is not None else 0.0)
        return floor, {
            "group": pol.group,
            "series": dict(self.labels),
            "state": state,
            "ttft_p99_s": round(p99, 6),
            "ttft_p99_target_s": pol.ttft_p99_target_s,
            "window_samples": n,
            "queue_depth": qd,
            "queue_depth_high": pol.queue_depth_high,
            "kv_headroom": {t: round(v, 4)
                            for t, v in sorted(kv_headroom.items())},
            "kv_headroom_low": pol.kv_headroom_low,
            "breach_for_s": round(breach_for, 3),
            "clear_for_s": round(clear_for, 3),
            "floor": floor,
        }
