"""NetworkPolicy controller (ref networkpolicy_controller.go:33, spec at
raycluster_types.go:254-311).  Feature-gated ``TpuClusterNetworkPolicy``.

Creates head + worker NetworkPolicies per TpuCluster: intra-cluster traffic
(ICI bootstrap, coordinator, metrics) always allowed; external ingress
limited to the head's dashboard/serve ports from allowed namespaces;
``DenyAllEgress`` additionally locks egress to in-cluster peers.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from kuberay_tpu.api.tpucluster import TpuCluster
from kuberay_tpu.controlplane.store import AlreadyExists, NotFound, ObjectStore
from kuberay_tpu.utils import constants as C
from kuberay_tpu.utils import features
from kuberay_tpu.utils.names import truncate_name


def build_network_policies(cluster: TpuCluster) -> List[Dict[str, Any]]:
    spec = cluster.spec.networkPolicy
    name = cluster.metadata.name
    ns = cluster.metadata.namespace
    if spec is None or not spec.enabled:
        return []
    same_cluster = {"podSelector": {"matchLabels": {C.LABEL_CLUSTER: name}}}
    allowed_ns = [{"namespaceSelector": {"matchLabels": {
        "kubernetes.io/metadata.name": n}}} for n in spec.allowNamespaces]

    head = {
        "apiVersion": "networking.k8s.io/v1",
        "kind": "NetworkPolicy",
        "metadata": {
            "name": truncate_name(f"{name}-head"),
            "namespace": ns,
            "labels": {C.LABEL_CLUSTER: name},
            "ownerReferences": [{
                "apiVersion": C.API_VERSION, "kind": C.KIND_CLUSTER,
                "name": name, "uid": cluster.metadata.uid,
                "controller": True, "blockOwnerDeletion": True,
            }],
        },
        "spec": {
            "podSelector": {"matchLabels": {
                C.LABEL_CLUSTER: name, C.LABEL_NODE_TYPE: C.NODE_TYPE_HEAD}},
            "policyTypes": ["Ingress"] + (
                ["Egress"] if spec.mode == "DenyAllEgress" else []),
            "ingress": [
                {"from": [same_cluster]},
                {"from": allowed_ns or [{}],
                 "ports": [{"port": C.PORT_DASHBOARD}, {"port": C.PORT_SERVE},
                           {"port": C.PORT_METRICS}]},
            ],
        },
    }
    worker = {
        "apiVersion": "networking.k8s.io/v1",
        "kind": "NetworkPolicy",
        "metadata": {
            "name": truncate_name(f"{name}-workers"),
            "namespace": ns,
            "labels": {C.LABEL_CLUSTER: name},
            "ownerReferences": head["metadata"]["ownerReferences"],
        },
        "spec": {
            "podSelector": {"matchLabels": {
                C.LABEL_CLUSTER: name, C.LABEL_NODE_TYPE: C.NODE_TYPE_WORKER}},
            "policyTypes": ["Ingress"] + (
                ["Egress"] if spec.mode == "DenyAllEgress" else []),
            # Workers only talk to each other (ICI/MXLA bootstrap) and the
            # head; serve/metrics ingress follows the same namespace
            # restriction as the head (an unqualified ports-only rule would
            # admit every peer in K8s NetworkPolicy semantics).
            "ingress": [{"from": [same_cluster]},
                        {"from": allowed_ns or [{}],
                         "ports": [{"port": C.PORT_SERVE},
                                   {"port": C.PORT_METRICS}]}],
        },
    }
    if spec.mode == "DenyAllEgress":
        for pol in (head, worker):
            pol["spec"]["egress"] = [{"to": [same_cluster]}]
    return [head, worker]


class NetworkPolicyController:
    """Standalone controller like the reference's (registered separately)."""

    KIND = C.KIND_CLUSTER

    def __init__(self, store: ObjectStore):
        self.store = store

    def reconcile(self, name: str, namespace: str = "default") -> Optional[float]:
        if not features.enabled("TpuClusterNetworkPolicy"):
            return None
        raw = self.store.try_get(self.KIND, name, namespace)
        if raw is None or raw["metadata"].get("deletionTimestamp"):
            return None   # policies GC via ownerReferences
        cluster = TpuCluster.from_dict(raw)
        for pol in build_network_policies(cluster):
            cur = self.store.try_get("NetworkPolicy",
                                     pol["metadata"]["name"], namespace)
            if cur is None:
                try:
                    self.store.create(pol)
                except AlreadyExists:
                    pass
            elif cur["spec"] != pol["spec"]:
                cur["spec"] = pol["spec"]
                self.store.update(cur)
        return None
