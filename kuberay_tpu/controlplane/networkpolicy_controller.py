"""NetworkPolicy controller (ref networkpolicy_controller.go:33, spec at
raycluster_types.go:254-311).  Feature-gated ``TpuClusterNetworkPolicy``.

Creates head + worker NetworkPolicies per TpuCluster: intra-cluster traffic
(ICI bootstrap, coordinator, metrics) always allowed; external ingress
limited to the head's dashboard/serve ports from allowed namespaces;
``DenyAllEgress`` additionally locks egress to in-cluster peers.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from kuberay_tpu.api.tpucluster import TpuCluster
from kuberay_tpu.controlplane.store import NotFound, ObjectStore
from kuberay_tpu.utils import constants as C
from kuberay_tpu.utils import features
from kuberay_tpu.builders.common import owner_reference
from kuberay_tpu.utils.names import truncate_name


def build_network_policies(cluster: TpuCluster) -> List[Dict[str, Any]]:
    spec = cluster.spec.networkPolicy
    name = cluster.metadata.name
    ns = cluster.metadata.namespace
    if spec is None or not spec.enabled:
        return []
    same_cluster = {"podSelector": {"matchLabels": {C.LABEL_CLUSTER: name}}}
    allowed_ns = [{"namespaceSelector": {"matchLabels": {
        "kubernetes.io/metadata.name": n}}} for n in spec.allowNamespaces]

    def external_rule(ports):
        # K8s semantics: a rule with no `from` admits all peers; an empty
        # peer `{}` is INVALID. With no allowNamespaces configured the rule
        # intentionally opens the ports to all, by omitting `from`.
        rule = {"ports": ports}
        if allowed_ns:
            rule["from"] = allowed_ns
        return rule

    head = {
        "apiVersion": "networking.k8s.io/v1",
        "kind": "NetworkPolicy",
        "metadata": {
            "name": truncate_name(f"{name}-head"),
            "namespace": ns,
            "labels": {C.LABEL_CLUSTER: name},
            "ownerReferences": [owner_reference(
                C.KIND_CLUSTER, name, cluster.metadata.uid)],
        },
        "spec": {
            "podSelector": {"matchLabels": {
                C.LABEL_CLUSTER: name, C.LABEL_NODE_TYPE: C.NODE_TYPE_HEAD}},
            "policyTypes": ["Ingress"] + (
                ["Egress"] if spec.mode == "DenyAllEgress" else []),
            "ingress": [
                {"from": [same_cluster]},
                external_rule([{"port": C.PORT_DASHBOARD},
                               {"port": C.PORT_SERVE},
                               {"port": C.PORT_METRICS}]),
            ],
        },
    }
    worker = {
        "apiVersion": "networking.k8s.io/v1",
        "kind": "NetworkPolicy",
        "metadata": {
            "name": truncate_name(f"{name}-workers"),
            "namespace": ns,
            "labels": {C.LABEL_CLUSTER: name},
            "ownerReferences": head["metadata"]["ownerReferences"],
        },
        "spec": {
            "podSelector": {"matchLabels": {
                C.LABEL_CLUSTER: name, C.LABEL_NODE_TYPE: C.NODE_TYPE_WORKER}},
            "policyTypes": ["Ingress"] + (
                ["Egress"] if spec.mode == "DenyAllEgress" else []),
            # Workers only talk to each other (ICI/MXLA bootstrap) and the
            # head; serve/metrics ingress follows the same namespace
            # restriction as the head (an unqualified ports-only rule would
            # admit every peer in K8s NetworkPolicy semantics).
            "ingress": [{"from": [same_cluster]},
                        external_rule([{"port": C.PORT_SERVE},
                                       {"port": C.PORT_METRICS}])],
        },
    }
    if spec.mode == "DenyAllEgress":
        for pol in (head, worker):
            pol["spec"]["egress"] = [{"to": [same_cluster]}]
    return [head, worker]


class NetworkPolicyController:
    """Standalone controller like the reference's (registered separately)."""

    KIND = C.KIND_CLUSTER

    def __init__(self, store: ObjectStore):
        self.store = store

    def reconcile(self, name: str, namespace: str = "default") -> Optional[float]:
        # kuberay-lint: disable-next-line=reconcile-exception-escape -- FeatureGateError means a typo'd compile-time gate constant; crashing into backoff is the loudest correct behavior
        if not features.enabled("TpuClusterNetworkPolicy"):
            return None
        raw = self.store.try_get(self.KIND, name, namespace)
        if raw is None or raw["metadata"].get("deletionTimestamp"):
            return None   # policies GC via ownerReferences
        cluster = TpuCluster.from_dict(raw)
        desired = build_network_policies(cluster)
        for pol in desired:
            self.store.ensure(pol)
        # Disabling the feature must remove previously created policies —
        # otherwise stale DenyAll rules keep enforcing after opt-out.
        desired_names = {p["metadata"]["name"] for p in desired}
        for cur in self.store.list("NetworkPolicy", namespace,
                                   labels={C.LABEL_CLUSTER: name}):
            if cur["metadata"]["name"] not in desired_names:
                try:
                    self.store.delete("NetworkPolicy",
                                      cur["metadata"]["name"], namespace)
                except NotFound:
                    pass
        return None
