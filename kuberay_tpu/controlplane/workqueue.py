"""Deduplicating, per-key-serialized work queue (controller-runtime
semantics: workqueue.Type's dirty/processing sets).

The contract that makes ``Manager.start(workers=N)`` safe AND fast:

- **Dedup while queued**: adding a key already waiting is a no-op.
- **Per-key serialization**: a key being processed is never handed to a
  second worker.  A popped key still held by another worker parks in
  the *dirty* set and re-queues the moment that worker calls
  :meth:`done` — so the triggering event is never lost, it is coalesced
  into one more level-triggered pass.  (Without this, two workers
  reconcile the same object concurrently and race their status writes —
  the latent bug the old list+set queue had.)
- **O(1) pops**: a deque, not ``list.pop(0)``.

Unlike controller-runtime (which parks in-flight re-adds in dirty at
Add time), a re-added in-flight key here enters the queue immediately
and the serialization check happens at :meth:`get`.  Single-threaded
draining (``run_until_idle`` — the deterministic-sim mode) therefore
processes keys in exactly the order the old dedup queue did, which is
what keeps chaos-replay journal hashes byte-identical; the observable
guarantees under concurrency are the same as controller-runtime's.

Timed re-adds (:meth:`add_after`) sit in a heap against the injected
``now_fn`` clock (the sim's virtual clock or wall time) and promote
through :meth:`add` when due.

Metrics (fed through the optional ``metrics`` facade —
``tpu_workqueue_depth`` / ``tpu_workqueue_latency_seconds``) and the
tracer's ``queued``/``dequeued`` seams stay at the Manager layer; the
queue itself only tracks per-key enqueue instants so latency is
measured from the FIRST pending cause (dedup keeps the earliest).
"""

from __future__ import annotations

import heapq
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Set, Tuple

Key = Tuple[str, str, str]


class WorkQueue:
    def __init__(self, now_fn: Optional[Callable[[], float]] = None,
                 metrics=None, name: str = "manager"):
        self._now = now_fn or time.time
        self._metrics = metrics
        self._name = name
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._queue: deque = deque()
        self._queued: Set[Key] = set()       # waiting in self._queue
        self._dirty: Set[Key] = set()        # needs another pass when done
        self._processing: Set[Key] = set()   # held by a worker right now
        self._delayed: List[Tuple[float, Key]] = []
        self._added_at: Dict[Key, float] = {}
        self._shutdown = False
        # Paused queues accumulate (and dedup) but hand nothing out —
        # the per-shard lease-handoff state (sharding.py): a shard whose
        # lease moved away keeps absorbing events so a later
        # re-acquisition resumes level-triggered, but its workers go
        # idle instead of racing the new owner.
        self._paused = False

    # -- producers ---------------------------------------------------------

    def add(self, key: Key) -> None:
        with self._cond:
            self._add_locked(key)
            self._cond.notify()

    def _add_locked(self, key: Key) -> None:
        self._added_at.setdefault(key, self._now())
        if key in self._dirty:
            return   # already coalesced; done() will requeue it
        if key not in self._queued:
            self._queued.add(key)
            self._queue.append(key)
            self._report_depth()

    def add_after(self, key: Key, after: float) -> None:
        if after <= 0:
            self.add(key)
            return
        with self._cond:
            # (deadline, key) on purpose: equal deadlines pop in key
            # order — a deterministic tiebreak the sim replay contract
            # depends on (virtual-clock requeues often share an instant).
            heapq.heappush(self._delayed, (self._now() + after, key))
            self._cond.notify()

    # -- consumers ---------------------------------------------------------

    def get(self, block: bool = True) -> Optional[Key]:
        """Next key, or None (non-blocking empty / shutdown).  The key is
        marked *processing* until the caller's :meth:`done`."""
        with self._cond:
            while True:
                self._promote_due_locked()
                while self._queue and not self._paused:
                    key = self._queue.popleft()
                    self._queued.discard(key)
                    if key in self._processing:
                        # Another worker holds this key: park it dirty;
                        # done() re-queues it.  Never hand one key to
                        # two workers.
                        self._dirty.add(key)
                        self._report_depth()
                        continue
                    self._processing.add(key)
                    self._report_depth()
                    added = self._added_at.pop(key, None)
                    if added is not None and self._metrics is not None:
                        self._metrics.workqueue_latency(
                            self._name, max(0.0, self._now() - added))
                    return key
                if not block or self._shutdown:
                    return None
                timeout = 1.0
                if self._delayed:
                    timeout = max(0.0, min(
                        timeout, self._delayed[0][0] - self._now()))
                self._cond.wait(timeout=timeout)

    def done(self, key: Key) -> None:
        """The worker finished this key.  A re-add that arrived while it
        was in flight (dirty) queues it again — never to two workers at
        once, never lost."""
        with self._cond:
            self._processing.discard(key)
            if key in self._dirty and key not in self._queued:
                self._dirty.discard(key)
                self._queued.add(key)
                self._queue.append(key)
                self._report_depth()
                self._cond.notify()
            if not self._processing:
                self._cond.notify_all()   # wake wait_idle_processing

    # -- pause / drain (per-shard lease handoff) ---------------------------

    def pause(self) -> None:
        """Stop handing keys out.  Adds/dedup/timed requeues keep
        accumulating; in-flight keys finish normally via :meth:`done`."""
        with self._cond:
            self._paused = True

    def resume(self) -> None:
        with self._cond:
            self._paused = False
            self._cond.notify_all()

    @property
    def paused(self) -> bool:
        with self._lock:
            return self._paused

    def wait_idle_processing(self, timeout: float = 5.0) -> bool:
        """Block until no key is in flight (the lease-handoff drain
        barrier — pause first or new pops keep the horizon open).
        Returns False on timeout."""
        deadline = time.monotonic() + timeout
        with self._cond:
            while self._processing:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(timeout=remaining)
            return True

    # -- timed re-adds -----------------------------------------------------

    def _promote_due_locked(self) -> None:
        now = self._now()
        while self._delayed and self._delayed[0][0] <= now:
            _, key = heapq.heappop(self._delayed)
            self._add_locked(key)

    def next_delayed_at(self) -> Optional[float]:
        """Earliest timed-re-add deadline (``now_fn`` clock domain), or
        None.  The sim harness advances its virtual clock exactly here."""
        with self._lock:
            return self._delayed[0][0] if self._delayed else None

    def flush_delayed(self) -> None:
        """Promote ALL timed re-adds immediately (tests: 'advance time')."""
        with self._cond:
            while self._delayed:
                _, key = heapq.heappop(self._delayed)
                self._add_locked(key)
            self._cond.notify_all()

    # -- lifecycle / introspection -----------------------------------------

    def shutdown(self) -> None:
        with self._cond:
            self._shutdown = True
            self._cond.notify_all()

    def restart(self) -> None:
        with self._cond:
            self._shutdown = False

    def depth(self) -> int:
        with self._lock:
            return len(self._queue)

    def delayed_len(self) -> int:
        with self._lock:
            return len(self._delayed)

    def delayed_items(self) -> List[Tuple[float, Key]]:
        """Scheduled (deadline, key) pairs, soonest first (introspection)."""
        with self._lock:
            return sorted(self._delayed)

    def _report_depth(self) -> None:
        if self._metrics is not None:
            self._metrics.workqueue_depth(self._name, len(self._queue))
