"""Copy-on-write snapshots: the store's zero-deepcopy read path.

The :class:`~kuberay_tpu.controlplane.store.ObjectStore` keeps committed
objects logically immutable — every mutator builds a NEW object (sharing
unchanged subtrees with the previous revision) and swaps it in, exactly
like the reference's informer cache hands out shared read-only objects
(SURVEY §4: mutating a cache object corrupts every other reader).

Reads therefore no longer deepcopy.  ``get``/``list``/watch events
return the committed object wrapped in a :class:`CowDict`: a real
``dict`` whose top level is a shallow copy and whose nested dict/list
values are wrapped lazily on first access.  Mutating a wrapper (or
anything reached through one) lands in wrapper-local storage only — the
committed object is never touched — so every pre-existing
read-modify-write caller keeps its exact semantics at a fraction of the
cost: a reconciler that reads a 60-field Pod and touches
``status.phase`` pays for two shallow dict copies, not a whole-object
deep copy.

``copy.deepcopy`` of a wrapper returns a plain ``dict``/``list`` (the
store's write-path entry deepcopy therefore also materializes wrapper
input), and legacy callers that need a fully private plain object up
front can pass ``deep=True`` to ``get``/``try_get``/``list``.

Contract for callers (enforced by tests/test_store_perf_contract.py):
mutate snapshots only THROUGH the wrapper.  Unpacking a wrapper into a
plain dict (``{**snap}`` / ``dict(snap)`` / ``snap.copy()``) yields raw
committed subtrees for any value not yet accessed — treat such spreads
as read-only (or deepcopy first).
"""

from __future__ import annotations

import copy
from typing import Any

__all__ = ["CowDict", "CowList", "snapshot"]


def _wrap(value: Any) -> Any:
    """Wrap exactly the committed-object container types.  Exact type
    checks on purpose: an already-wrapped value passes through, and
    scalars (str/int/float/bool/None) need no isolation."""
    t = type(value)
    if t is dict:
        return CowDict(value)
    if t is list:
        return CowList(value)
    return value


class CowDict(dict):
    """A dict snapshot of a committed object (sub)tree.

    Construction shallow-copies the source's top level; nested dicts and
    lists stay shared with the committed object until first access, when
    they are wrapped (one more shallow copy) and cached back in place.
    All mutation hits this wrapper's own storage — never the source.
    """

    __slots__ = ()

    def __getitem__(self, key):
        value = dict.__getitem__(self, key)
        wrapped = _wrap(value)
        if wrapped is not value:
            dict.__setitem__(self, key, wrapped)
        return wrapped

    def get(self, key, default=None):
        try:
            return self[key]
        except KeyError:
            return default

    def setdefault(self, key, default=None):
        if key in self:
            return self[key]
        dict.__setitem__(self, key, default)
        return default

    def pop(self, key, *default):
        # The popped value leaves this wrapper, so wrap it on the way
        # out: handing the caller a raw committed subtree would let a
        # later mutation reach the store.
        try:
            value = dict.pop(self, key)
        except KeyError:
            if default:
                return default[0]
            raise
        return _wrap(value)

    def popitem(self):
        key, value = dict.popitem(self)
        return key, _wrap(value)

    def items(self):
        return [(key, self[key]) for key in dict.keys(self)]

    def values(self):
        return [self[key] for key in dict.keys(self)]

    def copy(self):
        return CowDict(self)

    def __deepcopy__(self, memo):
        # Materialize: deepcopying a snapshot yields a plain dict, which
        # is what the store's write-path entry deepcopy (and legacy
        # ``deep=True`` callers) rely on.
        return {key: copy.deepcopy(value, memo)
                for key, value in dict.items(self)}

    def __reduce__(self):
        # Pickle as the materialized plain dict (wrappers are views).
        return (dict, (), None, None, iter(dict.items(self)))


class CowList(list):
    """List counterpart of :class:`CowDict`: shallow element copy up
    front, element wrapping on access/iteration."""

    __slots__ = ()

    def __getitem__(self, index):
        if isinstance(index, slice):
            return [self[i] for i in range(*index.indices(list.__len__(self)))]
        value = list.__getitem__(self, index)
        wrapped = _wrap(value)
        if wrapped is not value:
            list.__setitem__(self, index, wrapped)
        return wrapped

    def __iter__(self):
        for i in range(list.__len__(self)):
            yield self[i]

    def pop(self, index=-1):
        return _wrap(list.pop(self, index))

    def copy(self):
        return CowList(self)

    def __deepcopy__(self, memo):
        return [copy.deepcopy(value, memo) for value in list.__iter__(self)]

    def __reduce__(self):
        return (list, (), None, iter(list.__iter__(self)))


def snapshot(obj: dict) -> CowDict:
    """The store's read-path wrapper for one committed object."""
    return CowDict(obj)
