"""Leader election (ref main.go:232 ``ray-operator-leader`` via
controller-runtime's Lease-based election).

A ``Lease`` object in the store is the lock: the holder renews
``renewTime`` every ``renew_interval``; others take over once
``lease_duration`` passes without a renewal.  Acquisition and takeover go
through optimistic-concurrency updates, so exactly one candidate can win
any given transition — the single-writer-per-CR guarantee multi-replica
operators need.
"""

from __future__ import annotations

import logging
import threading
import time
import uuid
from typing import Callable, Optional

from kuberay_tpu.controlplane.store import (
    AlreadyExists,
    Conflict,
    NotFound,
    ObjectStore,
)

LEASE_NAME = "kuberay-tpu-operator-leader"

_LOG = logging.getLogger("kuberay_tpu.leader")


class LeaderElector:
    def __init__(self, store: ObjectStore, identity: Optional[str] = None,
                 namespace: str = "default",
                 lease_duration: float = 15.0,
                 renew_interval: float = 5.0,
                 on_started_leading: Optional[Callable[[], None]] = None,
                 on_stopped_leading: Optional[Callable[[], None]] = None):
        self.store = store
        self.identity = identity or f"operator-{uuid.uuid4().hex[:8]}"
        self.namespace = namespace
        self.lease_duration = lease_duration
        self.renew_interval = renew_interval
        self.on_started_leading = on_started_leading
        self.on_stopped_leading = on_stopped_leading
        self._is_leader = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @property
    def is_leader(self) -> bool:
        return self._is_leader

    # ------------------------------------------------------------------

    def _try_acquire_or_renew(self) -> bool:
        now = time.time()
        lease = self.store.try_get("Lease", LEASE_NAME, self.namespace)
        if lease is None:
            try:
                self.store.create({
                    "apiVersion": "coordination.k8s.io/v1", "kind": "Lease",
                    "metadata": {"name": LEASE_NAME,
                                 "namespace": self.namespace},
                    "spec": {"holderIdentity": self.identity,
                             "renewTime": now,
                             "leaseDurationSeconds": self.lease_duration},
                    "status": {},
                })
                return True
            except AlreadyExists:
                return False   # racer won; retry next tick
        holder = lease["spec"].get("holderIdentity", "")
        renew = float(lease["spec"].get("renewTime", 0.0))
        expired = now - renew > self.lease_duration
        if holder != self.identity and not expired:
            return False
        # Renew (ours) or take over (expired): optimistic update — exactly
        # one contender's rv matches.
        lease["spec"]["holderIdentity"] = self.identity
        lease["spec"]["renewTime"] = now
        try:
            self.store.update(lease)
            return True
        except (Conflict, NotFound):
            return False

    def _loop(self, stop: threading.Event):
        while not stop.is_set():
            leading = False
            try:
                leading = self._try_acquire_or_renew()
            except Exception:
                leading = False
            if leading and not self._is_leader:
                self._is_leader = True
                if self.on_started_leading:
                    try:
                        self.on_started_leading()
                    except Exception:
                        # A callback bug must not kill renewal — but it
                        # must be VISIBLE, or the operator "leads" while
                        # its reconcilers never started.
                        _LOG.exception("on_started_leading callback failed")
            elif not leading and self._is_leader:
                self._is_leader = False
                if self.on_stopped_leading:
                    try:
                        self.on_stopped_leading()
                    except Exception:
                        _LOG.exception("on_stopped_leading callback failed")
            stop.wait(self.renew_interval if leading
                      else min(self.renew_interval, 2.0))

    def start(self):
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop,
                                        args=(self._stop,), daemon=True,
                                        name="leader-elector")
        self._thread.start()

    def stop(self, release: bool = True):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
        was_leader = self._is_leader
        self._is_leader = False
        if release and was_leader:
            # Graceful handoff: zero the renew time so a successor takes
            # over immediately instead of waiting out the lease.
            try:
                lease = self.store.try_get("Lease", LEASE_NAME,
                                           self.namespace)
                if lease is not None and \
                        lease["spec"].get("holderIdentity") == self.identity:
                    lease["spec"]["renewTime"] = 0.0
                    self.store.update(lease)
            except (Conflict, NotFound):
                pass
