"""Leader election (ref main.go:232 ``ray-operator-leader`` via
controller-runtime's Lease-based election).

A ``Lease`` object in the store is the lock: the holder renews
``renewTime`` every ``renew_interval``; others take over once
``lease_duration`` passes without a renewal.  Acquisition and takeover go
through optimistic-concurrency updates, so exactly one candidate can win
any given transition — the single-writer-per-CR guarantee multi-replica
operators need.

Two granularities:

- :class:`LeaderElector` — the classic whole-operator lease
  (``kuberay-tpu-operator-leader``): one replica reconciles, the rest
  stand by.
- :class:`ShardLeaseElector` — one lease **per reconcile shard**
  (``kuberay-tpu-operator-shard-<i>``, sharding.py): N operator
  processes split the shard set instead of N-1 of them idling.  Each
  shard still has exactly one holder at a time (same optimistic-update
  lock), so the global per-key serialization guarantee survives the
  split: key -> exactly one shard -> exactly one holder -> exactly one
  worker.  ``max_owned`` caps how many shards one process grabs, which
  is what makes the split balance instead of first-runner-takes-all
  (docs/scaling.md).
"""

from __future__ import annotations

import logging
import threading
import time
import uuid
from typing import Callable, Optional

from kuberay_tpu.controlplane.store import (
    AlreadyExists,
    Conflict,
    NotFound,
    ObjectStore,
)

LEASE_NAME = "kuberay-tpu-operator-leader"
SHARD_LEASE_PREFIX = "kuberay-tpu-operator-shard-"

_LOG = logging.getLogger("kuberay_tpu.leader")


def shard_lease_name(shard: int) -> str:
    return f"{SHARD_LEASE_PREFIX}{shard}"


class LeaderElector:
    def __init__(self, store: ObjectStore, identity: Optional[str] = None,
                 namespace: str = "default",
                 lease_duration: float = 15.0,
                 renew_interval: float = 5.0,
                 on_started_leading: Optional[Callable[[], None]] = None,
                 on_stopped_leading: Optional[Callable[[], None]] = None,
                 lease_name: str = LEASE_NAME):
        self.store = store
        self.identity = identity or f"operator-{uuid.uuid4().hex[:8]}"
        self.namespace = namespace
        self.lease_name = lease_name
        self.lease_duration = lease_duration
        self.renew_interval = renew_interval
        self.on_started_leading = on_started_leading
        self.on_stopped_leading = on_stopped_leading
        self._is_leader = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @property
    def is_leader(self) -> bool:
        return self._is_leader

    # ------------------------------------------------------------------

    def _try_acquire_or_renew(self) -> bool:
        now = time.time()
        lease = self.store.try_get("Lease", self.lease_name, self.namespace)
        if lease is None:
            try:
                self.store.create({
                    "apiVersion": "coordination.k8s.io/v1", "kind": "Lease",
                    "metadata": {"name": self.lease_name,
                                 "namespace": self.namespace},
                    "spec": {"holderIdentity": self.identity,
                             "renewTime": now,
                             "leaseDurationSeconds": self.lease_duration},
                    "status": {},
                })
                return True
            except AlreadyExists:
                return False   # racer won; retry next tick
        holder = lease["spec"].get("holderIdentity", "")
        renew = float(lease["spec"].get("renewTime", 0.0))
        expired = now - renew > self.lease_duration
        if holder != self.identity and not expired:
            return False
        # Renew (ours) or take over (expired): optimistic update — exactly
        # one contender's rv matches.
        lease["spec"]["holderIdentity"] = self.identity
        lease["spec"]["renewTime"] = now
        try:
            self.store.update(lease)
            return True
        except (Conflict, NotFound):
            return False

    def _loop(self, stop: threading.Event):
        while not stop.is_set():
            leading = False
            try:
                leading = self._try_acquire_or_renew()
            except Exception:
                leading = False
            if leading and not self._is_leader:
                self._is_leader = True
                if self.on_started_leading:
                    try:
                        self.on_started_leading()
                    except Exception:
                        # A callback bug must not kill renewal — but it
                        # must be VISIBLE, or the operator "leads" while
                        # its reconcilers never started.
                        _LOG.exception("on_started_leading callback failed")
            elif not leading and self._is_leader:
                self._is_leader = False
                if self.on_stopped_leading:
                    try:
                        self.on_stopped_leading()
                    except Exception:
                        _LOG.exception("on_stopped_leading callback failed")
            stop.wait(self.renew_interval if leading
                      else min(self.renew_interval, 2.0))

    def start(self):
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop,
                                        args=(self._stop,), daemon=True,
                                        name="leader-elector")
        self._thread.start()

    def stop(self, release: bool = True):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
        was_leader = self._is_leader
        self._is_leader = False
        if release and was_leader:
            self._release_lease()

    def _release_lease(self):
        # Graceful handoff: zero the renew time so a successor takes
        # over immediately instead of waiting out the lease.
        try:
            lease = self.store.try_get("Lease", self.lease_name,
                                       self.namespace)
            if lease is not None and \
                    lease["spec"].get("holderIdentity") == self.identity:
                lease["spec"]["renewTime"] = 0.0
                self.store.update(lease)
        except (Conflict, NotFound):
            pass


class ShardLeaseElector:
    """Per-shard lease ownership for a sharded control plane.

    One ``Lease`` per reconcile shard; each tick this process renews the
    shards it holds and tries to acquire unheld/expired ones, up to
    ``max_owned``.  The cap is the balancing mechanism: with R replicas
    and S shards, run each with ``max_owned = ceil(S / R)`` and the
    fleet converges to an even split — a dead replica's shards expire
    and are absorbed by survivors (who may exceed their cap only via
    explicit ``None``).

    ``on_acquired(shard)`` / ``on_released(shard)`` fire on ownership
    edges, on the elector thread: wire them to
    :meth:`Manager.acquire_shard` / :meth:`Manager.release_shard` — the
    release path pauses + drains the pool BEFORE the lease can move, so
    a successor never overlaps in-flight reconciles.
    """

    def __init__(self, store: ObjectStore, shards: int,
                 identity: Optional[str] = None,
                 namespace: str = "default",
                 lease_duration: float = 15.0,
                 renew_interval: float = 5.0,
                 max_owned: Optional[int] = None,
                 on_acquired: Optional[Callable[[int], None]] = None,
                 on_released: Optional[Callable[[int], None]] = None):
        if shards < 1:
            raise ValueError(f"shards must be >= 1, got {shards}")
        self.shards = shards
        self.identity = identity or f"operator-{uuid.uuid4().hex[:8]}"
        self.max_owned = max_owned
        self.renew_interval = renew_interval
        self.on_acquired = on_acquired
        self.on_released = on_released
        # One (thread-less) elector per shard lease: reuses the exact
        # acquire/renew/takeover optimistic-update logic of the
        # whole-operator lease.
        self._electors = [
            LeaderElector(store, identity=self.identity,
                          namespace=namespace,
                          lease_duration=lease_duration,
                          renew_interval=renew_interval,
                          lease_name=shard_lease_name(i))
            for i in range(shards)
        ]
        self._owned: set = set()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def owned(self) -> set:
        with self._lock:
            return set(self._owned)

    def tick(self):
        """One acquire/renew pass over every shard lease (also the
        deterministic test entry point — no thread required)."""
        for shard, elector in enumerate(self._electors):
            with self._lock:
                holding = shard in self._owned
                at_cap = (self.max_owned is not None
                          and len(self._owned) >= self.max_owned)
            if not holding and at_cap:
                continue   # leave unheld shards for other replicas
            try:
                won = elector._try_acquire_or_renew()
            except Exception:
                _LOG.exception("shard %d lease tick failed", shard)
                won = False
            if won and not holding:
                with self._lock:
                    self._owned.add(shard)
                self._edge(self.on_acquired, shard, "acquired")
            elif not won and holding:
                # Lost the renewal race (or the lease was taken over):
                # release locally FIRST so the drain happens before we
                # ever try to re-acquire.
                with self._lock:
                    self._owned.discard(shard)
                self._edge(self.on_released, shard, "released")

    def _edge(self, cb: Optional[Callable[[int], None]], shard: int,
              what: str):
        if cb is None:
            return
        try:
            cb(shard)
        except Exception:
            # Callback bugs must not kill renewal — but silently
            # "owning" a shard whose reconcilers never started (or
            # never drained) is worse than noisy, so log loudly.
            _LOG.exception("shard %d on_%s callback failed", shard, what)

    def release_shard(self, shard: int):
        """Voluntarily shed one shard (rebalance / graceful shutdown):
        local release + zeroed renewTime so a peer absorbs it now."""
        with self._lock:
            if shard not in self._owned:
                return
            self._owned.discard(shard)
        self._edge(self.on_released, shard, "released")
        self._electors[shard]._release_lease()

    def _loop(self, stop: threading.Event):
        while not stop.is_set():
            self.tick()
            stop.wait(self.renew_interval)

    def start(self):
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop,
                                        args=(self._stop,), daemon=True,
                                        name="shard-lease-elector")
        self._thread.start()

    def stop(self, release: bool = True):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        for shard in sorted(self.owned()):
            if release:
                self.release_shard(shard)
            else:
                with self._lock:
                    self._owned.discard(shard)
                self._edge(self.on_released, shard, "released")
