"""Fake kubelet: runs pods that nobody actually runs.

The envtest analogue (SURVEY.md §4 tier 2: "pods are never actually run;
tests manually flip pod phases") promoted to a reusable component: it
watches the store and advances pod phases Pending -> Running, assigns pod
IPs, and can be told to fail specific pods — which is also the framework's
fault-injection hook (ref fail.py / pod-kill e2e patterns, §5.3).
"""

from __future__ import annotations

import itertools
from typing import Dict, Optional, Set

from kuberay_tpu.controlplane.store import NotFound, ObjectStore


class FakeKubelet:
    def __init__(self, store: ObjectStore, auto_run: bool = True):
        self.store = store
        self.auto_run = auto_run
        self._ip = itertools.count(1)
        self._fail_next: Set[str] = set()

    def fail_pod(self, name: str, namespace: str = "default"):
        """Inject a failure: the pod transitions to Failed."""
        pod = self.store.try_get("Pod", name, namespace)
        if pod is None:
            self._fail_next.add(f"{namespace}/{name}")
            return
        pod["status"] = {**pod.get("status", {}), "phase": "Failed"}
        self.store.update_status(pod)

    def step(self) -> int:
        """Advance every Pending pod one phase; returns pods touched."""
        touched = 0
        for pod in self.store.list("Pod"):
            md = pod["metadata"]
            key = f"{md['namespace']}/{md['name']}"
            phase = pod.get("status", {}).get("phase", "Pending")
            if md.get("deletionTimestamp"):
                continue
            if key in self._fail_next:
                self._fail_next.discard(key)
                pod["status"] = {"phase": "Failed"}
                self.store.update_status(pod)
                touched += 1
                continue
            if phase == "Pending" and self.auto_run:
                pod["status"] = {
                    "phase": "Running",
                    "podIP": f"10.0.{next(self._ip) // 256}.{next(self._ip) % 256}",
                    "conditions": [{"type": "Ready", "status": "True"}],
                }
                try:
                    self.store.update_status(pod)
                    touched += 1
                except NotFound:
                    pass
        return touched
