"""Fake kubelet: runs pods that nobody actually runs.

The envtest analogue (SURVEY.md §4 tier 2: "pods are never actually run;
tests manually flip pod phases") promoted to a reusable component: it
watches the store and advances pod phases Pending -> Running, assigns pod
IPs, and can be told to fail specific pods — which is also the framework's
fault-injection hook (ref fail.py / pod-kill e2e patterns, §5.3).

Event-driven: pod creations queue their keys via a store watch, so a
``step()`` touches only new/failed pods — O(changes), not O(all pods)
(what makes the 5k/10k-cluster scale benches measure the operator rather
than the harness).

Fault surface (consumed by tests and kuberay_tpu.sim):
- ``fail_pod`` / ``fail_slice``: transition one pod / every host of a
  slice to Failed, MERGING over the existing status so the last-reported
  ``podIP`` and conditions survive — exactly what a real kubelet reports
  for a dead container;
- ``hold_pod``: slow-start injection — the pod stays Pending until the
  given instant (``now_fn`` domain; the sim passes virtual time);
- ``resync``: the periodic kubelet relist, which is what recovers pods
  whose ADDED watch event was dropped by chaos.

Deterministic: batches iterate in sorted key order, so the event history
of a run is a pure function of the store history and injected faults.
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Callable, Dict, Optional, Set

from kuberay_tpu.controlplane.store import Conflict, Event, NotFound, ObjectStore
from kuberay_tpu.obs.trace import NOOP_TRACER
from kuberay_tpu.utils import constants as C


def _fail_status(pod: dict) -> dict:
    """Failed phase merged over the pod's last status: a killed pod still
    reports its last IP and conditions (the kubelet never wipes them)."""
    return {**pod.get("status", {}), "phase": "Failed"}


def _pod_owner_key(pod: dict):
    """The reconcile-chain key a pod's lifecycle belongs to: its owning
    TpuCluster (cluster label) or WarmSlicePool (pool label) — where the
    tracer parents pod-start spans so slice-ready durations decompose."""
    labels = pod.get("metadata", {}).get("labels", {}) or {}
    ns = pod.get("metadata", {}).get("namespace", "default")
    cluster = labels.get(C.LABEL_CLUSTER)
    if cluster:
        return (C.KIND_CLUSTER, ns, cluster)
    pool = labels.get("tpu.dev/warm-pool")   # warmpool_controller label
    if pool:
        return ("WarmSlicePool", ns, pool)
    return None


class FakeKubelet:
    def __init__(self, store: ObjectStore, auto_run: bool = True,
                 now_fn: Optional[Callable[[], float]] = None,
                 tracer=None):
        self.store = store
        self.auto_run = auto_run
        self._now = now_fn or time.time
        # Span annotations (pod-start) — no-op by default.
        self.tracer = tracer or NOOP_TRACER
        self._ip = itertools.count(1)
        self._lock = threading.Lock()
        self._pending: Set[tuple] = set()       # (ns, name)
        self._fail_next: Set[tuple] = set()
        self._hold_until: Dict[tuple, float] = {}   # (ns, name) -> release
        # Watch FIRST, then backfill — the set dedups the overlap, and the
        # reverse order would lose pods created in the gap.
        self._cancel = store.watch(self._on_event)
        self.resync()

    def close(self):
        self._cancel()

    def _on_event(self, ev: Event):
        if ev.kind != "Pod":
            return
        md = ev.obj.get("metadata", {})
        key = (md.get("namespace", "default"), md.get("name", ""))
        with self._lock:
            if ev.type == Event.ADDED:
                self._pending.add(key)
            elif ev.type == Event.DELETED:
                self._pending.discard(key)
                self._fail_next.discard(key)
                self._hold_until.pop(key, None)

    def resync(self) -> int:
        """Relist Pending pods into the work set (the kubelet's periodic
        resync): recovers pods whose creation event was lost (dropped
        watch delivery under chaos, or pods created before this kubelet
        attached).  Returns how many keys were (re)queued."""
        n = 0
        for pod in self.store.list("Pod"):
            md = pod["metadata"]
            if pod.get("status", {}).get("phase", "Pending") == "Pending":
                with self._lock:
                    self._pending.add((md["namespace"], md["name"]))
                n += 1
        return n

    def fail_pod(self, name: str, namespace: str = "default"):
        """Inject a failure: the pod transitions to Failed."""
        pod = self.store.try_get("Pod", name, namespace)
        if pod is None:
            with self._lock:
                self._fail_next.add((namespace, name))
            return
        pod["status"] = _fail_status(pod)
        self.store.update_status(pod)

    def fail_slice(self, slice_name: str, namespace: str = "default") -> int:
        """Node-drain analogue: every host of the slice fails together
        (pods share a node pool; a drained node takes the whole ICI ring
        down).  Returns pods failed."""
        pods = self.store.list("Pod", namespace,
                               labels={C.LABEL_SLICE_NAME: slice_name})
        for pod in pods:
            self.fail_pod(pod["metadata"]["name"], namespace)
        return len(pods)

    def hold_pod(self, name: str, namespace: str = "default",
                 until: float = float("inf")):
        """Slow-start injection: the pod stays Pending until ``until``
        (``now_fn`` clock domain), then runs on a later ``step()``."""
        with self._lock:
            self._hold_until[(namespace, name)] = until
            self._pending.add((namespace, name))

    def next_hold_at(self) -> Optional[float]:
        """Earliest hold release instant (sim settle loops advance their
        virtual clock here), or None."""
        with self._lock:
            return min(self._hold_until.values()) if self._hold_until else None

    def _record_pod_start(self, pod: dict, now: float) -> None:
        """pod-start span: creation -> Running, parented on the owning
        CR's reconcile chain — the pod-level share of slice-ready time
        (scheduling + env injection + kubelet start, and any injected
        slow-start hold)."""
        if not self.tracer.enabled:
            return
        owner = _pod_owner_key(pod)
        if owner is None:
            return
        md = pod["metadata"]
        # Clamp: creationTimestamp may come from a different clock
        # domain than now_fn (wall-time store under a virtual-clock
        # kubelet); a span must never run backwards.
        created = min(md.get("creationTimestamp") or now, now)
        self.tracer.record_for_key(
            owner, "pod-start", created, now,
            pod=md.get("name", ""),
            slice=md.get("labels", {}).get(C.LABEL_SLICE_NAME, ""))

    def step(self) -> int:
        """Advance queued Pending pods one phase; returns pods touched."""
        now = self._now()
        with self._lock:
            batch = sorted(self._pending)
            self._pending.clear()
            to_fail = set(self._fail_next)
            self._fail_next.clear()
        touched = 0
        for ns, name in batch:
            pod = self.store.try_get("Pod", name, ns)
            if pod is None or pod["metadata"].get("deletionTimestamp"):
                continue
            started = False
            if (ns, name) in to_fail:
                pod["status"] = _fail_status(pod)
                to_fail.discard((ns, name))
            elif pod.get("status", {}).get("phase", "Pending") == "Pending":
                with self._lock:
                    held = self._hold_until.get((ns, name), 0.0) > now
                if held or not self.auto_run:
                    # Not running this pod right now (slow-start hold or
                    # auto_run off): keep the key so a later step can
                    # still pick it up.
                    with self._lock:
                        self._pending.add((ns, name))
                    continue
                with self._lock:
                    self._hold_until.pop((ns, name), None)
                n = next(self._ip)
                pod["status"] = {
                    "phase": "Running",
                    "podIP": f"10.0.{(n // 256) % 256}.{n % 256}",
                    "conditions": [{"type": "Ready", "status": "True"}],
                }
                started = True
            else:
                continue
            try:
                self.store.update_status(pod)
                touched += 1
                if started:
                    self._record_pod_start(pod, now)
            except NotFound:
                pass
            except Conflict:
                # Concurrent writer won; requeue for the next step.
                with self._lock:
                    self._pending.add((ns, name))
        # Unconsumed failure injections: apply to running pods, re-park the
        # rest (the pod may simply not exist YET — deferred injection).
        for ns, name in sorted(to_fail):
            pod = self.store.try_get("Pod", name, ns)
            if pod is None:
                with self._lock:
                    self._fail_next.add((ns, name))
                continue
            pod["status"] = _fail_status(pod)
            try:
                self.store.update_status(pod)
                touched += 1
            except NotFound:
                pass
            except Conflict:
                with self._lock:
                    self._fail_next.add((ns, name))
        return touched
