"""Fake kubelet: runs pods that nobody actually runs.

The envtest analogue (SURVEY.md §4 tier 2: "pods are never actually run;
tests manually flip pod phases") promoted to a reusable component: it
watches the store and advances pod phases Pending -> Running, assigns pod
IPs, and can be told to fail specific pods — which is also the framework's
fault-injection hook (ref fail.py / pod-kill e2e patterns, §5.3).

Event-driven: pod creations queue their keys via a store watch, so a
``step()`` touches only new/failed pods — O(changes), not O(all pods)
(what makes the 5k/10k-cluster scale benches measure the operator rather
than the harness).
"""

from __future__ import annotations

import itertools
import threading
from typing import Set

from kuberay_tpu.controlplane.store import Conflict, Event, NotFound, ObjectStore


class FakeKubelet:
    def __init__(self, store: ObjectStore, auto_run: bool = True):
        self.store = store
        self.auto_run = auto_run
        self._ip = itertools.count(1)
        self._lock = threading.Lock()
        self._pending: Set[tuple] = set()       # (ns, name)
        self._fail_next: Set[tuple] = set()
        # Watch FIRST, then backfill — the set dedups the overlap, and the
        # reverse order would lose pods created in the gap.
        self._cancel = store.watch(self._on_event)
        for pod in store.list("Pod"):
            md = pod["metadata"]
            if pod.get("status", {}).get("phase", "Pending") == "Pending":
                self._pending.add((md["namespace"], md["name"]))

    def close(self):
        self._cancel()

    def _on_event(self, ev: Event):
        if ev.kind != "Pod":
            return
        md = ev.obj.get("metadata", {})
        key = (md.get("namespace", "default"), md.get("name", ""))
        with self._lock:
            if ev.type == Event.ADDED:
                self._pending.add(key)
            elif ev.type == Event.DELETED:
                self._pending.discard(key)
                self._fail_next.discard(key)

    def fail_pod(self, name: str, namespace: str = "default"):
        """Inject a failure: the pod transitions to Failed."""
        pod = self.store.try_get("Pod", name, namespace)
        if pod is None:
            with self._lock:
                self._fail_next.add((namespace, name))
            return
        pod["status"] = {**pod.get("status", {}), "phase": "Failed"}
        self.store.update_status(pod)

    def step(self) -> int:
        """Advance queued Pending pods one phase; returns pods touched."""
        with self._lock:
            batch = list(self._pending)
            self._pending.clear()
            to_fail = set(self._fail_next)
            self._fail_next.clear()
        touched = 0
        for ns, name in batch:
            pod = self.store.try_get("Pod", name, ns)
            if pod is None or pod["metadata"].get("deletionTimestamp"):
                continue
            if (ns, name) in to_fail:
                pod["status"] = {"phase": "Failed"}
                to_fail.discard((ns, name))
            elif pod.get("status", {}).get("phase", "Pending") == "Pending":
                if not self.auto_run:
                    # Not running pods right now: keep the key so a later
                    # auto_run=True step can still pick it up.
                    with self._lock:
                        self._pending.add((ns, name))
                    continue
                n = next(self._ip)
                pod["status"] = {
                    "phase": "Running",
                    "podIP": f"10.0.{(n // 256) % 256}.{n % 256}",
                    "conditions": [{"type": "Ready", "status": "True"}],
                }
            else:
                continue
            try:
                self.store.update_status(pod)
                touched += 1
            except NotFound:
                pass
            except Conflict:
                # Concurrent writer won; requeue for the next step.
                with self._lock:
                    self._pending.add((ns, name))
        # Unconsumed failure injections: apply to running pods, re-park the
        # rest (the pod may simply not exist YET — deferred injection).
        for ns, name in to_fail:
            pod = self.store.try_get("Pod", name, ns)
            if pod is None:
                with self._lock:
                    self._fail_next.add((ns, name))
                continue
            pod["status"] = {"phase": "Failed"}
            try:
                self.store.update_status(pod)
                touched += 1
            except NotFound:
                pass
            except Conflict:
                with self._lock:
                    self._fail_next.add((ns, name))
        return touched
