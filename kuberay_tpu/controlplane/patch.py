"""PATCH engines: json-merge, json-patch, strategic-merge, Server-Side
Apply.

The reference's V2 apiserver is a transparent proxy, so every kube PATCH
verb works against it (apiserversdk/proxy.go:28-40); real tooling
(autoscalers, kubectl, controllers) mutates via PATCH rather than
read-modify-write.  This module gives our store/apiserver the same verb
surface:

- **json-merge** (RFC 7386, ``application/merge-patch+json``): recursive
  dict merge; ``null`` deletes a key; lists replace wholesale.
- **json-patch** (RFC 6902, ``application/json-patch+json``): an op list
  (add/remove/replace/move/copy/test) addressed by JSON Pointers.
- **strategic-merge** (``application/strategic-merge-patch+json``):
  json-merge plus per-field list semantics — lists of objects with a
  known merge key (MERGE_KEYS) merge element-wise, ``$patch: delete``
  removes an element, ``$patch: replace`` forces wholesale replacement.
  Kube derives merge keys from struct tags; we carry the table for our
  CRD and core-pod shapes.
- **Server-Side Apply** (``application/apply-patch+yaml``): declarative
  upsert with field ownership.  Each manager's owned field set is stored
  in ``metadata.managedFields`` (fieldsV1 shape); applying a field owned
  by another manager with a different value is a 409 conflict unless
  forced; fields a manager stops applying are pruned when no one else
  owns them.

The engines are pure (object in → object out); the store commits results
atomically under its lock.
"""

from __future__ import annotations

import copy
import json
import time
from typing import Any, Dict, List, Optional, Set, Tuple

# Field name -> merge key for strategic list merging (kube: struct tags;
# ours: the CRD shapes in api/ + the core-pod subset the builders emit).
MERGE_KEYS: Dict[str, str] = {
    "workerGroupSpecs": "groupName",
    "containers": "name",
    "initContainers": "name",
    "env": "name",
    "volumes": "name",
    "volumeMounts": "name",
    "ports": "name",
    "conditions": "type",
    "tolerations": "key",
    "imagePullSecrets": "name",
    "hostAliases": "ip",
}

# Lists that merge as SETS of scalars (kube patchStrategy=merge on
# scalar lists — metadata.finalizers is the one we rely on).
SET_MERGE_LISTS = frozenset({"finalizers"})


class PatchError(Exception):
    """Malformed patch document (HTTP 400/422 at the API layer)."""


class ApplyConflict(Exception):
    """SSA field conflict. ``conflicts`` is [(path_str, other_manager)]."""

    def __init__(self, conflicts: List[Tuple[str, str]]):
        self.conflicts = conflicts
        msg = "; ".join(f"{p} owned by {m!r}" for p, m in conflicts)
        super().__init__(f"Apply failed with {len(conflicts)} conflict(s): "
                         f"{msg}")


# ---------------------------------------------------------------------------
# RFC 7386 json-merge
# ---------------------------------------------------------------------------

def json_merge_patch(target: Any, patch: Any) -> Any:
    if not isinstance(patch, dict):
        return copy.deepcopy(patch)
    out = dict(target) if isinstance(target, dict) else {}
    for k, v in patch.items():
        if v is None:
            out.pop(k, None)
        else:
            out[k] = json_merge_patch(out.get(k), v)
    return out


# ---------------------------------------------------------------------------
# RFC 6902 json-patch
# ---------------------------------------------------------------------------

def _ptr_tokens(pointer: str) -> List[str]:
    if pointer == "":
        return []
    if not pointer.startswith("/"):
        raise PatchError(f"bad JSON pointer {pointer!r}")
    return [t.replace("~1", "/").replace("~0", "~")
            for t in pointer[1:].split("/")]


def _ptr_walk(doc: Any, tokens: List[str]):
    """Returns (parent, final_token) for a pointer; raises on missing
    intermediate containers."""
    cur = doc
    for t in tokens[:-1]:
        cur = _ptr_step(cur, t)
    return cur, tokens[-1]


def _ptr_step(cur: Any, token: str):
    if isinstance(cur, list):
        try:
            return cur[int(token)]
        except (ValueError, IndexError):
            raise PatchError(f"bad list index {token!r}") from None
    if isinstance(cur, dict):
        if token not in cur:
            raise PatchError(f"path member {token!r} not found")
        return cur[token]
    raise PatchError(f"cannot traverse {type(cur).__name__} with {token!r}")


def _ptr_get(doc: Any, pointer: str):
    cur = doc
    for t in _ptr_tokens(pointer):
        cur = _ptr_step(cur, t)
    return cur


def _ptr_add(doc, tokens, value):
    parent, last = _ptr_walk(doc, tokens)
    if isinstance(parent, list):
        try:
            idx = len(parent) if last == "-" else int(last)
        except ValueError:
            raise PatchError(f"bad list index {last!r}") from None
        if not 0 <= idx <= len(parent):
            raise PatchError(f"list index {last} out of range")
        parent.insert(idx, value)
    elif isinstance(parent, dict):
        parent[last] = value
    else:
        raise PatchError("add target is not a container")


def _ptr_remove(doc, tokens):
    if not tokens:
        raise PatchError("cannot remove whole document")
    parent, last = _ptr_walk(doc, tokens)
    if isinstance(parent, list):
        try:
            return parent.pop(int(last))
        except (ValueError, IndexError):
            raise PatchError(f"bad list index {last!r}") from None
    if isinstance(parent, dict):
        if last not in parent:
            raise PatchError(f"remove: {last!r} not found")
        return parent.pop(last)
    raise PatchError("remove target is not a container")


def json_patch(target: Any, ops: List[Dict[str, Any]]) -> Any:
    """Apply an RFC 6902 op list; atomic — any failing op aborts."""
    if not isinstance(ops, list):
        raise PatchError("json-patch body must be a list of ops")
    doc = copy.deepcopy(target)
    for op in ops:
        if not isinstance(op, dict) or "op" not in op:
            raise PatchError(f"bad op {op!r}")
        kind = op["op"]
        path = op.get("path")
        if path is None:
            raise PatchError(f"op {kind!r} missing path")
        tokens = _ptr_tokens(path)
        if kind == "add":
            if not tokens:
                doc = copy.deepcopy(op.get("value"))
            else:
                _ptr_add(doc, tokens, copy.deepcopy(op.get("value")))
        elif kind == "remove":
            if not tokens:
                raise PatchError("cannot remove whole document")
            _ptr_remove(doc, tokens)
        elif kind == "replace":
            if not tokens:
                doc = copy.deepcopy(op.get("value"))
            else:
                parent, last = _ptr_walk(doc, tokens)
                _ptr_step(parent, last)          # must exist
                if isinstance(parent, list):
                    parent[int(last)] = copy.deepcopy(op.get("value"))
                else:
                    parent[last] = copy.deepcopy(op.get("value"))
        elif kind == "move":
            if "from" not in op:
                raise PatchError("move op missing 'from'")
            val = _ptr_remove(doc, _ptr_tokens(op["from"]))
            _ptr_add(doc, tokens, val)
        elif kind == "copy":
            if "from" not in op:
                raise PatchError("copy op missing 'from'")
            val = copy.deepcopy(_ptr_get(doc, op["from"]))
            _ptr_add(doc, tokens, val)
        elif kind == "test":
            if _ptr_get(doc, path) != op.get("value"):
                raise PatchError(f"test failed at {path}")
        else:
            raise PatchError(f"unknown op {kind!r}")
    return doc


# ---------------------------------------------------------------------------
# strategic-merge
# ---------------------------------------------------------------------------

def strategic_merge_patch(target: Any, patch: Any,
                          field: str = "") -> Any:
    if isinstance(patch, dict):
        if patch.get("$patch") == "replace":
            out = {k: copy.deepcopy(v) for k, v in patch.items()
                   if k != "$patch"}
            return out
        out = dict(target) if isinstance(target, dict) else {}
        for k, v in patch.items():
            if k == "$patch":
                continue
            if v is None:
                out.pop(k, None)
            else:
                out[k] = strategic_merge_patch(out.get(k), v, field=k)
        return out
    if isinstance(patch, list):
        key = MERGE_KEYS.get(field)
        if key and all(isinstance(e, dict) for e in patch):
            return _merge_keyed_list(
                target if isinstance(target, list) else [], patch, key)
        if field in SET_MERGE_LISTS:
            base = list(target) if isinstance(target, list) else []
            return base + [e for e in patch if e not in base]
        return copy.deepcopy(patch)                    # atomic replace
    return copy.deepcopy(patch)


def _merge_keyed_list(target: List[dict], patch: List[dict],
                      key: str) -> List[dict]:
    out = [copy.deepcopy(e) for e in target]
    index = {e.get(key): i for i, e in enumerate(out)
             if isinstance(e, dict)}
    for e in patch:
        kv = e.get(key)
        if kv is None:
            raise PatchError(
                f"list element missing merge key {key!r}: {e!r}")
        if e.get("$patch") == "delete":
            if kv in index:
                idx = index.pop(kv)
                out[idx] = None
            continue
        if kv in index:
            out[index[kv]] = strategic_merge_patch(out[index[kv]], e)
        else:
            index[kv] = len(out)
            out.append(strategic_merge_patch({}, e))
    return [e for e in out if e is not None]


# ---------------------------------------------------------------------------
# Server-Side Apply
# ---------------------------------------------------------------------------
#
# Field sets are sets of path tuples.  A path segment is either a dict
# key (str) or a list-item key ("k", merge_key_name, json_value) for
# merge-keyed lists.  Only LEAVES are owned: scalars, atomic lists, and
# empty maps.  fieldsV1 round-trips this shape for storage in
# metadata.managedFields (kube wire format: "f:name" map keys and
# 'k:{"name":"x"}' item keys).

_TOP_IGNORED = ("apiVersion", "kind", "metadata", "status")


def field_set(obj: Any, prefix: Tuple = ()) -> Set[Tuple]:
    """Leaf field paths of an applied configuration.  Top-level
    apiVersion/kind/metadata/status are identity/server-owned and not
    tracked (we track spec + any custom top-level sections; labels and
    annotations ARE tracked so appliers can own them)."""
    out: Set[Tuple] = set()
    if not prefix and isinstance(obj, dict):
        for k, v in obj.items():
            if k in _TOP_IGNORED:
                continue
            out |= field_set(v, (k,))
        md = obj.get("metadata", {})
        for sect in ("labels", "annotations"):
            if isinstance(md.get(sect), dict):
                out |= field_set(md[sect], ("metadata", sect))
        return out
    if isinstance(obj, dict):
        if not obj:
            return {prefix}
        for k, v in obj.items():
            out |= field_set(v, prefix + (k,))
        return out
    if isinstance(obj, list):
        key = MERGE_KEYS.get(prefix[-1] if prefix else "")
        if key and all(isinstance(e, dict) and key in e for e in obj):
            for e in obj:
                item = prefix + (("k", key, json.dumps(e[key])),)
                sub = {k: v for k, v in e.items() if k != key}
                if sub:
                    out |= field_set(sub, item)
                else:
                    out.add(item)
            return out
        return {prefix}                                # atomic list leaf
    return {prefix}


def _path_str(path: Tuple) -> str:
    parts = []
    for seg in path:
        if isinstance(seg, tuple):
            parts.append(f"[{seg[1]}={json.loads(seg[2])!r}]")
        else:
            parts.append("." + seg if parts else seg)
    return "".join(parts) or "."


def fields_to_v1(paths: Set[Tuple]) -> Dict[str, Any]:
    """Path set -> kube fieldsV1 dict ('f:' field keys, 'k:' item keys,
    '.' self-ownership marker on interior nodes that are also leaves)."""
    root: Dict[str, Any] = {}
    for path in sorted(paths, key=_path_str):
        node = root
        for seg in path:
            if isinstance(seg, tuple):
                wire = "k:" + json.dumps({seg[1]: json.loads(seg[2])},
                                         separators=(",", ":"))
            else:
                wire = f"f:{seg}"
            node = node.setdefault(wire, {})
        node["."] = {}
    return root


def fields_from_v1(v1: Dict[str, Any], prefix: Tuple = ()) -> Set[Tuple]:
    out: Set[Tuple] = set()
    for k, v in (v1 or {}).items():
        if k == ".":
            if prefix:
                out.add(prefix)
            continue
        if k.startswith("f:"):
            seg: Any = k[2:]
        elif k.startswith("k:"):
            try:
                item = json.loads(k[2:])
                (mk, mv), = item.items()
            except (ValueError, AttributeError):
                raise PatchError(f"bad fieldsV1 item key {k!r}") from None
            seg = ("k", mk, json.dumps(mv))
        else:
            raise PatchError(f"bad fieldsV1 key {k!r}")
        out |= fields_from_v1(v, prefix + (seg,))
        if not v:
            out.add(prefix + (seg,))
    return out


def _lookup(obj: Any, path: Tuple):
    """Value at a field path, or (False, None) when absent.
    Returns (present, value)."""
    cur = obj
    for seg in path:
        if isinstance(seg, tuple):
            _, mk, mv_json = seg
            mv = json.loads(mv_json)
            if not isinstance(cur, list):
                return False, None
            for e in cur:
                if isinstance(e, dict) and e.get(mk) == mv:
                    cur = e
                    break
            else:
                return False, None
        else:
            if not isinstance(cur, dict) or seg not in cur:
                return False, None
            cur = cur[seg]
    return True, cur


def _remove_path(obj: Any, path: Tuple) -> None:
    """Prune the value at path (no-op when absent).  Emptied parent
    containers are left in place — harmless for merge semantics."""
    if not path:
        return
    parents = []
    cur = obj
    for seg in path[:-1]:
        parents.append((cur, seg))
        if isinstance(seg, tuple):
            _, mk, mv_json = seg
            mv = json.loads(mv_json)
            nxt = None
            if isinstance(cur, list):
                for e in cur:
                    if isinstance(e, dict) and e.get(mk) == mv:
                        nxt = e
                        break
            if nxt is None:
                return
            cur = nxt
        else:
            if not isinstance(cur, dict) or seg not in cur:
                return
            cur = cur[seg]
    last = path[-1]
    if isinstance(last, tuple):
        _, mk, mv_json = last
        mv = json.loads(mv_json)
        if isinstance(cur, list):
            cur[:] = [e for e in cur
                      if not (isinstance(e, dict) and e.get(mk) == mv)]
    elif isinstance(cur, dict):
        cur.pop(last, None)


def managed_fields(obj: Dict[str, Any]) -> List[Dict[str, Any]]:
    return obj.get("metadata", {}).get("managedFields", []) or []


def _manager_entry(entries: List[dict], manager: str, subresource: str):
    for e in entries:
        if (e.get("manager") == manager
                and e.get("subresource", "") == subresource):
            return e
    return None


def apply_ssa(live: Optional[Dict[str, Any]], applied: Dict[str, Any],
              manager: str, *, force: bool = False,
              subresource: str = "") -> Dict[str, Any]:
    """Server-Side Apply: returns the new object (live may be None =
    create).  Raises ApplyConflict on unforced conflicts.  The caller
    stamps resourceVersion/generation and commits."""
    if not manager:
        raise PatchError("apply requires a fieldManager")
    new_fields = field_set(applied)
    now = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())

    if live is None:
        out = copy.deepcopy(applied)
        md = out.setdefault("metadata", {})
        md["managedFields"] = [{
            "manager": manager, "operation": "Apply",
            "apiVersion": applied.get("apiVersion", ""),
            "time": now, "fieldsType": "FieldsV1",
            "fieldsV1": fields_to_v1(new_fields),
            **({"subresource": subresource} if subresource else {}),
        }]
        return out

    entries = copy.deepcopy(managed_fields(live))
    mine = _manager_entry(entries, manager, subresource)
    old_fields = (fields_from_v1(mine.get("fieldsV1", {}))
                  if mine else set())

    # Conflict scan: fields I apply, someone else owns, values differ.
    conflicts: List[Tuple[str, str]] = []
    others: List[Tuple[dict, Set[Tuple]]] = []
    for e in entries:
        if e is mine:
            continue
        fs = fields_from_v1(e.get("fieldsV1", {}))
        others.append((e, fs))
        for p in new_fields & fs:
            present, live_val = _lookup(live, p)
            _, want_val = _lookup(applied, p)
            if not present or live_val != want_val:
                conflicts.append((_path_str(p), e.get("manager", "?")))
    if conflicts and not force:
        raise ApplyConflict(sorted(set(conflicts)))

    out = copy.deepcopy(live)
    # Removal: fields I owned but no longer apply, and nobody else owns.
    union_others: Set[Tuple] = set()
    for _, fs in others:
        union_others |= fs
    removed = sorted(old_fields - new_fields, key=len, reverse=True)
    for p in removed:
        if p not in union_others:
            _remove_path(out, p)
    # Removing every owned leaf of a merge-keyed list item leaves a stub
    # {mergeKey: value} element behind; prune the item itself when no
    # surviving owner (mine or others') references anything under it —
    # this is how dropping a worker group from an applied manifest
    # actually deletes the group.
    keep = new_fields | union_others
    for prefix in sorted({p[:i + 1] for p in removed
                          for i, seg in enumerate(p)
                          if isinstance(seg, tuple)},
                         key=len, reverse=True):
        if any(q[:len(prefix)] == prefix for q in keep):
            continue
        present, item = _lookup(out, prefix)
        if present and isinstance(item, dict) and \
                set(item) == {prefix[-1][1]}:
            _remove_path(out, prefix)

    # Merge the applied config in (strategic semantics).
    merged = strategic_merge_patch(
        {k: v for k, v in out.items() if k not in ("metadata", "status")},
        {k: v for k, v in applied.items()
         if k not in ("apiVersion", "kind", "metadata", "status")})
    for k in list(out.keys()):
        if k not in ("apiVersion", "kind", "metadata", "status") \
                and k not in merged:
            del out[k]
    out.update(merged)
    amd = applied.get("metadata", {})
    for sect in ("labels", "annotations"):
        if isinstance(amd.get(sect), dict):
            out["metadata"][sect] = strategic_merge_patch(
                out["metadata"].get(sect, {}), amd[sect])

    # Ownership bookkeeping: forced conflicts strip the loser's fields.
    if force and conflicts:
        lost = {p for p, _ in conflicts}
        for e, fs in others:
            kept = {p for p in fs if _path_str(p) not in lost}
            if kept != fs:
                e["fieldsV1"] = fields_to_v1(kept)
    new_entries = [e for e in entries if e is not mine
                   and e.get("fieldsV1")]
    new_entries.append({
        "manager": manager, "operation": "Apply",
        "apiVersion": applied.get("apiVersion",
                                  live.get("apiVersion", "")),
        "time": now, "fieldsType": "FieldsV1",
        "fieldsV1": fields_to_v1(new_fields),
        **({"subresource": subresource} if subresource else {}),
    })
    out["metadata"]["managedFields"] = new_entries
    return out


def claim_update(obj: Dict[str, Any], old: Optional[Dict[str, Any]],
                 new: Dict[str, Any], manager: str,
                 subresource: str = "") -> None:
    """Ownership bookkeeping for non-apply writes (kube: Update
    operations also own the fields they set): fields whose value changed
    move to ``manager``; other managers keep untouched fields.  Mutates
    ``obj['metadata']['managedFields']`` in place."""
    if not manager:
        return
    changed = set()
    for p in field_set(new):
        was, old_v = _lookup(old or {}, p)
        _, new_v = _lookup(new, p)
        if not was or old_v != new_v:
            changed.add(p)
    if old:
        # fields removed entirely also count as "changed" for the owners
        for p in field_set(old) - field_set(new):
            changed.add(p)
    if not changed:
        return
    entries = copy.deepcopy(managed_fields(old or {}))
    for e in entries:
        if e.get("manager") == manager and \
                e.get("subresource", "") == subresource:
            continue
        fs = fields_from_v1(e.get("fieldsV1", {}))
        kept = fs - changed
        e["fieldsV1"] = fields_to_v1(kept)
    entries = [e for e in entries if e.get("fieldsV1")]
    mine = _manager_entry(entries, manager, subresource)
    now = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    live_fields = {p for p in changed if _lookup(new, p)[0]}
    if mine:
        fs = fields_from_v1(mine.get("fieldsV1", {})) | live_fields
        mine["fieldsV1"] = fields_to_v1(fs)
        mine["time"] = now
        mine["operation"] = "Update"
    elif live_fields:
        entries.append({
            "manager": manager, "operation": "Update",
            "apiVersion": new.get("apiVersion", ""),
            "time": now, "fieldsType": "FieldsV1",
            "fieldsV1": fields_to_v1(live_fields),
            **({"subresource": subresource} if subresource else {}),
        })
    obj.setdefault("metadata", {})["managedFields"] = entries
