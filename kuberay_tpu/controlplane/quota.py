"""Hierarchical multi-tenant quota: THE capacity oracle for gang admission.

The survey's L0 queueing layer (Volcano queues, YuniKorn hierarchies,
Kueue borrowing) rebuilt TPU-native: every TpuCluster / TpuJob /
TpuService capacity claim flows through one ``admit()`` / ``release()``
seam, all-or-nothing per gang, denominated in chips because the atomic
schedulable unit is a whole slice.

Model (config = ``api/quotapool.py``; semantics in docs/scheduling.md):

- A **claim** is the full chip demand of one gang (head + every slice).
  There is no partial admission: a gang is either fully claimed or fully
  pending, so the sim invariant "no gang ever partially admitted" is a
  property of this ledger, not of pod-level luck.
- A queue may **borrow** idle capacity beyond its guarantee (up to its
  ceiling).  Borrowed capacity is a loan: when a guaranteed-backed
  request (or an escalated starving one) cannot fit, the manager
  **reclaims** from the lowest-priority borrowers — youngest first
  within a priority tie, which makes the tie deterministic and journal-
  stable under the seeded sim.
- Eviction is a *warned* preemption: the preemptor stamps PR 10's
  ``tpu.dev/preemption-notice`` on the victim's live pods, which fires
  the notice -> drain -> checkpoint path inside the controllers.  During
  the notice window the victim stays admitted (``reclaim-notice``) so an
  elastic job can shrink below its reclaim target and cancel the
  eviction entirely — elastic jobs shrink before they die.  Only after
  the window does the verdict flip to denied-with-``evict`` and the
  owning controller tears the gang down through the drain seam.
- **Starvation guard**: any gang pending past the pool's bound escalates
  to the front of its queue — it gets a capacity *reservation* (later
  *borrowers* cannot take the chips it is waiting for; admission within
  a guarantee is a pre-sold contract and never queues behind anyone)
  plus a borrowed-capacity override (it may exceed its guarantee even
  in a non-borrowable queue, reclaiming from strictly-lower-priority
  borrowers).  Reservations are ordered by pending age so two escalated
  gangs cannot deadlock each other.

Thread-safety: one lock guards the ledger (``_claims`` / ``_pending`` /
``_seq`` / ``_audit`` / ``_last_reason``); the injected clock keeps the
sim and the benchmark deterministic.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from kuberay_tpu.api.quotapool import QuotaPool, QuotaQueue
from kuberay_tpu.controlplane.store import Conflict, NotFound, ObjectStore
from kuberay_tpu.utils import constants as C

DEFAULT_QUEUE = "default"

ClaimKey = Tuple[str, str, str]  # (kind, namespace, workload name)


def claim_key(obj: Dict[str, Any]) -> ClaimKey:
    """Stable ledger key for a workload.

    A TpuJob and the TpuCluster it creates are ONE claim: clusters whose
    originated-from labels point at a TpuJob resolve to the job's key, so
    the job-level admission check and the cluster-level one never double
    count.  Service-managed and standalone clusters claim per cluster
    (a blue/green upgrade correctly needs both colors through quota).
    """
    md = obj.get("metadata", {})
    ns = md.get("namespace", "default")
    labels = md.get("labels", {}) or {}
    if labels.get(C.LABEL_ORIGINATED_FROM_CRD) == C.KIND_JOB and \
            labels.get(C.LABEL_ORIGINATED_FROM_CR_NAME):
        return (C.KIND_JOB, ns, labels[C.LABEL_ORIGINATED_FROM_CR_NAME])
    return (obj.get("kind", C.KIND_CLUSTER), ns, md.get("name", ""))


def build_demand(obj: Dict[str, Any]) -> Dict[str, Any]:
    """Rich gang demand: chip quantum + tenant/queue/priority identity."""
    from kuberay_tpu.scheduler.interface import total_cluster_demand

    demand = total_cluster_demand(obj)
    spec = obj.get("spec", {}) or {}
    md = obj.get("metadata", {})
    demand.update({
        "kind": obj.get("kind", C.KIND_CLUSTER),
        "namespace": md.get("namespace", "default"),
        "name": md.get("name", ""),
        "tenant": spec.get("tenant", "") or "",
        "queue": spec.get("gangSchedulingQueue", "") or DEFAULT_QUEUE,
        "priority": int(spec.get("priority", 0) or 0),
        "key": claim_key(obj),
    })
    return demand


def job_pseudo_cluster(job: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """A TpuJob viewed as the cluster it will create, for admission
    purposes: the embedded clusterSpec with the job-level quota identity
    (tenant / priority / queue) overlaid — mirroring the job
    controller's spec forwarding.  ``None`` when the job brings no
    clusterSpec (clusterSelector mode claims nothing new)."""
    spec = job.get("spec", {}).get("clusterSpec")
    if not spec:
        return None
    pseudo_spec = dict(spec)
    jspec = job.get("spec", {})
    for field in ("tenant", "gangSchedulingQueue"):
        if jspec.get(field):
            pseudo_spec[field] = jspec[field]
    if jspec.get("priority"):
        pseudo_spec["priority"] = jspec["priority"]
    return {"metadata": job["metadata"], "kind": C.KIND_JOB,
            "spec": pseudo_spec}


@dataclasses.dataclass
class QuotaVerdict:
    """Admission outcome.  Truthy iff admitted, so plain-bool call sites
    (``if not scheduler.on_cluster_submission(...)``) keep working."""

    admitted: bool = True
    reason: str = ""
    evict: bool = False       # denied AND the holder must tear down now
    tenant: str = ""
    queue: str = ""
    escalated: bool = False   # starvation override active for this gang
    chips: int = 0

    def __bool__(self) -> bool:
        return self.admitted


class QuotaManager:
    """Tenant -> queue chip-budget ledger behind the gang-admission seam.

    ``preemptor(victim_claim, deadline)`` overrides how reclaim warns a
    victim; the default stamps ``tpu.dev/preemption-notice`` on the
    victim's live pods so the controllers' drain seam takes over.
    """

    def __init__(self, store: ObjectStore, *, metrics=None,
                 clock: Callable[[], float] = time.time,
                 preemptor: Optional[Callable[[Dict[str, Any], float],
                                              None]] = None,
                 audit_capacity: int = 256):
        self.store = store
        self.metrics = metrics
        self._clock = clock
        self._preemptor = preemptor or self._default_preemptor
        self._lock = threading.Lock()
        self._claims: Dict[ClaimKey, Dict[str, Any]] = {}
        self._pending: Dict[ClaimKey, Dict[str, Any]] = {}
        self._audit: "deque[Dict[str, Any]]" = deque(maxlen=audit_capacity)
        self._last_reason: Dict[ClaimKey, str] = {}
        self._seq = 0  # arrival order; breaks priority ties deterministically

    # --- public seam ---------------------------------------------------------

    def admit(self, demand: Dict[str, Any]) -> QuotaVerdict:
        """All-or-nothing admission for one gang's full chip demand.

        Level-triggered: controllers re-ask on every reconcile, so every
        path is idempotent and audit entries only record *changes*.
        """
        now = self._clock()
        with self._lock:
            return self._admit_locked(dict(demand), now)

    def release(self, obj: Dict[str, Any]) -> None:
        """Drop the workload's claim (CR finished or deleted)."""
        key = obj.get("key") if isinstance(obj.get("key"), tuple) \
            else claim_key(obj)
        now = self._clock()
        with self._lock:
            claim = self._claims.pop(key, None)
            pending = self._pending.pop(key, None)
            self._last_reason.pop(key, None)
            if claim is not None or pending is not None:
                src = claim or pending
                self._record_locked(now, key, src, "released",
                                    admitted=False, evict=False)
                self._publish_locked(src.get("namespace", key[1]))

    def debug_snapshot(self) -> Dict[str, Any]:
        """Served at ``/debug/quota``: pools + ledger + last-N decisions."""
        with self._lock:
            claims = [dict(c) for c in self._claims.values()]
            pending = [dict(p) for p in self._pending.values()]
            decisions = list(self._audit)[::-1]
        for c in claims + pending:
            c["key"] = list(c["key"])
        pools = []
        for p in self.store.list(C.KIND_QUOTA_POOL):
            pools.append({"namespace": p["metadata"].get("namespace"),
                          "name": p["metadata"].get("name"),
                          "spec": p.get("spec", {})})
        claims.sort(key=lambda c: tuple(c["key"]))
        pending.sort(key=lambda p: (p["since"], p["seq"]))
        return {"pools": pools, "claims": claims, "pending": pending,
                "decisions": decisions}

    # --- admission core (all under self._lock) -------------------------------

    def _admit_locked(self, demand: Dict[str, Any],
                      now: float) -> QuotaVerdict:
        ns = demand.get("namespace", "default")
        chips = int(demand.get("tpuChips", demand.get("chips", 0)))
        demand.setdefault("chips", chips)
        key = demand.get("key") or (demand.get("kind", C.KIND_CLUSTER), ns,
                                    demand.get("name", ""))
        demand["key"] = key
        pool = self._resolve_pool(ns)
        if pool is None:
            return QuotaVerdict(True, reason="no-quota-pool")
        tenant = demand.get("tenant", "")
        if not tenant:
            # Quota is opt-in per workload: untenanted gangs bypass the
            # ledger entirely (and never hold chips against any queue).
            return QuotaVerdict(True, reason="untenanted")
        queue = demand.get("queue") or DEFAULT_QUEUE
        qcfg = self._queue_config(pool, tenant, queue)
        if qcfg is None:
            # Config error, not contention: no pending entry (it could
            # never be satisfied, so the starvation guard must not see it).
            return self._deny_locked(now, pool, demand, qcfg,
                                     "unknown-tenant-or-queue",
                                     pending=False)

        self._gc_pending_locked(now, pool)
        self._nudge_expired_locked(now, pool)
        claim = self._claims.get(key)
        if claim is not None and claim["evicting"]:
            return self._admit_evicting_locked(now, pool, demand, qcfg,
                                               claim)
        if claim is not None:
            return self._admit_resize_locked(now, pool, demand, qcfg, claim)
        return self._admit_fresh_locked(now, pool, demand, qcfg)

    def _admit_fresh_locked(self, now: float, pool: QuotaPool,
                            demand: Dict[str, Any],
                            qcfg: QuotaQueue) -> QuotaVerdict:
        tenant, queue = demand["tenant"], demand["queue"]
        chips = demand["chips"]
        key = demand["key"]
        escalated = self._pending.get(key, {}).get("escalated", False)
        ok, reason, shortfall, satisfiable, within_guaranteed = \
            self._admissible_locked(pool, qcfg, tenant, queue, chips,
                                    escalated, key)
        if ok:
            guaranteed_left = max(
                0, qcfg.guaranteedChips - self._used_locked(tenant, queue,
                                                            exclude=key))
            self._seq += 1
            self._claims[key] = {
                "key": key, "kind": demand.get("kind", C.KIND_CLUSTER),
                "namespace": demand.get("namespace", "default"),
                "name": demand.get("name", ""),
                "tenant": tenant, "queue": queue,
                "priority": demand.get("priority", 0),
                "chips": chips, "members": demand.get("minMember", 0),
                "seq": self._seq,
                "borrowed": max(0, chips - guaranteed_left),
                "evicting": False, "evicting_since": 0.0,
                "reclaim_target": 0,
            }
            self._pending.pop(key, None)
            verdict = QuotaVerdict(True, reason="admitted", tenant=tenant,
                                   queue=queue, escalated=escalated,
                                   chips=chips)
            self._record_locked(now, key, demand, "admitted", admitted=True,
                               evict=False, escalated=escalated)
            self._count_locked(queue, "admitted")
            self._publish_locked(demand.get("namespace", "default"))
            return verdict
        if not satisfiable:
            # Larger than the queue ceiling / the pool itself: reject
            # outright, never pending (it would "starve" forever).
            return self._deny_locked(now, pool, demand, qcfg, reason,
                                     pending=False)
        verdict = self._deny_locked(now, pool, demand, qcfg, reason,
                                    pending=True,
                                    guaranteed_backed=within_guaranteed)
        if shortfall > 0 and (within_guaranteed or verdict.escalated):
            self._reclaim_locked(now, pool, demand, shortfall,
                                 escalated_only=not within_guaranteed)
        return verdict

    def _admit_resize_locked(self, now: float, pool: QuotaPool,
                             demand: Dict[str, Any], qcfg: QuotaQueue,
                             claim: Dict[str, Any]) -> QuotaVerdict:
        tenant, queue = claim["tenant"], claim["queue"]
        chips = demand["chips"]
        if chips == claim["chips"]:
            return QuotaVerdict(True, reason="already-admitted",
                                tenant=tenant, queue=queue, chips=chips)
        if chips < claim["chips"]:
            freed = claim["chips"] - chips
            claim["chips"] = chips
            claim["borrowed"] = max(0, claim["borrowed"] - freed)
            claim["priority"] = demand.get("priority", claim["priority"])
            self._record_locked(now, claim["key"], claim, "resized-shrink",
                               admitted=True, evict=False)
            self._count_locked(queue, "resized")
            self._publish_locked(claim["namespace"])
            return QuotaVerdict(True, reason="resized-shrink", tenant=tenant,
                                queue=queue, chips=chips)
        # Grow: the delta is a fresh admission decision.
        delta = chips - claim["chips"]
        escalated = self._pending.get(claim["key"], {}).get("escalated",
                                                            False)
        ok, reason, shortfall, satisfiable, wg = self._admissible_locked(
            pool, qcfg, tenant, queue, delta, escalated, claim["key"],
            base=claim["chips"])
        if not ok:
            verdict = self._deny_locked(now, pool, demand, qcfg,
                                        f"grow-denied:{reason}",
                                        pending=satisfiable,
                                        guaranteed_backed=wg)
            if shortfall > 0 and (wg or verdict.escalated):
                self._reclaim_locked(now, pool, demand, shortfall,
                                     escalated_only=not wg)
            return verdict
        guaranteed_left = max(
            0, qcfg.guaranteedChips - self._used_locked(tenant, queue))
        claim["chips"] = chips
        claim["borrowed"] += max(0, delta - guaranteed_left)
        claim["priority"] = demand.get("priority", claim["priority"])
        self._pending.pop(claim["key"], None)
        self._record_locked(now, claim["key"], claim, "resized-grow",
                           admitted=True, evict=False)
        self._count_locked(queue, "resized")
        self._publish_locked(claim["namespace"])
        return QuotaVerdict(True, reason="resized-grow", tenant=tenant,
                            queue=queue, chips=chips)

    def _admit_evicting_locked(self, now: float, pool: QuotaPool,
                               demand: Dict[str, Any], qcfg: QuotaQueue,
                               claim: Dict[str, Any]) -> QuotaVerdict:
        tenant, queue = claim["tenant"], claim["queue"]
        chips = demand["chips"]
        if chips < claim["chips"]:
            # The elastic shrink path: give back what it no longer needs.
            freed = claim["chips"] - chips
            claim["chips"] = chips
            claim["borrowed"] = max(0, claim["borrowed"] - freed)
            if chips <= claim["reclaim_target"]:
                # Shrink satisfied the reclaim — eviction cancelled.
                claim["evicting"] = False
                claim["evicting_since"] = 0.0
                claim["reclaim_target"] = 0
                self._record_locked(now, claim["key"], claim,
                                    "eviction-cancelled-by-shrink",
                                    admitted=True, evict=False)
                self._count_locked(queue, "resized")
                self._publish_locked(claim["namespace"])
                return QuotaVerdict(True, reason="resized-shrink",
                                    tenant=tenant, queue=queue, chips=chips)
            self._publish_locked(claim["namespace"])
        deadline = claim["evicting_since"] + pool.spec.reclaimNoticeSeconds
        if now < deadline:
            # Notice window: still admitted so the workload can shrink or
            # checkpoint; the drain seam has already been warned.
            return QuotaVerdict(True, reason="reclaim-notice", tenant=tenant,
                                queue=queue, chips=claim["chips"])
        if self._live_pods(claim) == 0:
            # Teardown finished (or never materialized): free the claim
            # and decide afresh — the gang re-queues like any other.
            self._claims.pop(claim["key"], None)
            self._record_locked(now, claim["key"], claim, "evicted",
                               admitted=False, evict=False)
            self._count_locked(queue, "evicted")
            self._publish_locked(claim["namespace"])
            return self._admit_fresh_locked(now, pool, demand, qcfg)
        self._record_locked(now, claim["key"], claim, "reclaim-evict",
                           admitted=False, evict=True)
        self._count_locked(queue, "denied")
        return QuotaVerdict(False, reason="reclaim-evict", evict=True,
                            tenant=tenant, queue=queue, chips=claim["chips"])

    def _deny_locked(self, now: float, pool: QuotaPool,
                     demand: Dict[str, Any], qcfg, reason: str, *,
                     pending: bool,
                     guaranteed_backed: bool = False) -> QuotaVerdict:
        tenant = demand.get("tenant", "")
        queue = demand.get("queue", DEFAULT_QUEUE)
        key = demand["key"]
        escalated = False
        if pending:
            entry = self._pending.get(key)
            if entry is None:
                self._seq += 1
                entry = {"key": key, "since": now, "seq": self._seq,
                         "escalated": False, "chips": demand["chips"],
                         "tenant": tenant, "queue": queue,
                         "priority": demand.get("priority", 0),
                         "namespace": demand.get("namespace", "default"),
                         "kind": demand.get("kind", C.KIND_CLUSTER),
                         "name": demand.get("name", ""),
                         "guaranteed_backed": False,
                         "last_reason": "", "last_seen": now}
                self._pending[key] = entry
            entry["chips"] = demand["chips"]
            entry["guaranteed_backed"] = guaranteed_backed
            entry["last_seen"] = now
            bound = pool.spec.starvationBoundSeconds
            if not entry["escalated"] and now - entry["since"] >= bound:
                entry["escalated"] = True
                self._record_locked(now, key, entry,
                                    "starvation-escalated", admitted=False,
                                    evict=False, escalated=True)
                if self.metrics is not None:
                    self.metrics.quota_starvation_escalation(queue)
            escalated = entry["escalated"]
            entry["last_reason"] = reason
        if self._last_reason.get(key) != reason:
            self._last_reason[key] = reason
            self._record_locked(now, key, demand, reason, admitted=False,
                               evict=False, escalated=escalated)
        self._count_locked(queue, "denied")
        self._publish_locked(demand.get("namespace", "default"))
        return QuotaVerdict(False, reason=reason, tenant=tenant, queue=queue,
                            escalated=escalated, chips=demand["chips"])

    def _admissible_locked(self, pool: QuotaPool, qcfg: QuotaQueue,
                           tenant: str, queue: str, chips: int,
                           escalated: bool, key: ClaimKey, *,
                           base: int = 0):
        """-> (ok, reason, shortfall, satisfiable, within_guaranteed).

        ``base`` is the requester's already-claimed chips (grow path):
        ceiling/guarantee checks see ``used + base + chips`` while the
        free-capacity check only needs the ``chips`` delta.
        """
        total = pool.spec.totalChips
        ceiling = qcfg.ceilingChips or total
        if base + chips > ceiling or base + chips > total:
            return (False, "gang-exceeds-ceiling", 0, False, False)
        used_q = self._used_locked(tenant, queue, exclude=key) + base
        used_total = self._used_locked(None, None, exclude=key) + base
        if used_q + chips > ceiling:
            return (False, "queue-ceiling", 0, True, False)
        within_guaranteed = used_q + chips <= qcfg.guaranteedChips
        if not within_guaranteed and not qcfg.borrowable and not escalated:
            return (False, "not-borrowable", 0, True, False)
        free = total - used_total
        if free < chips:
            return (False, "insufficient-capacity", chips - free, True,
                    within_guaranteed)
        reserved = self._reservations_locked(key, escalated,
                                             within_guaranteed)
        if free - reserved < chips:
            # Physically fits, but an older starving gang called dibs.
            return (False, "reserved-for-escalated", 0, True,
                    within_guaranteed)
        return (True, "", 0, True, within_guaranteed)

    def _gc_pending_locked(self, now: float, pool: QuotaPool) -> None:
        """Drop pending entries nobody is re-asking for (a controller
        that stopped requeueing — deleted CR, abandoned cron catch-up):
        a live gang re-asks every few seconds, so anything silent for a
        starvation-bound's worth of time is gone, and its escalation
        reservation must not starve the living."""
        stale = max(60.0, pool.spec.starvationBoundSeconds)
        for key in [k for k, p in self._pending.items()
                    if now - p["last_seen"] > stale]:
            self._pending.pop(key, None)
            self._last_reason.pop(key, None)

    def _reservations_locked(self, key: ClaimKey, escalated: bool,
                             within_guaranteed: bool = True) -> int:
        """Chips reserved by *other* pending gangs that outrank this
        request.

        Reservations constrain **borrowers**, never a request inside its
        own guarantee: a guarantee is a contract the pool pre-sold, so
        admission within it must not queue behind anyone (otherwise one
        starved borrower would invert priority over every tenant and
        head-of-line-block the whole pool).  Among borrowers, escalated
        waiters reserve first (only longer-pending escalated ones
        against an escalated requester — the total order prevents two
        escalated gangs from reserving each other to death), then
        guaranteed-backed waiters: reclaim freed those chips to honor a
        guarantee, so a borrower must not re-take them first (borrowing
        is a loan)."""
        if within_guaranteed and not escalated:
            return 0
        mine = self._pending.get(key)
        my_rank = (mine["since"], mine["seq"]) if mine else None
        reserved = 0
        for k, p in self._pending.items():
            if k == key:
                continue
            if p["escalated"]:
                if escalated and my_rank is not None and \
                        (p["since"], p["seq"]) >= my_rank:
                    continue
                reserved += p["chips"]
            elif not within_guaranteed and p.get("guaranteed_backed"):
                reserved += p["chips"]
        return reserved

    def _nudge_expired_locked(self, now: float, pool: QuotaPool) -> None:
        """Re-warn evicting claims whose notice window has expired but
        whose pods live on (the controllers' warned-preemption path
        pre-replaces noticed slices, so a victim can converge holding
        fresh *un-noticed* pods and never reconcile again).  Re-stamping
        the notice is a store write, which level-triggers the victim's
        reconcile -> admission re-ask -> ``reclaim-evict`` teardown; on
        already-noticed pods the preemptor is a no-op, so this never
        generates journal churn."""
        for c in self._claims.values():
            if not c["evicting"]:
                continue
            if now >= c["evicting_since"] + pool.spec.reclaimNoticeSeconds:
                self._preemptor(dict(c), now)

    def _reclaim_locked(self, now: float, pool: QuotaPool,
                        demand: Dict[str, Any], shortfall: int, *,
                        escalated_only: bool) -> None:
        """Warn the lowest-priority borrowers until ``shortfall`` chips
        are on their way back.  ``escalated_only`` is the starvation
        borrow-override: it may only displace strictly-lower-priority
        borrowers, while a guaranteed-backed request may displace any
        borrower (the guarantee is a contract)."""
        requester_priority = demand.get("priority", 0)
        # Capacity already being reclaimed (victims drain for a notice
        # window) counts against the shortfall, or every level-triggered
        # re-ask would warn one more victim and cascade-evict the fleet.
        in_flight = sum(c["chips"] - c["reclaim_target"]
                        for c in self._claims.values() if c["evicting"])
        remaining = shortfall - in_flight
        if remaining <= 0:
            return
        victims = [c for c in self._claims.values()
                   if not c["evicting"] and c["borrowed"] > 0
                   and c["key"] != demand["key"]]
        if escalated_only:
            victims = [c for c in victims
                       if c["priority"] < requester_priority]
        # Lowest priority first; youngest first within a tie (the
        # deterministic, journal-stable tie-break).
        victims.sort(key=lambda c: (c["priority"], -c["seq"]))
        deadline = now + pool.spec.reclaimNoticeSeconds
        for victim in victims:
            if remaining <= 0:
                break
            take = min(victim["borrowed"], remaining)
            victim["evicting"] = True
            victim["evicting_since"] = now
            victim["reclaim_target"] = victim["chips"] - take
            remaining -= take
            self._record_locked(now, victim["key"], victim,
                                "reclaim-noticed", admitted=True,
                                evict=False)
            if self.metrics is not None:
                self.metrics.quota_reclaim_eviction(victim["queue"])
            self._preemptor(dict(victim), deadline)

    # --- ledger arithmetic ---------------------------------------------------

    def _used_locked(self, tenant: Optional[str], queue: Optional[str], *,
                     exclude: Optional[ClaimKey] = None) -> int:
        """Claimed chips — evicting claims still count (conservation is
        about capacity *held*, and a victim holds chips until drained)."""
        total = 0
        for k, c in self._claims.items():
            if k == exclude:
                continue
            if tenant is not None and c["tenant"] != tenant:
                continue
            if queue is not None and c["queue"] != queue:
                continue
            total += c["chips"]
        return total

    def _resolve_pool(self, namespace: str) -> Optional[QuotaPool]:
        pools = self.store.list(C.KIND_QUOTA_POOL, namespace)
        if not pools and namespace != "default":
            pools = self.store.list(C.KIND_QUOTA_POOL, "default")
        if not pools:
            return None
        return QuotaPool.from_dict(pools[0])  # store.list sorts by name

    def _queue_config(self, pool: QuotaPool, tenant: str,
                      queue: str) -> Optional[QuotaQueue]:
        for t in pool.spec.tenants:
            if t.name != tenant:
                continue
            for q in t.queues:
                if q.name == queue:
                    return q
        return None

    # --- eviction plumbing ---------------------------------------------------

    def _workload_clusters(self, claim: Dict[str, Any]) -> List[str]:
        ns = claim["namespace"]
        if claim["key"][0] == C.KIND_JOB:
            clusters = self.store.list(C.KIND_CLUSTER, ns, labels={
                C.LABEL_ORIGINATED_FROM_CR_NAME: claim["key"][2],
                C.LABEL_ORIGINATED_FROM_CRD: C.KIND_JOB,
            })
            return [c["metadata"]["name"] for c in clusters]
        return [claim["key"][2]]

    def _live_pods(self, claim: Dict[str, Any]) -> int:
        ns = claim["namespace"]
        count = 0
        for cname in self._workload_clusters(claim):
            for pod in self.store.list("Pod", ns,
                                       labels={C.LABEL_CLUSTER: cname}):
                if not pod["metadata"].get("deletionTimestamp"):
                    count += 1
        return count

    def _default_preemptor(self, claim: Dict[str, Any],
                           deadline: float) -> None:
        """Stamp the advance-notice annotation on the victim's live pods;
        PR 10's drain seam (checkpoint request + drained-at ack) and the
        elastic shrink logic take it from there."""
        ns = claim["namespace"]
        for cname in self._workload_clusters(claim):
            for pod in self.store.list("Pod", ns,
                                       labels={C.LABEL_CLUSTER: cname}):
                md = pod["metadata"]
                if md.get("deletionTimestamp"):
                    continue
                if C.ANNOTATION_PREEMPTION_NOTICE in (
                        md.get("annotations") or {}):
                    continue
                try:
                    self.store.patch("Pod", md["name"], ns, {
                        "metadata": {"annotations": {
                            C.ANNOTATION_PREEMPTION_NOTICE:
                                f"{deadline:.3f}"}}})
                except (NotFound, Conflict):
                    # Pod raced away or a concurrent writer won; the
                    # level-triggered admit loop re-warns next pass.
                    continue

    # --- observability -------------------------------------------------------

    def _record_locked(self, now: float, key: ClaimKey,
                       src: Dict[str, Any], reason: str, *, admitted: bool,
                       evict: bool, escalated: bool = False) -> None:
        self._audit.append({
            "ts": round(now, 3), "kind": key[0], "namespace": key[1],
            "name": key[2], "tenant": src.get("tenant", ""),
            "queue": src.get("queue", ""), "reason": reason,
            "chips": src.get("chips", 0),
            "priority": src.get("priority", 0),
            "admitted": admitted, "evict": evict, "escalated": escalated,
        })

    def _count_locked(self, queue: str, verdict: str) -> None:
        if self.metrics is not None:
            self.metrics.quota_admission(queue, verdict)

    def _publish_locked(self, namespace: str) -> None:
        if self.metrics is None:
            return
        pool = self._resolve_pool(namespace)
        if pool is None:
            return
        pending_by_queue: Dict[Tuple[str, str], int] = {}
        for p in self._pending.values():
            k = (p["tenant"], p["queue"])
            pending_by_queue[k] = pending_by_queue.get(k, 0) + 1
        for t in pool.spec.tenants:
            for q in t.queues:
                used = self._used_locked(t.name, q.name)
                self.metrics.quota_usage(
                    t.name, q.name, used=used,
                    guaranteed=q.guaranteedChips,
                    ceiling=q.ceilingChips or pool.spec.totalChips)
                self.metrics.quota_pending(
                    q.name, pending_by_queue.get((t.name, q.name), 0))
