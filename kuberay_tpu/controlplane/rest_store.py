"""REST-backed object store: the real-cluster seam.

Same verb surface as ``ObjectStore`` (controllers are duck-typed against
it), speaking K8s-style REST to a remote API server — ours
(apiserver/server.py) or, with the URL scheme/paths it shares, a real
kube-apiserver fronting the tpu.dev CRDs.  This is how the control plane
detaches from the in-memory store without touching a single controller
(the reference's equivalent split: controller-runtime client vs envtest).

Watch speaks the K8s protocol natively: one streaming
``?watch=true&resourceVersion=N`` connection per kind (the informer
model), consuming ADDED/MODIFIED/DELETED/BOOKMARK chunked events,
reconnecting from the last-seen resourceVersion on clean timeouts, and
relisting + rediffing on 410 Gone — so the same store fronts our
apiserver or a real kube-apiserver.  Two fallbacks ladder down for
older servers: the legacy ``/watch`` long-poll, then list-diff polling.

Client auth: ``token=`` sends ``Authorization: Bearer`` on every
request; ``ca_cert``/``client_cert`` configure TLS against an https
endpoint (kubeconfig-style credentials, minus the kubeconfig file).
"""

from __future__ import annotations

import json
import logging
import threading
import urllib.error
import urllib.parse
import urllib.request
from typing import Any, Callable, Dict, List, Optional

from kuberay_tpu.utils import constants as C
from kuberay_tpu.controlplane.store import (
    AlreadyExists,
    Conflict,
    Event,
    Invalid,
    NotFound,
    StoreError,
)

_LOG = logging.getLogger("kuberay_tpu.rest_store")

_CRD_PLURALS = C.CRD_PLURALS
_CORE_PLURALS = C.CORE_PLURALS
# Kinds the polling watch tracks (what the manager/expectations need).
WATCHED_KINDS = ("TpuCluster", "TpuJob", "TpuService", "TpuCronJob",
                 "WarmSlicePool", "Pod", "Service", "Job")

# Label scope per kind for the watch/relist streams (the reference's
# scoped informer caches, internal/managercache/cache.go:18: only
# operator-created Pods enter the cache — what bounds operator memory
# on clusters whose OTHER workloads dwarf ours).  Jobs stay unscoped:
# they are few (one submitter per TpuJob) and scoping them would blind
# a restarted operator to Jobs created before the label existed.
DEFAULT_WATCH_SCOPE = {
    "Pod": {C.LABEL_CREATED_BY: C.CREATED_BY_OPERATOR},
}


def _selector_str(scope: Dict[str, str]) -> str:
    return ",".join(f"{k}={v}" for k, v in scope.items())


class RestObjectStore:
    def __init__(self, base_url: str, timeout: float = 10.0,
                 poll_interval: float = 0.2,
                 watched_kinds=WATCHED_KINDS,
                 token: Optional[str] = None,
                 ca_cert: Optional[str] = None,
                 client_cert: Optional[tuple] = None,
                 insecure_skip_verify: bool = False,
                 watch_scope: Optional[Dict[str, Dict[str, str]]] = None):
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout
        self.poll_interval = poll_interval
        self.watched_kinds = tuple(watched_kinds)
        # Per-kind labelSelector on watch/relist streams ({} disables).
        self.watch_scope = (DEFAULT_WATCH_SCOPE if watch_scope is None
                            else watch_scope)
        self.token = token
        self._ssl_ctx = None
        if self.base_url.startswith("https"):
            import ssl
            ctx = ssl.create_default_context(cafile=ca_cert)
            if insecure_skip_verify:
                ctx.check_hostname = False
                ctx.verify_mode = ssl.CERT_NONE
            if client_cert:
                ctx.load_cert_chain(*client_cert)
            self._ssl_ctx = ctx
        self._watchers: List[Callable[[Event], None]] = []
        self._lock = threading.Lock()
        self._known: Dict[tuple, int] = {}      # (kind, ns, name) -> rv
        self._last: Dict[tuple, dict] = {}      # last-seen objects (DELETED
                                                # events must carry labels)
        self._stop = threading.Event()
        self._poll_thread: Optional[threading.Thread] = None
        self._kind_threads: List[threading.Thread] = []
        self._starting = False   # a watch() is probing outside the lock
        self._synced = threading.Event()
        self._sync_pending: set = set()
        # Per-kind watch resume points (last event/bookmark rv) —
        # introspection for the O(delta) reconnect contract tests.
        self._resume_rv: Dict[str, str] = {}
        self._relists: Dict[str, int] = {}

    # -- plumbing ----------------------------------------------------------

    def _path(self, kind: str, ns: Optional[str], name: str = "",
              sub: str = "") -> str:
        if kind in _CRD_PLURALS:
            plural = _CRD_PLURALS[kind]
            base = (f"/apis/tpu.dev/v1/namespaces/{ns}/{plural}" if ns
                    else f"/apis/tpu.dev/v1/{plural}")
        elif kind in _CORE_PLURALS:
            plural = _CORE_PLURALS[kind]
            base = (f"/api/v1/namespaces/{ns}/{plural}" if ns
                    else f"/api/v1/{plural}")
        else:
            raise Invalid(f"unknown kind {kind!r}")
        if name:
            base += f"/{name}"
        if sub:
            base += f"/{sub}"
        return base

    def _headers(self) -> Dict[str, str]:
        h = {"Content-Type": "application/json"}
        if self.token:
            h["Authorization"] = f"Bearer {self.token}"
        return h

    def _req(self, method: str, path: str, body: Any = None,
             timeout: Optional[float] = None,
             content_type: Optional[str] = None):
        data = json.dumps(body).encode() if body is not None else None
        headers = self._headers()
        if content_type:
            headers["Content-Type"] = content_type
        req = urllib.request.Request(
            self.base_url + path, data=data, method=method,
            headers=headers)
        try:
            with urllib.request.urlopen(
                    req, timeout=timeout or self.timeout,
                    context=self._ssl_ctx) as resp:
                payload = resp.read()
                return json.loads(payload) if payload else {}
        except urllib.error.HTTPError as e:
            self._raise_http(e)
        except urllib.error.URLError as e:
            raise StoreError(f"{method} {path}: {e}") from None

    @staticmethod
    def _raise_http(e: urllib.error.HTTPError) -> None:
        try:
            msg = json.loads(e.read()).get("message", str(e))
        except Exception:
            msg = str(e)
        if e.code == 404:
            raise NotFound(msg) from None
        if e.code == 409:
            # The apiserver uses 409 for both exists + rv conflicts.
            if "already exists" in msg:
                raise AlreadyExists(msg) from None
            raise Conflict(msg) from None
        if e.code in (400, 415, 422):
            raise Invalid(msg) from None
        raise StoreError(f"HTTP {e.code}: {msg}") from None

    # -- verbs (ObjectStore-compatible) ------------------------------------

    def create(self, obj: Dict[str, Any]) -> Dict[str, Any]:
        md = obj.get("metadata", {})
        return self._req("POST", self._path(obj["kind"],
                                            md.get("namespace", "default")),
                         obj)

    def get(self, kind: str, name: str, namespace: str = "default"):
        return self._req("GET", self._path(kind, namespace, name))

    def try_get(self, kind: str, name: str, namespace: str = "default"):
        try:
            return self.get(kind, name, namespace)
        except NotFound:
            return None

    # Chunked LIST page size (client-go reflector default): a real
    # apiserver with many objects answers `?limit=` pages with a
    # metadata.continue token; servers without pagination return
    # everything in the first page and the loop exits immediately.
    LIST_PAGE_LIMIT = 500

    def _list_all(self, path: str,
                  query: Optional[Dict[str, str]] = None) -> Dict[str, Any]:
        """Paginated LIST: follow metadata.continue until exhausted.
        Returns the final page's body with ALL items merged (the list
        resourceVersion of the last page is the coherent resume point —
        apiserver semantics for paginated lists)."""
        q = dict(query or {})
        q["limit"] = str(self.LIST_PAGE_LIMIT)
        items: List[Dict[str, Any]] = []
        while True:
            out = self._req("GET",
                            path + "?" + urllib.parse.urlencode(q))
            items.extend(out.get("items", []))
            cont = (out.get("metadata") or {}).get("continue", "")
            if not cont:
                break
            # All other query params must repeat verbatim (K8s contract).
            q["continue"] = cont
        out["items"] = items
        return out

    def list(self, kind: str, namespace: Optional[str] = None,
             labels: Optional[Dict[str, str]] = None) -> List[Dict[str, Any]]:
        # namespace=None lists ALL namespaces (ObjectStore semantics).
        query = {}
        if labels:
            query["labelSelector"] = ",".join(
                f"{k}={v}" for k, v in labels.items())
        return self._list_all(self._path(kind, namespace),
                              query).get("items", [])

    def update(self, obj: Dict[str, Any], *, subresource: str = ""):
        md = obj["metadata"]
        return self._req("PUT", self._path(
            obj["kind"], md.get("namespace", "default"), md["name"],
            subresource), obj)

    def update_status(self, obj: Dict[str, Any]):
        return self.update(obj, subresource="status")

    # The four kube patch MIME types (server counterpart:
    # apiserver/server.py do_PATCH; a real kube-apiserver speaks the
    # same ones, which is the point of using the wire verb).
    _PATCH_CTYPES = C.PATCH_CONTENT_TYPES

    def patch(self, kind: str, name: str, namespace: str = "default",
              body: Any = None, *, patch_type: str = "merge",
              subresource: str = "", field_manager: str = "",
              force: bool = False) -> Dict[str, Any]:
        """Wire PATCH (merge | strategic | json | apply) — one round
        trip, no read-modify-write conflict loop."""
        ctype = self._PATCH_CTYPES.get(patch_type)
        if ctype is None:
            raise Invalid(f"unknown patch type {patch_type!r}")
        path = self._path(kind, namespace, name, subresource)
        q = {}
        if field_manager:
            q["fieldManager"] = field_manager
        if force:
            q["force"] = "true"
        if q:
            path += "?" + urllib.parse.urlencode(q)
        return self._req("PATCH", path, body, content_type=ctype)

    def patch_labels(self, kind: str, name: str, namespace: str,
                     labels: Dict[str, Optional[str]]):
        # json-merge: null deletes a label — single round trip, no
        # conflict loop (RFC 7386 semantics end-to-end).
        return self.patch(kind, name, namespace,
                          {"metadata": {"labels": dict(labels)}},
                          patch_type="merge")

    def delete(self, kind: str, name: str, namespace: str = "default"):
        self._req("DELETE", self._path(kind, namespace, name))

    def add_finalizer(self, kind: str, name: str, namespace: str,
                      finalizer: str, rv=None):
        # Strategic set-merge on metadata.finalizers (kube
        # patchStrategy=merge): union, idempotent, race-free.  Returns
        # the updated object so callers can thread the bumped
        # resourceVersion; ``rv`` adds a precondition (Conflict on a
        # foreign write in the window).
        md: Dict[str, Any] = {"finalizers": [finalizer]}
        if rv is not None:
            md["resourceVersion"] = rv
        return self.patch(kind, name, namespace, {"metadata": md},
                          patch_type="strategic")

    def remove_finalizer(self, kind: str, name: str, namespace: str,
                         finalizer: str, rv=None):
        # Removal needs the full remaining list (merge can't subtract
        # from a set-merge list), so it keeps the rv-guarded RMW — but
        # via PATCH with a resourceVersion precondition, not PUT.  With
        # an explicit ``rv`` the precondition is the caller's snapshot
        # and a Conflict propagates (no silent retry against it).
        for _ in range(1 if rv is not None else 4):
            cur = self.try_get(kind, name, namespace)
            if cur is None:
                return None
            fins = cur["metadata"].get("finalizers", [])
            if finalizer not in fins:
                return cur
            try:
                return self.patch(
                    kind, name, namespace,
                    {"metadata": {
                        "resourceVersion":
                            rv if rv is not None
                            else cur["metadata"]["resourceVersion"],
                        "finalizers":
                            [f for f in fins if f != finalizer]}},
                    patch_type="merge")
            except Conflict:
                if rv is not None:
                    raise
                continue
            except NotFound:
                return None
        return None

    def count(self, kind: str) -> int:
        return len(self.list(kind))

    def ensure(self, obj: Dict[str, Any], compare=None) -> bool:
        compare = compare or (lambda o: o.get("spec"))
        md = obj["metadata"]
        cur = self.try_get(obj["kind"], md["name"],
                           md.get("namespace", "default"))
        if cur is None:
            try:
                self.create(obj)
                return True
            except AlreadyExists:
                return False
        if compare(cur) != compare(obj):
            cur["spec"] = obj.get("spec", cur.get("spec"))
            self.update(cur)
            return True
        return False

    # -- watch -------------------------------------------------------------
    #
    # Three tiers, best available wins (probed once per watch start):
    #   k8s    — per-kind streaming ?watch=true (informer model)
    #   legacy — /watch long-poll (round-1 protocol, older servers)
    #   poll   — list-diff polling (any REST server)

    def watch(self, fn: Callable[[Event], None]) -> Callable[[], None]:
        stop: Optional[threading.Event] = None
        with self._lock:
            self._watchers.append(fn)
            running = (self._starting
                       or any(t.is_alive() for t in self._kind_threads)
                       or (self._poll_thread is not None
                           and self._poll_thread.is_alive()))
            if not running:
                self._stop = threading.Event()
                stop = self._stop
                self._starting = True

        if stop is not None:
            # The mode probe and initial relist do network I/O — they run
            # OUTSIDE the lock so a slow or unreachable server cannot
            # wedge every other store caller behind watch start-up.
            # ``_starting`` keeps a concurrent watch() from double-probing;
            # ``_known`` priming without the lock is safe because only the
            # poll path (not yet running) reads it.
            mode = None
            try:
                mode, definitive = self._detect_watch_mode()
                if mode != "k8s":
                    self._prime()
            finally:
                with self._lock:
                    self._starting = False
                    if mode is not None and not stop.is_set():
                        # close() didn't race us and the probe completed.
                        if mode == "k8s":
                            self._start_kind_threads_locked()
                        else:
                            # The loop captures ITS stop event: a
                            # long-poll can outlive close()'s join, and a
                            # restarted watch must not resurrect the old
                            # thread via the replaced self._stop.  A
                            # non-definitive probe (server down) makes the
                            # poll loop re-probe periodically instead of
                            # pinning the downgrade forever.
                            self._poll_thread = threading.Thread(
                                target=self._poll_loop,
                                args=(stop, mode == "legacy",
                                      not definitive),
                                daemon=True, name="rest-watch")
                            self._poll_thread.start()

        # Snapshot under the lock; the sync wait happens OUTSIDE it so a
        # slow relist doesn't serialize every other store caller.
        with self._lock:
            kind_threads = list(self._kind_threads)
            synced = self._synced

        # WaitForCacheSync: block until every kind completed its initial
        # relist — from that point on, any change is guaranteed to reach
        # watchers (each stream resumes from its relist rv), the contract
        # the in-memory store gives for free by synchronous registration.
        if kind_threads:
            synced.wait(timeout=15.0)

        def cancel():
            with self._lock:
                if fn in self._watchers:
                    self._watchers.remove(fn)
        return cancel

    def close(self):
        # Detach thread state under the lock; join OUTSIDE it (a wedged
        # long-poll must not hold up every other store caller).
        with self._lock:
            self._stop.set()
            poll_thread = self._poll_thread
            self._poll_thread = None
            kind_threads = self._kind_threads
            self._kind_threads = []
        if poll_thread is not None:
            poll_thread.join(timeout=2.0)
        for t in kind_threads:
            t.join(timeout=2.0)

    def _start_kind_threads_locked(self):
        """Start the per-kind k8s watch threads (caller holds _lock)."""
        self._kind_threads = []
        self._synced = threading.Event()
        self._sync_pending = set(self.watched_kinds)
        for kind in self.watched_kinds:
            t = threading.Thread(
                target=self._kind_loop, args=(kind, self._stop),
                daemon=True, name=f"rest-watch-{kind}")
            t.start()
            self._kind_threads.append(t)

    def _dispatch(self, events: List[Event]):
        for ev in events:
            for w in list(self._watchers):
                try:
                    w(ev)
                except Exception:
                    # Watcher errors never poison the stream, but a
                    # controller throwing on every event must be visible.
                    _LOG.exception("watcher failed on %s %s",
                                   ev.type, ev.kind)

    # -- K8s-native streaming watch ---------------------------------------

    def _detect_watch_mode(self) -> tuple:
        """Probe the server's best watch dialect; returns
        ``(mode, definitive)``.  A K8s-protocol server answers
        ``?watch=true&timeoutSeconds=1`` with an (empty) event stream; a
        round-1 server ignores the params and returns the full List
        body; a bare REST server leaves only polling.  ``definitive``
        False means the probe itself failed (server down mid-probe) and
        the caller should re-probe later instead of pinning the fallback
        mode forever."""
        try:
            path = self._path(self.watched_kinds[0], None)
            req = urllib.request.Request(
                self.base_url + path + "?watch=true&timeoutSeconds=1",
                headers=self._headers())
            with urllib.request.urlopen(
                    req, timeout=self.timeout,
                    context=self._ssl_ctx) as resp:
                body = resp.read(4096)
            if b'"items"' not in body:
                return "k8s", True
        except urllib.error.HTTPError as e:
            # 5xx during the probe (server restarting, LB hiccup) is not
            # evidence about the dialect — re-probe later rather than
            # pinning poll mode forever.
            if e.code >= 500:
                return "poll", False
        except Exception:
            return "poll", False
        return ("legacy", True) if self._probe_watch_rv() is not None \
            else ("poll", True)

    def _kind_loop(self, kind: str, stop: threading.Event):
        rv: Optional[str] = None
        first = True
        backoff = self.poll_interval
        while not stop.is_set():
            try:
                if rv is None:
                    # Initial sync is silent (matching in-memory
                    # ObjectStore.watch: level-triggered consumers list on
                    # startup); post-410 relists emit the missed diff.
                    rv = self._relist_kind(kind, silent=first)
                    with self._lock:
                        self._relists[kind] = self._relists.get(kind, 0) + 1
                    if first:
                        first = False
                        with self._lock:
                            self._sync_pending.discard(kind)
                            if not self._sync_pending:
                                self._synced.set()
                rv = self._stream_kind(kind, rv, stop)
                if rv is not None:
                    with self._lock:
                        self._resume_rv[kind] = rv
                backoff = self.poll_interval
            except Exception:
                # Transient failure (connection reset, 5xx, timeout
                # mid-stream): keep ``rv`` and reconnect from the last
                # event/bookmark — an O(delta) rejoin.  Only the
                # server's 410 Expired (``_stream_kind`` -> None) forces
                # the O(kind-size) relist; a flaky network no longer
                # relists the world on every blip.  Exponential backoff
                # per kind either way (client-go reflector behavior).
                stop.wait(backoff)
                backoff = min(backoff * 2, 30.0)

    def watch_resume_points(self) -> Dict[str, str]:
        """Per-kind last-seen watch rv (event or BOOKMARK) — the resume
        point a reconnect uses instead of relisting."""
        with self._lock:
            return dict(self._resume_rv)

    def relist_counts(self) -> Dict[str, int]:
        """How many times each kind paid a full relist (initial sync
        counts once; after that only 410 Expired should add)."""
        with self._lock:
            return dict(self._relists)

    def _relist_kind(self, kind: str, silent: bool = False) -> str:
        query = {}
        scope = self._scope(kind)
        if scope:
            query["labelSelector"] = _selector_str(scope)
        out = self._list_all(self._path(kind, None), query or None)
        items = out.get("items", [])
        rv = (out.get("metadata") or {}).get("resourceVersion") \
            or str(out.get("resourceVersion", 0))
        events: List[Event] = []
        with self._lock:
            seen = set()
            for obj in items:
                md = obj.get("metadata", {})
                key = (kind, md.get("namespace", "default"),
                       md.get("name", ""))
                seen.add(key)
                nrv = md.get("resourceVersion", 0)
                old = self._known.get(key)
                if old is None:
                    events.append(Event(Event.ADDED, kind, obj))
                elif nrv != old:
                    events.append(Event(Event.MODIFIED, kind, obj))
                self._known[key] = nrv
                self._last[key] = obj
            for key in [k for k in self._known
                        if k[0] == kind and k not in seen]:
                _, ns, name = key
                del self._known[key]
                gone = self._last.pop(key, None) or {
                    "kind": kind,
                    "metadata": {"namespace": ns, "name": name,
                                 "labels": {}}}
                events.append(Event(Event.DELETED, kind, gone))
        if not silent:
            self._dispatch(events)
        return str(rv)

    def _stream_kind(self, kind: str, rv: str,
                     stop: threading.Event) -> Optional[str]:
        """One watch connection: consume events until the server's
        timeoutSeconds window closes (return the resume rv) or the
        stream expires (return None -> caller relists)."""
        import socket
        hold = 30
        params = {
            "watch": "true", "resourceVersion": rv,
            "timeoutSeconds": str(hold), "allowWatchBookmarks": "true"}
        scope = self._scope(kind)
        if scope:
            params["labelSelector"] = _selector_str(scope)
        query = urllib.parse.urlencode(params)
        req = urllib.request.Request(
            self.base_url + self._path(kind, None) + "?" + query,
            headers=self._headers())
        try:
            with urllib.request.urlopen(
                    req, timeout=hold + 15,
                    context=self._ssl_ctx) as resp:
                for line in resp:
                    if stop.is_set():
                        return rv
                    line = line.strip()
                    if not line:
                        continue
                    entry = json.loads(line)
                    etype = entry.get("type", "")
                    obj = entry.get("object", {})
                    if etype == "BOOKMARK":
                        rv = str(obj.get("metadata", {})
                                 .get("resourceVersion", rv))
                        continue
                    if etype == "ERROR":
                        if obj.get("code") == 410:
                            return None          # expired: relist
                        return rv                # transient: reconnect
                    md = obj.get("metadata", {})
                    key = (kind, md.get("namespace", "default"),
                           md.get("name", ""))
                    ev = Event(etype, kind, obj)
                    with self._lock:
                        if etype == Event.DELETED:
                            self._known.pop(key, None)
                            self._last.pop(key, None)
                        else:
                            self._known[key] = md.get("resourceVersion", 0)
                            self._last[key] = obj
                    self._dispatch([ev])
                    rv = str(md.get("resourceVersion", rv))
        except urllib.error.HTTPError as e:
            e.read()
            if e.code == 410:
                return None                      # expired before connect
            raise StoreError(f"watch {kind}: HTTP {e.code}") from None
        except (socket.timeout, TimeoutError):
            return rv                            # idle socket: reconnect
        return rv                                # clean server timeout

    def _scope(self, kind: str) -> Optional[Dict[str, str]]:
        """Watch-stream label scope for a kind (None = unscoped)."""
        return self.watch_scope.get(kind) or None

    def _prime(self):
        """Seed known-state without emitting events — pre-existing objects
        are intentionally silent, matching in-memory ObjectStore.watch
        (level-triggered consumers list on startup instead)."""
        for kind in self.watched_kinds:
            try:
                for obj in self.list(kind, labels=self._scope(kind)):
                    md = obj["metadata"]
                    self._known[(kind, md["namespace"], md["name"])] = \
                        md.get("resourceVersion", 0)
            except StoreError:
                continue

    def _poll_once(self):
        seen = set()
        failed_kinds = set()
        events: List[Event] = []
        for kind in self.watched_kinds:
            try:
                items = self.list(kind, labels=self._scope(kind))
            except StoreError:
                # A transient failure means UNKNOWN state — treating it as
                # "everything of this kind vanished" would storm the
                # operator with fake DELETEDs.
                failed_kinds.add(kind)
                continue
            for obj in items:
                md = obj["metadata"]
                key = (kind, md["namespace"], md["name"])
                seen.add(key)
                rv = md.get("resourceVersion", 0)
                old = self._known.get(key)
                if old is None:
                    events.append(Event(Event.ADDED, kind, obj))
                elif rv != old:
                    events.append(Event(Event.MODIFIED, kind, obj))
                self._known[key] = rv
                self._last[key] = obj
        for key in [k for k in self._known if k not in seen
                    and k[0] in self.watched_kinds
                    and k[0] not in failed_kinds]:
            kind, ns, name = key
            del self._known[key]
            gone = self._last.pop(key, None) or {
                "kind": kind, "metadata": {"namespace": ns, "name": name,
                                           "labels": {}}}
            events.append(Event(Event.DELETED, kind, gone))
        for ev in events:
            for w in list(self._watchers):
                try:
                    w(ev)
                except Exception:
                    # Watcher errors never poison the stream, but a
                    # controller throwing on every event must be visible.
                    _LOG.exception("watcher failed on %s %s",
                                   ev.type, ev.kind)

    def _poll_loop(self, stop: threading.Event, try_legacy: bool = True,
                   reprobe: bool = False):
        # Prefer the server's long-poll /watch (immediate delivery, no
        # per-interval full lists); fall back to list-diff polling.
        import time as _time
        rv = None
        if try_legacy:
            try:
                rv = self._resync()
            except Exception:
                rv = None
        last_probe = _time.time()
        while not stop.is_set():
            if reprobe and _time.time() - last_probe > 15.0:
                # The original dialect probe failed transiently; a server
                # that has since come back may speak the k8s protocol —
                # upgrade instead of polling it forever.
                last_probe = _time.time()
                mode, definitive = self._detect_watch_mode()
                if definitive:
                    reprobe = False
                    if mode == "k8s":
                        with self._lock:
                            self._start_kind_threads_locked()
                        return
                    if mode == "legacy" and rv is None:
                        try:
                            rv = self._resync()
                        except Exception:
                            rv = None
            if rv is not None:
                try:
                    rv = self._watch_once(rv)
                except Exception:
                    rv = None        # malformed response must not kill us
                if rv is None:        # stream broken/truncated: resync
                    try:
                        rv = self._resync()
                    except Exception:
                        rv = None
                    if rv is None:
                        stop.wait(self.poll_interval)
                continue
            try:
                self._poll_once()
            except Exception:
                # Transient server blip: routine for a poller, retried
                # next interval — logged at debug so a persistent outage
                # still leaves a trail.
                _LOG.debug("list-diff poll failed; retrying",
                           exc_info=True)
            stop.wait(self.poll_interval)

    def _resync(self):
        """Atomic-enough resume point: capture the rv BEFORE relisting, so
        events racing the relist get replayed (duplicates are harmless to
        level-triggered consumers) instead of lost."""
        rv0 = self._probe_watch_rv()
        try:
            self._poll_once()
        except Exception:
            _LOG.debug("relist during resync failed; stream will retry",
                       exc_info=True)
        return rv0

    def _probe_watch_rv(self):
        """Returns the server's current rv when /watch exists, else None."""
        try:
            out = self._req("GET", "/watch?sinceRv=999999999&timeoutSeconds=0")
            return int(out.get("resourceVersion", 0))
        except (StoreError, NotFound, Invalid):
            return None

    def _watch_once(self, rv):
        hold = 20.0
        try:
            out = self._req(
                "GET",
                f"/watch?sinceRv={rv}&timeoutSeconds={hold}"
                f"&kinds={','.join(self.watched_kinds)}",
                timeout=hold + 10.0)   # client must outlive the server hold
        except StoreError:
            return None
        if out.get("truncated"):
            return None
        for entry in out.get("events", []):
            kind = entry.get("kind", "")
            obj = entry.get("object", {})
            md = obj.get("metadata", {})
            key = (kind, md.get("namespace", "default"), md.get("name", ""))
            ev = Event(entry.get("type", "MODIFIED"), kind, obj)
            # Legacy /watch has no labelSelector: enforce the watch
            # scope client-side.  An object LEAVING scope (label
            # stripped) becomes a synthetic DELETED — the kube watch
            # contract for selector-scoped streams — so the cache and
            # controllers never hold a phantom entry.
            scope = self._scope(kind)
            if scope and any((md.get("labels") or {}).get(k) != v
                             for k, v in scope.items()):
                if key not in self._known:
                    continue
                ev = Event(Event.DELETED, kind, obj)
            if ev.type == Event.DELETED:
                self._known.pop(key, None)
                self._last.pop(key, None)
            else:
                self._known[key] = md.get("resourceVersion", 0)
                self._last[key] = obj
            for w in list(self._watchers):
                try:
                    w(ev)
                except Exception:
                    # Watcher errors never poison the stream, but a
                    # controller throwing on every event must be visible.
                    _LOG.exception("watcher failed on %s %s",
                                   ev.type, ev.kind)
        return int(out.get("resourceVersion", rv))
