"""Hash-sharded reconcile pools: the 10k-cluster concurrency substrate.

A single work queue serializes every reconcile through one lock and one
condition variable; past a few thousand clusters the queue itself (herd
wakeups, depth bookkeeping, one dirty-set) becomes the contention point
the scale ladder exposes (docs/scaling.md).  The fix is the classic
controller-sharding move: partition reconcile **keys** across N
independent pools by a stable hash.

The invariant that survives the split: **a key hashes to exactly one
pool**, and each pool keeps the workqueue's per-key serialization — so
per-key serialization holds *globally*.  Two workers never reconcile
the same object, no matter how many pools or processes exist, because
there is never a second pool that could hand the key out.

``shard_of`` is a pure function of the key (crc32, NOT Python's salted
``hash``), so:

- shard assignment is stable under requeue, restart, and across
  processes — the property multi-process deployments split per-shard
  leases on (:class:`~kuberay_tpu.controlplane.leader.ShardLeaseElector`);
- replays are deterministic: the same seed routes the same keys to the
  same pools.

:class:`ShardedQueuePool` owns the N :class:`WorkQueue` s and routes
every producer verb through the hash.  Direct ``WorkQueue.add`` calls
outside the router modules are a lint error (analysis rule
``shard-affinity``): an enqueue that bypasses the router can land a key
in the wrong pool and break the one-pool-per-key invariant.
"""

from __future__ import annotations

import threading
import zlib
from typing import Callable, List, Optional, Set, Tuple

from kuberay_tpu.controlplane.workqueue import WorkQueue

Key = Tuple[str, str, str]  # (kind, namespace, name)


def shard_of(key: Key, shards: int) -> int:
    """Stable shard index for a reconcile key.

    crc32 over ``kind/namespace/name``: deterministic across processes
    and Python runs (``hash()`` is seed-salted and would re-deal every
    key on restart, defeating per-shard lease ownership).
    """
    if shards <= 1:
        return 0
    h = zlib.crc32(f"{key[0]}/{key[1]}/{key[2]}".encode("utf-8"))
    return h % shards


class ShardedQueuePool:
    """N per-shard work queues behind one routing surface.

    Producers call :meth:`add`/:meth:`add_after` with a key; the pool
    routes by :func:`shard_of`.  Consumers either bind to one shard
    (``get(shard=i)`` — worker threads pinned to a pool, the
    ``start(workers=N)`` mode) or drain round-robin
    (:meth:`get_any` — the deterministic ``run_until_idle`` mode).

    Ownership: a pool can be *paused* (lease lost) — its queue keeps
    accumulating and deduplicating keys, but hands nothing out until
    :meth:`resume_shard`.  :meth:`drain_shard` waits for the in-flight
    keys of a paused shard to finish — the clean lease-handoff barrier.
    """

    def __init__(self, shards: int = 1,
                 now_fn: Optional[Callable[[], float]] = None,
                 metrics=None, name: str = "manager",
                 shard_fn: Callable[[Key, int], int] = shard_of):
        if shards < 1:
            raise ValueError(f"shards must be >= 1, got {shards}")
        self.shards = shards
        self._shard_fn = shard_fn
        # shards=1 keeps the historical queue name ("manager") so the
        # workqueue depth/latency series and every existing dashboard
        # stay continuous; sharded pools label per shard.
        self.queues: List[WorkQueue] = [
            WorkQueue(now_fn=now_fn, metrics=metrics,
                      name=name if shards == 1 else f"{name}-shard-{i}")
            for i in range(shards)
        ]
        self._rr = 0        # round-robin cursor for get_any

    def shard_of(self, key: Key) -> int:
        return self._shard_fn(key, self.shards)

    def queue_for(self, key: Key) -> WorkQueue:
        return self.queues[self.shard_of(key)]

    # -- producers (the shard router) --------------------------------------

    def add(self, key: Key) -> None:
        self.queue_for(key).add(key)

    def add_after(self, key: Key, after: float) -> None:
        self.queue_for(key).add_after(key, after)

    # -- consumers ---------------------------------------------------------

    def get(self, shard: int, block: bool = True) -> Optional[Key]:
        return self.queues[shard].get(block=block)

    def get_any(self) -> Optional[Key]:
        """Non-blocking pop across pools, round-robin from the cursor —
        deterministic (cursor state is part of the drain order, which is
        single-threaded in ``run_until_idle`` mode) and fair (a hot
        shard cannot starve the others)."""
        for i in range(self.shards):
            idx = (self._rr + i) % self.shards
            key = self.queues[idx].get(block=False)
            if key is not None:
                self._rr = (idx + 1) % self.shards
                return key
        return None

    def done(self, key: Key) -> None:
        self.queue_for(key).done(key)

    # -- ownership (per-shard lease handoff) -------------------------------

    def pause_shard(self, shard: int) -> None:
        self.queues[shard].pause()

    def resume_shard(self, shard: int) -> None:
        self.queues[shard].resume()

    def drain_shard(self, shard: int, timeout: float = 5.0) -> bool:
        """Wait until the shard has no in-flight keys (pause first, or
        new pops keep the horizon open).  Returns False on timeout."""
        return self.queues[shard].wait_idle_processing(timeout=timeout)

    # -- timed requeues / lifecycle (fan-out over pools) -------------------

    def next_delayed_at(self) -> Optional[float]:
        deadlines = [q.next_delayed_at() for q in self.queues]
        deadlines = [d for d in deadlines if d is not None]
        return min(deadlines) if deadlines else None

    def flush_delayed(self) -> None:
        for q in self.queues:
            q.flush_delayed()

    def delayed_items(self) -> List[Tuple[float, Key]]:
        out: List[Tuple[float, Key]] = []
        for q in self.queues:
            out.extend(q.delayed_items())
        return sorted(out)

    def shutdown(self) -> None:
        for q in self.queues:
            q.shutdown()

    def restart(self) -> None:
        for q in self.queues:
            q.restart()

    def depth(self) -> int:
        return sum(q.depth() for q in self.queues)

    def delayed_len(self) -> int:
        return sum(q.delayed_len() for q in self.queues)


class ShardSet:
    """Thread-safe owned-shard set: which shards this process currently
    reconciles.  ``None``-less by design — a Manager always has an
    explicit set (default: all shards), so the hot path is a plain
    membership test."""

    def __init__(self, shards: int, owned: Optional[Set[int]] = None):
        self._lock = threading.Lock()
        self.shards = shards
        self._owned: Set[int] = (set(range(shards)) if owned is None
                                 else set(owned))

    def owns(self, shard: int) -> bool:
        with self._lock:
            return shard in self._owned

    def add(self, shard: int) -> bool:
        with self._lock:
            if shard in self._owned:
                return False
            self._owned.add(shard)
            return True

    def discard(self, shard: int) -> bool:
        with self._lock:
            if shard not in self._owned:
                return False
            self._owned.discard(shard)
            return True

    def snapshot(self) -> Set[int]:
        with self._lock:
            return set(self._owned)
