"""TpuCronJob reconciler (ref raycronjob_controller.go:93-135).

Cron schedule -> TpuJob creation with missed-run catch-up against
``lastScheduleTime``, concurrency policies, and history-limit pruning.
Feature-gated (``TpuCronJob``) like the reference.
"""

from __future__ import annotations

import copy
import time
from typing import List, Optional

from kuberay_tpu.api.tpucronjob import ConcurrencyPolicy, TpuCronJob
from kuberay_tpu.api.tpujob import JobDeploymentStatus
from kuberay_tpu.builders.common import owner_reference
from kuberay_tpu.controlplane.events import EventRecorder
from kuberay_tpu.controlplane.store import (AlreadyExists, NotFound,
                                             ObjectStore)
from kuberay_tpu.obs.trace import NOOP_TRACER
from kuberay_tpu.utils import constants as C
from kuberay_tpu.utils import features
from kuberay_tpu.utils.cron import CronError, missed_runs, next_run_after
from kuberay_tpu.utils.names import truncate_name
from kuberay_tpu.utils.validation import validate_cronjob

_TERMINAL = (JobDeploymentStatus.COMPLETE, JobDeploymentStatus.FAILED)


class TpuCronJobController:
    KIND = C.KIND_CRONJOB

    def __init__(self, store: ObjectStore,
                 recorder: Optional[EventRecorder] = None,
                 tracer=None, scheduler=None):
        self.store = store
        self.recorder = recorder or EventRecorder(store)
        # Span annotations — no-op by default, passed like ``metrics``.
        self.tracer = tracer or NOOP_TRACER
        # Gang scheduler: a due run is only launched when its prospective
        # job would clear quota admission (deadline fleets under
        # contention hold as catch-up instead of piling on denied jobs).
        self.scheduler = scheduler

    def reconcile(self, name: str, namespace: str = "default") -> Optional[float]:
        raw = self.store.try_get(self.KIND, name, namespace)
        if raw is None:
            return None
        # kuberay-lint: disable-next-line=reconcile-exception-escape -- FeatureGateError means a typo'd compile-time gate constant; crashing into backoff is the loudest correct behavior
        if not features.enabled("TpuCronJob"):
            return None
        cron = TpuCronJob.from_dict(raw)
        # Snapshot status for the update throttle; the final write
        # carries the reconcile-start rv (SURVEY §5.2).
        cron._orig_status = copy.deepcopy(raw.get("status", {}))
        if cron.metadata.deletionTimestamp:
            return None   # child jobs are GC'd via ownerReferences

        errs = validate_cronjob(cron)
        if errs:
            self.recorder.warning(raw, C.EVENT_INVALID_SPEC, "; ".join(errs))
            return None

        now = time.time()
        self._refresh_active(cron)

        if not cron.spec.suspend:
            horizon = cron.spec.startingDeadlineSeconds or 86400
            last = cron.status.lastScheduleTime or cron.metadata.creationTimestamp
            try:
                due = missed_runs(cron.spec.schedule, last, now,
                                  horizon_seconds=horizon)
            except CronError as e:
                # validate_cronjob pre-checks the schedule, but an object
                # written by an older/looser validator must degrade to an
                # event, not crash the reconcile worker.
                self.recorder.warning(raw, C.EVENT_INVALID_SPEC,
                                      f"schedule: {e}")
                return None
            if due and self._preemption_active(cron.metadata.namespace):
                # Backfill hold: while slices in the namespace sit under
                # an active preemption notice, batch launches would race
                # the replacement provisioning for capacity.  Keep
                # lastScheduleTime so the run fires as catch-up (backfill
                # onto the reclaimed capacity) once the drill clears,
                # bounded by startingDeadlineSeconds like any miss.
                self.recorder.normal(
                    cron.to_dict(), "BackfillHold",
                    "deferring scheduled run: preemption drill active "
                    "in namespace")
                self._prune_history(cron)
                self._update_status(cron)
                return 5.0
            if due:
                # Only the most recent missed run is executed (standard
                # CronJob catch-up semantics; the rest are logged as missed).
                if len(due) > 1:
                    self.recorder.warning(
                        cron.to_dict(), "MissedRuns",
                        f"{len(due) - 1} scheduled runs were missed")
                outcome = self._launch(cron, due[-1])
                if outcome == "launched":
                    cron.status.lastScheduleTime = due[-1]
                elif outcome == "quota-held":
                    # Keep lastScheduleTime so the run fires as catch-up
                    # once quota clears (the pending gang is tracked by
                    # the QuotaManager's starvation guard), bounded by
                    # startingDeadlineSeconds like any miss.
                    self._prune_history(cron)
                    self._update_status(cron)
                    return 5.0
                # Forbid-skipped runs keep lastScheduleTime so the run still
                # fires once the active job finishes (standard CronJob
                # behavior), bounded by startingDeadlineSeconds.

        self._prune_history(cron)
        self._update_status(cron)
        try:
            nxt = next_run_after(cron.spec.schedule, now)
        except CronError as e:
            self.recorder.warning(raw, C.EVENT_INVALID_SPEC,
                                  f"schedule: {e}")
            return None
        return max(1.0, nxt - now) if nxt else None

    # ------------------------------------------------------------------

    def _job_name(self, cron: TpuCronJob, scheduled: float) -> str:
        # Minute-resolution schedule time makes the name deterministic, so
        # double-reconciles cannot double-launch (create is the idempotency
        # barrier).
        return truncate_name(f"{cron.metadata.name}-{int(scheduled) // 60}")

    def _preemption_active(self, namespace: str) -> bool:
        """Any live (non-deleting) pod in the namespace under an active,
        undrained preemption notice: its capacity is about to vanish
        and the replacement claim/build is in flight."""
        for p in self.store.list("Pod", namespace):
            md = p.get("metadata", {})
            if md.get("deletionTimestamp"):
                continue
            ann = md.get("annotations", {}) or {}
            if ann.get(C.ANNOTATION_PREEMPTION_NOTICE) and \
                    not ann.get(C.ANNOTATION_DRAINED_AT):
                return True
        return False

    def _refresh_active(self, cron: TpuCronJob):
        active = []
        for jname in cron.status.activeJobNames:
            job = self.store.try_get(C.KIND_JOB, jname, cron.metadata.namespace)
            if job is None:
                continue
            if job.get("status", {}).get("jobDeploymentStatus") not in _TERMINAL:
                active.append(jname)
        cron.status.activeJobNames = active

    def _launch(self, cron: TpuCronJob, scheduled: float) -> str:
        """-> ``"launched"`` (job created or already exists),
        ``"skipped"`` (concurrency policy), or ``"quota-held"``
        (prospective job would be denied admission; caller keeps
        lastScheduleTime for catch-up)."""
        policy = cron.spec.concurrencyPolicy
        if cron.status.activeJobNames:
            if policy == ConcurrencyPolicy.FORBID:
                self.recorder.normal(cron.to_dict(), "SkippedRun",
                                     "previous run still active (Forbid)")
                return "skipped"
            if policy == ConcurrencyPolicy.REPLACE:
                for jname in cron.status.activeJobNames:
                    try:
                        self.store.delete(C.KIND_JOB, jname,
                                          cron.metadata.namespace)
                    except NotFound:
                        pass
                cron.status.activeJobNames = []

        jname = self._job_name(cron, scheduled)
        job = {
            "apiVersion": C.API_VERSION,
            "kind": C.KIND_JOB,
            "metadata": {
                "name": jname,
                "namespace": cron.metadata.namespace,
                "labels": {
                    C.LABEL_ORIGINATED_FROM_CR_NAME: cron.metadata.name,
                    C.LABEL_ORIGINATED_FROM_CRD: C.KIND_CRONJOB,
                },
                "ownerReferences": [owner_reference(
                    C.KIND_CRONJOB, cron.metadata.name, cron.metadata.uid)],
            },
            "spec": cron.spec.jobTemplate.to_dict(),
            "status": {},
        }
        verdict = self._admission_verdict(job)
        if verdict is not None and not verdict:
            reason = getattr(verdict, "reason", "") or "capacity-hold"
            self.recorder.normal(
                cron.to_dict(), C.EVENT_QUOTA_HELD,
                f"deferring scheduled run {jname}: {reason}")
            return "quota-held"
        try:
            self.store.create(job)
            cron.status.activeJobNames.append(jname)
            self.recorder.normal(cron.to_dict(), "LaunchedJob",
                                 f"launched {jname}")
        except AlreadyExists:
            pass
        return "launched"

    def _admission_verdict(self, job):
        """THE capacity seam (analysis rule #13) for cron launches: the
        prospective job is probed against the QuotaManager *ledger*
        directly (no PodGroup side effects for runs that never fire);
        admission reserves the claim the launched job then re-asserts
        idempotently.  ``None`` when no quota-backed scheduler is
        mounted — oracle-only schedulers gate the job itself in
        Initializing instead."""
        quota = getattr(self.scheduler, "quota", None)
        if quota is None:
            return None
        from kuberay_tpu.controlplane.quota import (build_demand,
                                                    job_pseudo_cluster)
        pseudo = job_pseudo_cluster(job)
        if pseudo is None:
            return None
        return quota.admit(build_demand(pseudo))

    def _prune_history(self, cron: TpuCronJob):
        ns = cron.metadata.namespace
        children = self.store.list(
            C.KIND_JOB, ns,
            labels={C.LABEL_ORIGINATED_FROM_CR_NAME: cron.metadata.name,
                    C.LABEL_ORIGINATED_FROM_CRD: C.KIND_CRONJOB})
        finished: List[tuple] = []
        for job in children:
            st = job.get("status", {})
            if st.get("jobDeploymentStatus") in _TERMINAL:
                finished.append((
                    st.get("jobDeploymentStatus") == JobDeploymentStatus.COMPLETE,
                    st.get("endTime", 0.0), job["metadata"]["name"]))
        for ok, limit in ((True, cron.spec.successfulJobsHistoryLimit),
                          (False, cron.spec.failedJobsHistoryLimit)):
            bucket = sorted([f for f in finished if f[0] == ok],
                            key=lambda f: f[1], reverse=True)
            for _, _, jname in bucket[limit:]:
                try:
                    self.store.delete(C.KIND_JOB, jname, ns)
                except NotFound:
                    pass

    def _update_status(self, cron: TpuCronJob):
        obj = cron.to_dict()
        # rv precondition = the reconcile-start snapshot (no pre-write
        # re-read): a foreign write anywhere in the pass 409s and
        # requeues instead of being clobbered (SURVEY §5.2).
        if obj.get("status") == getattr(cron, "_orig_status", None):
            return
        with self.tracer.span("store-write", kind=self.KIND,
                              obj=cron.metadata.name):
            try:
                out = self.store.update_status(obj)
            except NotFound:
                return      # deleted mid-reconcile
        cron.metadata.resourceVersion = out["metadata"]["resourceVersion"]
        cron._orig_status = copy.deepcopy(out.get("status", {}))
