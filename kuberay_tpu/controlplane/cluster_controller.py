"""TpuCluster reconciler: the only component that creates/deletes pods.

Level-triggered, idempotent, slice-atomic.  The reconcile pipeline mirrors
the reference's (raycluster_controller.go:330-341 ``reconcileFuncs`` and
:902 ``reconcilePods``) with the multi-host invariants of
``reconcileMultiHostWorkerGroup`` (:1246-1410) promoted to *the* scaling
algorithm — every group scales in whole slices:

1.  validation -> InvalidSpec condition (never a crash)
2.  deletion path: state cleanup + finalizer release
3.  services: head, headless (multi-host peer DNS), serve
4.  pods:
    - suspend: delete everything, mark Suspended
    - Recreate upgrade on pod-template hash drift
    - gang admission hook (scheduler plugin)
    - head pod create/repair
    - per group: clean incomplete slices -> delete unhealthy slices whole
      -> honor autoscaler slicesToDelete -> diff in slice units
5.  status: ready counts, conditions, throttled update
"""

from __future__ import annotations

import os
import random
import time
from typing import Any, Dict, List, Optional

from kuberay_tpu.api.common import Condition, set_condition
from kuberay_tpu.api.computetemplate import resolve_compute_templates
from kuberay_tpu.api.tpucluster import (
    ClusterConditionType,
    ClusterState,
    TpuCluster,
    UpgradeStrategyType,
    WorkerGroupSpec,
)
from kuberay_tpu.builders.pod import build_head_pod, build_slice_pods
from kuberay_tpu.builders.service import (
    build_head_service,
    build_headless_service,
    needs_headless_service,
)
from kuberay_tpu.controlplane.events import EventRecorder
from kuberay_tpu.controlplane.quota import QuotaVerdict
from kuberay_tpu.controlplane.expectations import HEAD_GROUP, ScaleExpectations
from kuberay_tpu.controlplane.store import (AlreadyExists, Conflict,
                                             NotFound, ObjectStore,
                                             StoreError)
from kuberay_tpu.controlplane.warmpool_controller import KIND_WARM_POOL
from kuberay_tpu.obs.goodput import NOOP_TRANSITIONS
from kuberay_tpu.obs.trace import NOOP_TRACER
from kuberay_tpu.utils import constants as C
from kuberay_tpu.utils import features
from kuberay_tpu.utils.names import head_service_name, spec_hash
from kuberay_tpu.utils.validation import (
    validate_cluster,
    validate_cluster_status,
    waive_create_only,
)

POD_SPEC_HASH_ANNOTATION = "tpu.dev/pod-template-hash"


def pod_phase(pod: Dict[str, Any]) -> str:
    return pod.get("status", {}).get("phase", "Pending")


def pod_failed(pod: Dict[str, Any]) -> bool:
    # Workers/head never legitimately Succeed while the cluster lives
    # (ref shouldDeletePod raycluster_controller.go:1464).
    return pod_phase(pod) in ("Failed", "Succeeded")


def pod_running(pod: Dict[str, Any]) -> bool:
    return pod_phase(pod) == "Running"


def pod_deleting(pod: Dict[str, Any]) -> bool:
    return bool(pod.get("metadata", {}).get("deletionTimestamp"))


class TpuClusterController:
    KIND = C.KIND_CLUSTER

    def __init__(self, store: ObjectStore,
                 expectations: Optional[ScaleExpectations] = None,
                 recorder: Optional[EventRecorder] = None,
                 scheduler=None,
                 config_env: Optional[Dict[str, str]] = None,
                 metrics=None,
                 use_openshift_route: bool = False,
                 tracer=None,
                 transitions=None,
                 warmpool=None,
                 client_provider=None,
                 pod_delete_rng: Optional[random.Random] = None):
        self.store = store
        self.exp = expectations or ScaleExpectations()
        self.recorder = recorder or EventRecorder(store)
        self.scheduler = scheduler        # gang plugin (scheduler/ package)
        self.config_env = config_env or {}
        self.metrics = metrics
        # Span annotations (store-write, slice-ready) — no-op by default,
        # passed like ``metrics`` (kuberay_tpu.obs.trace).
        self.tracer = tracer or NOOP_TRACER
        # State-transition seam (obs.goodput): every .status.state write
        # routes through it (analysis rule phase-transition-recorded).
        self.transitions = transitions or NOOP_TRANSITIONS
        # (ns, cluster, group, slice idx) already observed ready: the
        # slice-ready duration (north-star) is emitted once per
        # provisioning — a slice that fails and is rebuilt re-observes.
        self._slices_observed_ready: set = set()
        # OpenShift clusters expose the head via a Route (openshift.go).
        self.use_openshift_route = use_openshift_route
        # Preemption lifecycle (docs/preemption.md): a WarmSlicePool
        # controller to claim pre-provisioned replacements from on an
        # advance notice, and a coordinator-client provider
        # (status -> client) for the checkpoint-drain hook.
        self.warmpool = warmpool
        self.client_provider = client_provider
        # Victim-shuffle source for ENV_ENABLE_RANDOM_POD_DELETE: an
        # injectable instance, so deterministic harnesses can seed it
        # (module-level random would leak wall-entropy into reconciles).
        self._pod_delete_rng = pod_delete_rng or random.Random()
        # (ns, cluster, group, slice name) -> first-sight wall clock of an
        # active preemption notice; closed (warned-recovery observed)
        # once the slice is gone and the group is back at readiness.
        self._notice_started: Dict[tuple, float] = {}

    # ------------------------------------------------------------------
    # entry point
    # ------------------------------------------------------------------

    def reconcile(self, name: str, namespace: str = "default") -> Optional[float]:
        """Returns requeue-after seconds or None.

        Optimistic-concurrency contract (SURVEY §5.2): ``raw`` is the
        reconcile-start snapshot and every decision below derives from
        it, so every write in the pass carries ITS resourceVersion —
        threaded through ``cluster.metadata.resourceVersion`` and bumped
        only by our own writes' return values, never by a pre-write
        re-read.  A foreign write anywhere in the pass (leader-failover
        overlap) therefore 409s and requeues instead of being clobbered.
        """
        raw = self.store.try_get(self.KIND, name, namespace)
        if raw is None:
            self.exp.forget_cluster(namespace, name)
            self._forget_ready(namespace, name)
            return None
        cluster = TpuCluster.from_dict(raw)

        # Kueue-style external management (ref ManagedBy skip :155).
        if cluster.spec.managedBy and cluster.spec.managedBy != C.CREATED_BY_OPERATOR:
            return None

        if cluster.metadata.deletionTimestamp:
            return self._reconcile_deletion(cluster)

        # Resolve named slice presets before validation so a template-filled
        # group is validated exactly like an explicit one (server-side, so
        # every client benefits — ref apiserver ComputeTemplate resolution).
        errs = resolve_compute_templates(cluster, self.store)
        # kuberay-lint: disable-next-line=reconcile-exception-escape -- FeatureGateError means a typo'd compile-time gate constant; crashing into backoff is the loudest correct behavior
        errs += waive_create_only(validate_cluster(cluster))
        # Status sanity (ref ValidateRayClusterStatus :23): mutually
        # exclusive suspend conditions mean a forged/corrupt status.
        errs += validate_cluster_status(cluster)
        if errs:
            self.recorder.warning(raw, C.EVENT_INVALID_SPEC, "; ".join(errs))
            # kuberay-lint: disable-next-line=reconcile-exception-escape -- StoreError (write without resourceVersion) is a programming error in _write_status; it must fail loud, not be swallowed into a requeue
            self._set_status(cluster, state=ClusterState.FAILED,
                             reason="; ".join(errs)[:500])
            return None

        self._ensure_finalizer(cluster)
        self._reconcile_services(cluster)
        requeue = self._reconcile_pods(cluster, raw)
        self._update_status(cluster)
        return requeue

    # ------------------------------------------------------------------
    # deletion (ref :193-326 GCS-FT deletion path)
    # ------------------------------------------------------------------

    def _needs_cleanup_finalizer(self, cluster: TpuCluster) -> bool:
        hso = cluster.spec.headStateOptions
        return hso is not None and hso.backend == "external"

    def _ensure_finalizer(self, cluster: TpuCluster):
        if self._needs_cleanup_finalizer(cluster):
            if C.FINALIZER_GCS_FT not in cluster.metadata.finalizers:
                out = self.store.add_finalizer(
                    self.KIND, cluster.metadata.name,
                    cluster.metadata.namespace, C.FINALIZER_GCS_FT,
                    rv=cluster.metadata.resourceVersion)
                cluster.metadata.finalizers.append(C.FINALIZER_GCS_FT)
                cluster.metadata.resourceVersion = \
                    out["metadata"]["resourceVersion"]

    def _reconcile_deletion(self, cluster: TpuCluster) -> Optional[float]:
        ns, name = cluster.metadata.namespace, cluster.metadata.name
        pods = self._cluster_pods(cluster)
        # Even a full teardown honors the drain contract: pods under an
        # active preemption notice get their checkpoint request first.
        if not self._drain_noticed(cluster, pods):
            return 1.0
        # Head-pod-first deletion so workers don't thrash reconnecting
        # (ref head-first delete :240-ish), then the rest.
        head = [p for p in pods if p["metadata"]["labels"].get(
            C.LABEL_NODE_TYPE) == C.NODE_TYPE_HEAD]
        rest = [p for p in pods if p not in head]
        for p in head + rest:
            self._delete_pod(p)
        if self._needs_cleanup_finalizer(cluster):
            # External coordinator-state cleanup (ref Redis cleanup Job):
            # spawn a cleanup Job object; release finalizer once it succeeds
            # or after the timeout annotation.
            done = self._reconcile_cleanup_job(cluster)
            if not done:
                return 5.0
            self.store.remove_finalizer(self.KIND, name, ns, C.FINALIZER_GCS_FT)
        self.exp.forget_cluster(ns, name)
        self._forget_ready(ns, name)
        if self.scheduler is not None:
            self.scheduler.cleanup(cluster.to_dict())
        return None

    def _forget_ready(self, namespace: str, name: str):
        self._slices_observed_ready = {
            k for k in self._slices_observed_ready
            if not (k[0] == namespace and k[1] == name)}
        self._notice_started = {
            k: v for k, v in self._notice_started.items()
            if not (k[0] == namespace and k[1] == name)}

    def _reconcile_cleanup_job(self, cluster: TpuCluster) -> bool:
        ns, name = cluster.metadata.namespace, cluster.metadata.name
        job_name = f"{name}-state-cleanup"
        job = self.store.try_get("Job", job_name, ns)
        if job is None:
            hso = cluster.spec.headStateOptions
            self.store.create({
                "apiVersion": "batch/v1", "kind": "Job",
                "metadata": {
                    "name": job_name, "namespace": ns,
                    "labels": {C.LABEL_CLUSTER: name,
                               C.LABEL_CREATED_BY: C.CREATED_BY_OPERATOR},
                },
                "spec": {"template": {"spec": {"containers": [{
                    "name": "cleanup",
                    "command": ["python", "-m", "kuberay_tpu.runtime.state_cleanup",
                                "--address", hso.externalStorageAddress,
                                "--namespace",
                                hso.externalStorageNamespace or cluster.metadata.uid],
                }], "restartPolicy": "Never"}}},
                "status": {},
            })
            return False
        # Timeout guard (ref gcs-ft-deletion-timeout annotation).
        timeout = float(cluster.metadata.annotations.get(
            C.ANNOTATION_FT_DELETION_TIMEOUT, "300"))
        if job.get("status", {}).get("succeeded"):
            return True
        started = job["metadata"].get("creationTimestamp", 0)
        if not started:
            # A store backend that omits creationTimestamp must not make
            # the timeout instantly true (finalizer released without the
            # cleanup having run).  Stamp the observation time into an
            # ANNOTATION — store.update force-restores creationTimestamp
            # from its stored copy, so writing that field would be
            # silently discarded.
            ann = job["metadata"].setdefault("annotations", {})
            started = float(ann.get(C.ANNOTATION_CLEANUP_OBSERVED_AT, 0))
            if not started:
                ann[C.ANNOTATION_CLEANUP_OBSERVED_AT] = str(time.time())
                self.store.update(job)
                return False
        return time.time() - started > timeout

    # ------------------------------------------------------------------
    # services
    # ------------------------------------------------------------------

    def _ensure(self, obj: Dict[str, Any]):
        try:
            self.store.create(obj)
            self.recorder.normal(obj, C.EVENT_CREATED_SERVICE,
                                 f"created {obj['kind']} {obj['metadata']['name']}")
        except AlreadyExists:
            pass

    def _reconcile_services(self, cluster: TpuCluster):
        self._ensure(build_head_service(cluster))
        if needs_headless_service(cluster):
            self._ensure(build_headless_service(cluster))
        if cluster.spec.headGroupSpec.enableIngress:
            if self.use_openshift_route:
                from kuberay_tpu.builders.ingress import build_head_route
                self._ensure(build_head_route(cluster))
            else:
                from kuberay_tpu.builders.ingress import build_head_ingress
                self._ensure(build_head_ingress(cluster))
        if cluster.spec.enableTokenAuth:
            # _ensure never rotates: Secrets carry no spec, so the compare
            # is always equal and only the initial create happens.
            from kuberay_tpu.builders.auth import build_auth_secret
            self._ensure(build_auth_secret(cluster))

    # ------------------------------------------------------------------
    # pods
    # ------------------------------------------------------------------

    def _cluster_pods(self, cluster: TpuCluster) -> List[Dict[str, Any]]:
        return self.store.list(
            "Pod", cluster.metadata.namespace,
            labels={C.LABEL_CLUSTER: cluster.metadata.name})

    def _delete_pod(self, pod: Dict[str, Any], group: str = ""):
        """Expectation is recorded BEFORE the API call: the store notifies
        watchers synchronously, so recording after would lose the event and
        wedge the group until the expectation timeout (the same ordering
        contract the reference's expectations follow)."""
        md = pod["metadata"]
        cluster = md["labels"].get(C.LABEL_CLUSTER, "")
        group = group or md["labels"].get(C.LABEL_GROUP, HEAD_GROUP)
        self.exp.expect_delete(md["namespace"], cluster, group, md["name"])
        try:
            self.store.delete("Pod", md["name"], md["namespace"])
        except NotFound:
            self.exp.forget(md["namespace"], cluster, group, md["name"])

    def _create_pod(self, pod: Dict[str, Any], group: str):
        md = pod["metadata"]
        cluster = md["labels"].get(C.LABEL_CLUSTER, "")
        self.exp.expect_create(md["namespace"], cluster, group, md["name"])
        try:
            self.store.create(pod)
        except AlreadyExists:
            self.exp.forget(md["namespace"], cluster, group, md["name"])

    def _template_hash(self, cluster: TpuCluster) -> str:
        spec = cluster.spec.to_dict()
        return spec_hash({
            "auth": spec.get("enableTokenAuth", False),
            "head": spec.get("headGroupSpec"),
            "groups": [
                {k: v for k, v in g.items()
                 if k in ("groupName", "accelerator", "topology", "template",
                          "startParams")}
                for g in spec.get("workerGroupSpecs", [])
            ],
        })

    def _reconcile_pods(self, cluster: TpuCluster,
                        raw: Dict[str, Any]) -> Optional[float]:
        ns, name = cluster.metadata.namespace, cluster.metadata.name
        pods = self._cluster_pods(cluster)

        # Suspend: delete all (ref :912-927), Kueue-compatible quiescence.
        if cluster.spec.suspend:
            if not self._drain_noticed(cluster, pods):
                return 1.0
            for p in pods:
                self._delete_pod(p)
            return None

        # Recreate-upgrade: template hash drift deletes everything
        # (ref :941-954).
        thash = self._template_hash(cluster)
        if cluster.spec.upgradeStrategy == UpgradeStrategyType.RECREATE:
            stale = [p for p in pods
                     if p["metadata"].get("annotations", {}).get(
                         POD_SPEC_HASH_ANNOTATION) not in (None, thash)]
            if stale:
                if not self._drain_noticed(cluster, pods):
                    return 1.0
                for p in pods:
                    self._delete_pod(p)
                return 1.0

        # Gang admission (ref DoBatchSchedulingOnSubmission :963-971): the
        # scheduler's quota/capacity oracle reserves the whole cluster
        # before any pod exists; every create below is gated on the
        # admitted verdict (analysis rule #13 capacity-through-quota-seam).
        verdict = self._admission_verdict(cluster)
        if verdict is not None:
            if not verdict:
                return self._hold_for_admission(cluster, pods, verdict)
            set_condition(cluster.status.conditions, Condition(
                type=ClusterConditionType.GANG_ADMITTED, status="True",
                reason="Admitted",
                observedGeneration=cluster.metadata.generation))

        requeue = None
        live = [p for p in pods if not pod_deleting(p)]

        # --- head (ref :974-1031) ---
        if self.exp.satisfied(ns, name, HEAD_GROUP):
            heads = [p for p in live if p["metadata"]["labels"].get(
                C.LABEL_NODE_TYPE) == C.NODE_TYPE_HEAD]
            if any(pod_failed(p) for p in heads):
                for p in heads:
                    if pod_failed(p):
                        self.recorder.warning(
                            cluster.to_dict(), C.EVENT_DELETED_POD,
                            f"restarting failed head pod {p['metadata']['name']}")
                        self._delete_pod(p)
                requeue = 1.0
            elif not heads:
                pod = build_head_pod(cluster, self.config_env)
                pod["metadata"].setdefault("annotations", {})[
                    POD_SPEC_HASH_ANNOTATION] = thash
                if self.scheduler is not None:
                    self.scheduler.add_metadata(cluster.to_dict(), pod)
                self._create_pod(pod, HEAD_GROUP)
                self.recorder.normal(cluster.to_dict(), C.EVENT_CREATED_POD,
                                     f"created head pod {pod['metadata']['name']}")

        # --- worker groups, slice-atomic (ref :1034 + :1246-1410) ---
        # One pod list serves every group (avoids O(groups x pods) store
        # scans); per-group deletions only touch that group's own slices.
        for group in cluster.spec.workerGroupSpecs:
            r = self._reconcile_worker_group(cluster, group, thash, live, raw)
            requeue = min(r, requeue) if (r and requeue) else (r or requeue)
        return requeue

    def _admission_verdict(self, cluster: TpuCluster):
        """THE capacity seam (analysis rule #13): the only place the
        controller consults the gang scheduler's quota/capacity oracle.
        ``None`` means no scheduler is mounted (admission-free mode);
        plain-bool oracles from external scheduler adapters are
        normalized to a QuotaVerdict."""
        if self.scheduler is None:
            return None
        verdict = self.scheduler.on_cluster_submission(cluster.to_dict())
        if isinstance(verdict, QuotaVerdict):
            return verdict
        return QuotaVerdict(bool(verdict),
                            reason="" if verdict else "capacity-hold")

    def _hold_for_admission(self, cluster: TpuCluster,
                            pods: List[Dict[str, Any]],
                            verdict) -> float:
        """Denied verdict: surface it (condition + event — the
        scheduler already counted it in tpu_gang_admission_total) and
        requeue.  ``evict`` means quota reclaim outran the notice
        window: tear the whole gang down through the drain seam so the
        gang stays 0-or-full (eviction is a warned preemption — the
        notices were stamped when reclaim fired, so draining here
        acks checkpoints, never ambushes them)."""
        reason = verdict.reason or "capacity-hold"
        changed = set_condition(cluster.status.conditions, Condition(
            type=ClusterConditionType.GANG_ADMITTED, status="False",
            reason="QuotaEvicting" if verdict.evict else "QuotaHeld",
            message=reason,
            observedGeneration=cluster.metadata.generation))
        if changed:
            self.recorder.warning(
                cluster.to_dict(), C.EVENT_QUOTA_HELD,
                f"gang admission denied: {reason}")
        if not verdict.evict:
            return 5.0
        # Re-read: the admission call itself may have just (re)stamped
        # preemption notices (QuotaManager level-triggers expired
        # reclaims), and the caller's list predates that write — a
        # stale view here would skip the drain and ambush the pods.
        pods = self._cluster_pods(cluster)
        live = [p for p in pods if not pod_deleting(p)]
        if not self._drain_noticed(cluster, live):
            return 1.0
        for group in cluster.spec.workerGroupSpecs:
            slices = self._group_pods_by_slice(live, group)
            for idx in sorted(slices):
                self._delete_slice(cluster, slices[idx], group.groupName)
        for p in live:
            if p["metadata"]["labels"].get(
                    C.LABEL_NODE_TYPE) == C.NODE_TYPE_HEAD:
                self._delete_pod(p)
        if live:
            self.recorder.warning(
                cluster.to_dict(), C.EVENT_QUOTA_EVICTED,
                f"quota reclaim evicted the gang: {reason}")
        return 1.0

    def _group_pods_by_slice(self, pods: List[Dict[str, Any]],
                             group: WorkerGroupSpec
                             ) -> Dict[int, List[Dict[str, Any]]]:
        out: Dict[int, List[Dict[str, Any]]] = {}
        for p in pods:
            labels = p["metadata"]["labels"]
            if labels.get(C.LABEL_GROUP) != group.groupName:
                continue
            try:
                idx = int(labels.get(C.LABEL_SLICE_INDEX, "-1"))
            except ValueError:
                idx = -1
            out.setdefault(idx, []).append(p)
        return out

    def _reconcile_worker_group(self, cluster: TpuCluster,
                                group: WorkerGroupSpec,
                                thash: str,
                                live_pods: List[Dict[str, Any]],
                                raw: Dict[str, Any]
                                ) -> Optional[float]:
        ns, name = cluster.metadata.namespace, cluster.metadata.name
        if not self.exp.satisfied(ns, name, group.groupName):
            return 1.0

        slices = self._group_pods_by_slice(live_pods, group)
        topo = group.slice_topology()
        hosts = topo.num_hosts
        requeue: Optional[float] = None

        if group.suspend:
            for plist in slices.values():
                if not self._delete_slice(cluster, plist, group.groupName):
                    requeue = 1.0
            return requeue

        # 0. Advance-notice preemptions: note first sight (metric, event,
        #    recovery clock) before any teardown/diff decision below.
        noticed_idx = self._note_preemptions(cluster, group, slices)

        # 1. Incomplete slices are useless (no ICI ring): delete whole
        #    (ref :1257-1267).
        for idx, plist in list(slices.items()):
            if idx < 0 or len(plist) != hosts or \
                    len({p["metadata"]["labels"].get(C.LABEL_HOST_INDEX)
                         for p in plist}) != hosts:
                if not self._delete_slice(cluster, plist, group.groupName):
                    requeue = 1.0
                    continue
                self.recorder.warning(
                    cluster.to_dict(), C.EVENT_DELETED_SLICE,
                    f"deleted incomplete slice {group.groupName}/{idx} "
                    f"({len(plist)}/{hosts} hosts)")
                del slices[idx]
                noticed_idx.discard(idx)

        # 2. Any failed host poisons the whole slice (ref :1269-1289).
        for idx, plist in list(slices.items()):
            if any(pod_failed(p) for p in plist):
                if not self._delete_slice(cluster, plist, group.groupName):
                    requeue = 1.0
                    continue
                self.recorder.warning(
                    cluster.to_dict(), C.EVENT_UNHEALTHY_SLICE,
                    f"deleted unhealthy slice {group.groupName}/{idx}")
                del slices[idx]
                noticed_idx.discard(idx)

        # 3. Autoscaler-named victims expand to whole slices (ref :1293-1322;
        #    here the contract is already slice-granular).  Executed victims
        #    are CLEARED from the spec: slice names are deterministic, so a
        #    stale entry would re-kill a later recreation of the same index.
        victims = set(group.scaleStrategy.slicesToDelete or [])
        if victims:
            executed = set()
            for idx, plist in list(slices.items()):
                sname = plist[0]["metadata"]["labels"].get(C.LABEL_SLICE_NAME)
                if sname in victims:
                    if not self._delete_slice(cluster, plist,
                                              group.groupName):
                        requeue = 1.0
                        continue
                    del slices[idx]
                    noticed_idx.discard(idx)
                    executed.add(sname)
            if executed:
                self._clear_executed_victims(cluster, raw,
                                             group.groupName, executed)

        # 4. Diff in slice units (ref :1343-1378).  Slices under an active
        #    notice count against a RAISED target (desired + noticed,
        #    capped at maxReplicas): the replacement is pre-provisioned
        #    while the doomed slice still runs — slice atomicity holds,
        #    the old slice stays whole until the new one is Ready.
        desired = max(0, group.replicas)
        pending = {i for i in noticed_idx if i in slices}
        target = desired + len(pending)
        if group.maxReplicas:
            target = min(target, max(desired, group.maxReplicas))
        have = len(slices)
        if have < target:
            used = set(slices.keys())
            next_idx = 0
            created = 0
            reason = "preemption" if pending else "scale-up"
            while created < target - have:
                if next_idx in used:
                    next_idx += 1
                    continue
                if self._claim_warm_slice(cluster, group, next_idx, reason):
                    used.add(next_idx)
                    created += 1
                    continue
                new_pods = build_slice_pods(cluster, group, next_idx,
                                            config_env=self.config_env)
                for p in new_pods:
                    p["metadata"].setdefault("annotations", {})[
                        POD_SPEC_HASH_ANNOTATION] = thash
                    if self.scheduler is not None:
                        self.scheduler.add_metadata(cluster.to_dict(), p)
                    self._create_pod(p, group.groupName)
                self.recorder.normal(
                    cluster.to_dict(), C.EVENT_CREATED_SLICE,
                    f"created slice {group.groupName}/{next_idx} ({hosts} hosts)")
                used.add(next_idx)
                created += 1
        elif have > target:
            # Scale down: autoscaler owns victim choice when enabled
            # (ref :1181-1239); otherwise delete highest indices first
            # (deterministic; ENABLE_RANDOM_POD_DELETE env restores the
            # reference's random choice).  Noticed slices are never
            # scale-down victims — their teardown is the retirement path
            # below, gated on replacement readiness.
            excess = have - target
            if cluster.spec.enableInTreeAutoscaling and not victims:
                return requeue  # wait for slicesToDelete
            order = [i for i in sorted(slices.keys(), reverse=True)
                     if i not in pending]
            if os.environ.get(C.ENV_ENABLE_RANDOM_POD_DELETE) == "true":
                self._pod_delete_rng.shuffle(order)
            for idx in order[:excess]:
                if not self._delete_slice(cluster, slices[idx],
                                          group.groupName):
                    requeue = 1.0
                    continue
                self.recorder.normal(
                    cluster.to_dict(), C.EVENT_DELETED_SLICE,
                    f"scaled down slice {group.groupName}/{idx}")
                del slices[idx]

        # 5. Retire noticed slices once replacement capacity is Ready:
        #    the drain (checkpoint request + drained-at stamp) happens
        #    inside the seam, before the kill deadline lands.
        if pending:
            ready_other = sum(
                1 for idx, plist in slices.items()
                if idx not in pending and len(plist) == hosts
                and all(pod_running(p) for p in plist))
            if ready_other >= desired:
                for idx in sorted(pending):
                    if idx not in slices:
                        continue
                    if not self._delete_slice(cluster, slices[idx],
                                              group.groupName):
                        requeue = 1.0
                        continue
                    self.recorder.normal(
                        cluster.to_dict(), C.EVENT_DELETED_SLICE,
                        f"retired preempted slice {group.groupName}/{idx} "
                        "(replacement ready)")
                    del slices[idx]
            else:
                requeue = min(requeue, 1.0) if requeue else 1.0
        return requeue

    # ------------------------------------------------------------------
    # preemption lifecycle (docs/preemption.md)
    # ------------------------------------------------------------------

    def _note_preemptions(self, cluster: TpuCluster, group: WorkerGroupSpec,
                          slices: Dict[int, List[Dict[str, Any]]]) -> set:
        """Indices of live slices under an active preemption notice;
        first sight per slice starts the warned-recovery clock and emits
        ``tpu_preemption_notices_total`` + a PreemptionNotice event."""
        ns, name = cluster.metadata.namespace, cluster.metadata.name
        noticed = set()
        for idx, plist in slices.items():
            deadlines = [p["metadata"].get("annotations", {}).get(
                C.ANNOTATION_PREEMPTION_NOTICE) for p in plist]
            deadlines = [d for d in deadlines if d]
            if not deadlines:
                continue
            noticed.add(idx)
            sname = plist[0]["metadata"]["labels"].get(
                C.LABEL_SLICE_NAME, f"{group.groupName}-{idx}")
            k = (ns, name, group.groupName, sname)
            if k in self._notice_started:
                continue
            self._notice_started[k] = time.time()
            if self.metrics is not None:
                self.metrics.preemption_notice(name, group.groupName)
            self.recorder.warning(
                cluster.to_dict(), C.EVENT_PREEMPTION_NOTICE,
                f"preemption notice on slice {sname} (kill deadline "
                f"{min(deadlines)}): pre-provisioning replacement")
        return noticed

    def _delete_slice(self, cluster: TpuCluster,
                      plist: List[Dict[str, Any]], group_name: str) -> bool:
        """THE slice-teardown seam (analysis rule
        slice-teardown-through-drain-seam): every whole-slice delete
        routes through here, so a slice under an active preemption
        notice is drained — checkpoint requested via the coordinator,
        drain acknowledgment stamped — before any of its pods is
        deleted.  Returns False with NOTHING deleted when the drain
        write loses its rv race (caller requeues; level-triggered
        retry)."""
        if not self._drain_noticed(cluster, plist):
            return False
        for p in plist:
            self._delete_pod(p, group_name)
        return True

    def _drain_noticed(self, cluster: TpuCluster,
                       pods: List[Dict[str, Any]]) -> bool:
        ns = cluster.metadata.namespace
        noticed = [
            p for p in pods
            if p["metadata"].get("annotations", {}).get(
                C.ANNOTATION_PREEMPTION_NOTICE)
            and not p["metadata"].get("annotations", {}).get(
                C.ANNOTATION_DRAINED_AT)]
        if not noticed:
            return True
        for p in noticed:
            # The drain stamp echoes the notice deadline it acknowledged:
            # self-describing in production, and deterministic under the
            # sim clock (a wall-clock stamp would break the replay-hash
            # contract).
            deadline = p["metadata"]["annotations"][
                C.ANNOTATION_PREEMPTION_NOTICE]
            try:
                self.store.patch(
                    "Pod", p["metadata"]["name"], ns,
                    {"metadata": {"annotations": {
                        C.ANNOTATION_DRAINED_AT: deadline}}})
            except NotFound:
                continue
            except Conflict:
                # rv race on the stamp: nothing was deleted yet, so the
                # caller requeues and the whole drain re-runs (the
                # drain-before-delete invariant stays intact).
                return False
        self._request_checkpoint(cluster, noticed)
        sname = noticed[0]["metadata"]["labels"].get(C.LABEL_SLICE_NAME, "")
        self.recorder.normal(
            cluster.to_dict(), C.EVENT_DRAINED_SLICE,
            f"drained slice {sname}: checkpoint requested for "
            f"{len(noticed)} noticed pod(s) before teardown")
        return True

    def _request_checkpoint(self, cluster: TpuCluster,
                            pods: List[Dict[str, Any]]):
        """Checkpoint-drain hook: one request per drained batch, into
        the coordinator (train.checkpoint CheckpointWriter on the far
        side).  Best-effort — a severed coordinator (DCN partition) must
        not wedge teardown; the drained-at stamp is the contract the
        invariant checker reads."""
        if self.client_provider is None:
            return
        sname = pods[0]["metadata"]["labels"].get(C.LABEL_SLICE_NAME, "")
        try:
            client = self.client_provider(cluster.status.to_dict())
            client.request_checkpoint(tag=f"preempt-{sname}",
                                      reason="preemption")
        except Exception:
            pass

    def _claim_warm_slice(self, cluster: TpuCluster, group: WorkerGroupSpec,
                          idx: int, reason: str) -> bool:
        """Warm pre-replacement: adopt a ready warm slice from a
        matching (accelerator, topology) pool in the namespace instead
        of a cold build.  Adoption stamps cluster identity onto the
        claimed pods via label patches (never conflict-injected: the
        claim deliberately has no retry loop).  Returns True when a
        slice was adopted as ``group/idx``."""
        if self.warmpool is None or not features.enabled("WarmSlicePools"):
            return False
        ns, name = cluster.metadata.namespace, cluster.metadata.name
        pools = [o for o in self.store.list(KIND_WARM_POOL, ns)
                 if o.get("spec", {}).get("accelerator") == group.accelerator
                 and o.get("spec", {}).get("topology") == group.topology
                 and not o["metadata"].get("deletionTimestamp")]
        for pool in sorted(pools, key=lambda o: o["metadata"]["name"]):
            names = self.warmpool.claim(pool["metadata"]["name"], ns)
            if not names:
                continue
            for pname in names:
                try:
                    self.store.patch_labels(
                        "Pod", pname, ns,
                        {C.LABEL_CLUSTER: name,
                         C.LABEL_GROUP: group.groupName,
                         C.LABEL_SLICE_INDEX: str(idx)})
                except NotFound:
                    # Vanished mid-adoption: the incomplete-slice sweep
                    # cleans the remainder next pass, cold rebuild.
                    pass
            if self.metrics is not None:
                self.metrics.warmpool_claim(reason)
            self.recorder.normal(
                cluster.to_dict(), C.EVENT_ADOPTED_WARM_SLICE,
                f"adopted warm slice from pool {pool['metadata']['name']} "
                f"as {group.groupName}/{idx} ({reason})")
            return True
        if pools and self.metrics is not None:
            self.metrics.warmpool_claim("miss")
        return False

    def _clear_executed_victims(self, cluster: TpuCluster,
                                raw: Dict[str, Any], group_name: str,
                                executed: set):
        """Mutates the reconcile-start snapshot (``raw`` — the pristine
        spec, NOT the template-resolved in-memory copy) and writes it
        under the snapshot's rv: the victims were chosen from that
        snapshot, so a foreign spec write in the window 409s and the
        whole pass recomputes, instead of the stale victim list landing
        on top of it."""
        changed = False
        for g in raw["spec"].get("workerGroupSpecs", []):
            if g.get("groupName") != group_name:
                continue
            ss = g.get("scaleStrategy") or {}
            remaining = [s for s in ss.get("slicesToDelete", [])
                         if s not in executed]
            if remaining != ss.get("slicesToDelete", []):
                ss["slicesToDelete"] = remaining
                g["scaleStrategy"] = ss
                changed = True
        if changed:
            raw["metadata"]["resourceVersion"] = \
                cluster.metadata.resourceVersion
            out = self.store.update(raw)
            # Thread our own bump so the status write at the end of the
            # pass doesn't self-conflict.
            cluster.metadata.resourceVersion = \
                out["metadata"]["resourceVersion"]
            raw["metadata"]["resourceVersion"] = \
                out["metadata"]["resourceVersion"]

    # ------------------------------------------------------------------
    # status (ref calculateStatus :1874 + consistency.go throttling)
    # ------------------------------------------------------------------

    def _update_status(self, cluster: TpuCluster):
        pods = self._cluster_pods(cluster)
        live = [p for p in pods if not pod_deleting(p)]
        heads = [p for p in live if p["metadata"]["labels"].get(
            C.LABEL_NODE_TYPE) == C.NODE_TYPE_HEAD]
        head_ready = any(pod_running(p) for p in heads)

        status = cluster.status
        prev = status.to_dict()
        status.observedGeneration = cluster.metadata.generation
        status.desiredSlices = status.readySlices = 0
        status.desiredWorkerHosts = status.readyWorkerHosts = 0
        status.desiredTpuChips = 0
        status.groups = []

        from kuberay_tpu.api.tpucluster import WorkerGroupStatus
        for group in cluster.spec.workerGroupSpecs:
            topo = group.slice_topology()
            desired = 0 if (group.suspend or cluster.spec.suspend) else group.replicas
            slices = self._group_pods_by_slice(live, group)
            ready_idx = {idx for idx, plist in slices.items()
                         if len(plist) == topo.num_hosts
                         and all(pod_running(p) for p in plist)}
            self._observe_slice_ready(cluster, group, slices, ready_idx,
                                      topo.num_hosts)
            ready_slices = len(ready_idx)
            self._observe_warned_recovery(cluster, group, slices,
                                          ready_slices, desired)
            gs = WorkerGroupStatus(
                groupName=group.groupName,
                desiredSlices=desired,
                readySlices=ready_slices,
                desiredHosts=desired * topo.num_hosts,
                readyHosts=sum(1 for plist in slices.values()
                               for p in plist if pod_running(p)),
                desiredTpuChips=desired * topo.num_chips,
            )
            status.groups.append(gs)
            status.desiredSlices += gs.desiredSlices
            status.readySlices += gs.readySlices
            status.desiredWorkerHosts += gs.desiredHosts
            status.readyWorkerHosts += gs.readyHosts
            status.desiredTpuChips += gs.desiredTpuChips

        status.headServiceName = head_service_name(cluster.metadata.name)
        status.headPodName = heads[0]["metadata"]["name"] if heads else ""
        status.headPodIP = (heads[0].get("status", {}).get("podIP", "")
                            if heads else "")
        from kuberay_tpu.builders.pod import coordinator_address
        status.coordinatorAddress = coordinator_address(cluster)

        set_condition(status.conditions, Condition(
            type=ClusterConditionType.HEAD_POD_READY,
            status="True" if head_ready else "False",
            reason="HeadPodRunning" if head_ready else "HeadPodNotRunning",
            observedGeneration=cluster.metadata.generation))

        all_ready = (head_ready and status.readySlices >= status.desiredSlices)
        if cluster.spec.suspend:
            new_state = ClusterState.SUSPENDED
            set_condition(status.conditions, Condition(
                type=ClusterConditionType.SUSPENDED,
                status="True" if not live else "False",
                reason="Suspended" if not live else "Suspending",
                observedGeneration=cluster.metadata.generation))
        elif all_ready:
            new_state = ClusterState.READY
        else:
            new_state = status.state or ""
        if all_ready:
            # Provisioned latches once (ref RayClusterProvisioned :1930-1960).
            set_condition(status.conditions, Condition(
                type=ClusterConditionType.PROVISIONED, status="True",
                reason="AllSlicesReady",
                observedGeneration=cluster.metadata.generation))
        if new_state and new_state != status.state:
            self.transitions.record(
                self.KIND, cluster.metadata.namespace,
                cluster.metadata.name, new_state,
                old_state=status.state or "")
            status.stateTransitionTimes[new_state] = time.time()
            if self.metrics is not None and new_state == ClusterState.READY:
                created = cluster.metadata.creationTimestamp or time.time()
                self.metrics.observe_provisioned(
                    cluster.metadata.name, time.time() - created)
        status.state = new_state

        # Throttle: skip update when nothing but timestamps changed
        # (ref consistency.go:16).
        new = status.to_dict()
        if self._status_equal(prev, new):
            return
        # The write carries the reconcile-start resourceVersion (plus
        # bumps threaded from our own mid-reconcile writes — finalizer
        # add, victim clearing).  NO pre-write re-read: this status was
        # computed from the snapshot, so a FOREIGN write anywhere in the
        # pass — the leader-failover overlap — must 409 and requeue
        # rather than silently clobber the new leader's status
        # (optimistic concurrency via resourceVersion, SURVEY §5.2).
        obj = cluster.to_dict()
        obj["status"] = new
        self._write_status(obj)

    def _observe_slice_ready(self, cluster: TpuCluster,
                             group: WorkerGroupSpec,
                             slices: Dict[int, List[Dict[str, Any]]],
                             ready_idx: set, hosts: int):
        """Emit the north-star decomposition anchor once per slice
        provisioning: ``tpu_slice_ready_duration_seconds`` (earliest pod
        creation -> all hosts Running) plus a ``slice-ready`` span on the
        cluster's reconcile chain, whose child queue-wait / reconcile /
        pod-start spans account for where the time went.  A slice that
        degrades drops out of the observed set, so its rebuild is a new
        observation."""
        ns, name = cluster.metadata.namespace, cluster.metadata.name
        now = time.time()
        for idx in ready_idx:
            k = (ns, name, group.groupName, idx)
            if k in self._slices_observed_ready:
                continue
            self._slices_observed_ready.add(k)
            started = min((p["metadata"].get("creationTimestamp") or now)
                          for p in slices[idx])
            if self.metrics is not None:
                self.metrics.observe_slice_ready(name, group.groupName,
                                                 now - started)
            self.tracer.record_for_key(
                (self.KIND, ns, name), "slice-ready", started, now,
                group=group.groupName, slice=idx, hosts=hosts)
        stale = {k for k in self._slices_observed_ready
                 if k[0] == ns and k[1] == name
                 and k[2] == group.groupName and k[3] not in ready_idx}
        self._slices_observed_ready -= stale

    def _observe_warned_recovery(self, cluster: TpuCluster,
                                 group: WorkerGroupSpec,
                                 slices: Dict[int, List[Dict[str, Any]]],
                                 ready_slices: int, desired: int):
        """Close the warned-recovery clock: once a noticed slice is gone
        AND the group is back at full readiness, observe
        ``tpu_preemption_warned_recovery_seconds`` (notice first sight ->
        capacity restored) exactly once per notice."""
        ns, name = cluster.metadata.namespace, cluster.metadata.name
        snames = {plist[0]["metadata"]["labels"].get(C.LABEL_SLICE_NAME)
                  for plist in slices.values() if plist}
        for k in list(self._notice_started):
            if k[0] != ns or k[1] != name or k[2] != group.groupName:
                continue
            if k[3] in snames or ready_slices < desired:
                continue
            started = self._notice_started.pop(k)
            if self.metrics is not None:
                self.metrics.observe_warned_recovery(
                    name, group.groupName, time.time() - started)

    def _set_status(self, cluster: TpuCluster, state: str, reason: str = ""):
        obj = cluster.to_dict()
        st = obj.setdefault("status", {})
        if st.get("state") == state and st.get("reason") == reason:
            return
        self.transitions.record(self.KIND, cluster.metadata.namespace,
                                cluster.metadata.name, state,
                                old_state=st.get("state") or "")
        st["state"] = state
        st["reason"] = reason
        # Snapshot rv, same contract as _update_status.
        self._write_status(obj)

    def _write_status(self, obj: Dict[str, Any]):
        if not obj["metadata"].get("resourceVersion"):
            # Loud, like carry_rv: an rv-less write silently reverts to
            # last-writer-wins, the bug class this contract prevents.
            raise StoreError(
                f"{self.KIND} {obj['metadata'].get('name')}: snapshot has "
                "no resourceVersion; refusing an unguarded status write")
        with self.tracer.span("store-write", kind=self.KIND,
                              obj=obj["metadata"].get("name", "")):
            try:
                self.store.update_status(obj)
            except NotFound:
                # Deleted mid-reconcile: the deletion path owns cleanup.
                return

    @staticmethod
    def _status_equal(a: Dict[str, Any], b: Dict[str, Any]) -> bool:
        def strip(d):
            d = dict(d)
            d.pop("stateTransitionTimes", None)
            conds = []
            for c in d.get("conditions", []):
                c = dict(c)
                c.pop("lastTransitionTime", None)
                conds.append(c)
            if conds:
                d["conditions"] = conds
            return d
        return strip(a) == strip(b)
