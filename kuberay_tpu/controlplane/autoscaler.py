"""Slice-unit autoscaler: the Replicas/slicesToDelete protocol.

The reference's contract (raycluster_types.go:421-424): the autoscaler is
the sole scale decision-maker when enabled — it patches
``WorkerGroupSpec.Replicas`` and names victims in
``ScaleStrategy.WorkersToDelete``; the operator only executes.  Here the
contract is slice-granular from the start (victims are slice names), and
the demand signal is job/queue state rather than Ray resource bookkeeping
(SURVEY.md §7.6): idle-slice detection is driven by what the scheduler
knows, not by scraping the runtime.

Pure decision core (``decide``) + a loop (``SliceAutoscaler``) that reads
demand from queued TpuJobs and slice idleness from a pluggable source —
runs in-process with the operator or as the head-pod sidecar the builders
inject (builders/pod.py BuildAutoscalerContainer analogue).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from kuberay_tpu.api.tpucluster import TpuCluster
from kuberay_tpu.controlplane.store import (
    Conflict,
    Invalid,
    NotFound,
    ObjectStore,
)
from kuberay_tpu.utils import constants as C


@dataclasses.dataclass
class GroupDecision:
    group: str
    replicas: int                      # desired slice count (clamped)
    slices_to_delete: List[str]        # named victims (downscale only)
    reason: str = ""


@dataclasses.dataclass
class SliceInfo:
    name: str                          # tpu.dev/slice-name label value
    group: str
    ready: bool
    idle_seconds: float = 0.0


def decide(cluster: TpuCluster,
           demand: Dict[str, int],
           slices: List[SliceInfo],
           idle_timeout: float = 60.0,
           upscaling_mode: str = "Default") -> List[GroupDecision]:
    """Pure scaling decision.

    demand: group -> slices wanted by admitted/queued work.
    slices: observed slices with idleness.
    Upscaling modes (ref AutoscalerOptions): Default = one slice per pass,
    Aggressive = jump straight to demand, Conservative = never upscale.
    """
    out: List[GroupDecision] = []
    by_group: Dict[str, List[SliceInfo]] = {}
    for s in slices:
        by_group.setdefault(s.group, []).append(s)

    for g in cluster.spec.workerGroupSpecs:
        cur = g.replicas
        want = demand.get(g.groupName, 0)
        lo, hi = g.minReplicas, g.maxReplicas
        target = cur
        victims: List[str] = []
        reason = ""
        # Per-group override (ref autoscaler-v2 idleTimeoutSeconds):
        # 0 inherits the cluster-level timeout.
        group_idle = g.idleTimeoutSeconds or idle_timeout

        if want > cur and upscaling_mode != "Conservative":
            step = (want - cur) if upscaling_mode == "Aggressive" else 1
            target = min(hi, cur + step)
            reason = f"demand {want} > {cur}"
        else:
            # Downscale: idle slices beyond demand, newest-idle last.
            idle = sorted(
                (s for s in by_group.get(g.groupName, [])
                 if s.ready and s.idle_seconds >= group_idle),
                key=lambda s: -s.idle_seconds)
            removable = min(len(idle), cur - max(lo, want))
            if removable > 0:
                victims = [s.name for s in idle[:removable]]
                target = cur - removable
                reason = f"{removable} slices idle >= {group_idle}s"

        target = max(lo, min(hi, target))
        if target != cur or victims:
            out.append(GroupDecision(g.groupName, target, victims, reason))
    return out


class DecisionAudit:
    """Bounded last-N ring of autoscaler decisions: the input signals
    (demand, slice idleness, current replicas) next to the verdict
    (target replicas, named victims, reason) — so "why did it scale?"
    is answerable after the fact without replaying the loop.  Served at
    ``/debug/autoscaler``; each record also increments
    ``tpu_autoscaler_decisions_total{kind,direction}``."""

    def __init__(self, capacity: int = 256, metrics=None, clock=None):
        self._ring: "deque[Dict[str, Any]]" = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self.metrics = metrics
        self._now = clock.now if clock is not None else time.time
        # Lifetime decision count (monotonic; the ring holds the last N).
        self.total = 0

    def record(self, namespace: str, cluster: str, decision: GroupDecision,
               *, current: int, demand: Dict[str, int],
               slices: List[SliceInfo], applied: bool,
               slo: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        if decision.replicas > current:
            direction = "up"
        elif decision.replicas < current or decision.slices_to_delete:
            direction = "down"
        else:
            direction = "none"
        entry = {
            "ts": self._now(),
            "namespace": namespace, "cluster": cluster,
            "group": decision.group, "direction": direction,
            "replicas_before": current, "replicas_after": decision.replicas,
            "slices_to_delete": list(decision.slices_to_delete),
            "reason": decision.reason,
            "applied": applied,
            "signals": {
                "demand": demand.get(decision.group, 0),
                "slices": [{"name": s.name, "ready": s.ready,
                            "idle_seconds": s.idle_seconds}
                           for s in slices if s.group == decision.group],
            },
        }
        if slo is not None:
            entry["signals"]["slo"] = dict(slo)
        with self._lock:
            self._ring.append(entry)
            self.total += 1
        if self.metrics is not None:
            self.metrics.autoscaler_decision(C.KIND_CLUSTER, direction)
        return entry

    def record_upgrade(self, namespace: str, service: str, action: str,
                       *, green_weight: int, reason: str = "",
                       alert: Optional[Dict[str, Any]] = None,
                       profile_diff: Optional[Dict[str, Any]] = None
                       ) -> Dict[str, Any]:
        """An upgrade-ramp verdict (promote/rollback/abort) in the same
        audit ring as scale decisions — with the baseline-vs-candidate
        critical-path trace diff attached when a profiler was wired, so
        "why did it roll back" names the regressing span kind, not just
        the alert that fired."""
        entry: Dict[str, Any] = {
            "ts": self._now(), "kind": "upgrade",
            "namespace": namespace, "service": service,
            "action": action, "green_weight": green_weight,
            "reason": reason,
        }
        if alert:
            entry["alert"] = dict(alert)
        if profile_diff is not None:
            entry["profile_diff"] = profile_diff
        with self._lock:
            self._ring.append(entry)
            self.total += 1
        return entry

    def to_list(self) -> List[Dict[str, Any]]:
        """Newest-first snapshot of the ring."""
        with self._lock:
            return list(reversed(self._ring))

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)


def apply_decisions(store: ObjectStore, cluster_name: str, namespace: str,
                    decisions: List[GroupDecision]) -> bool:
    """Scale via a single strategic-merge PATCH (workerGroupSpecs merge
    by groupName): one round trip, no read-modify-write conflict loop,
    and concurrent spec edits to OTHER fields are never clobbered — the
    reference autoscaler likewise patches Replicas/WorkersToDelete
    (raycluster_types.go:421-424) rather than replacing the spec."""
    if not decisions:
        return False
    obj = store.try_get(C.KIND_CLUSTER, cluster_name, namespace)
    if obj is None:
        return False
    known = {g.get("groupName") for g in
             obj["spec"].get("workerGroupSpecs", [])}
    groups = []
    for d in decisions:
        if d.group not in known:
            continue       # a merge-keyed patch would APPEND unknown groups
        groups.append({"groupName": d.group, "replicas": d.replicas,
                       "scaleStrategy": {
                           "slicesToDelete": list(d.slices_to_delete)}})
    if not groups:
        return False
    try:
        store.patch(
            C.KIND_CLUSTER, cluster_name, namespace,
            # resourceVersion precondition: the known-group check above
            # is a read — without CAS, a group deleted between read and
            # patch would be resurrected as a stub by the merge-keyed
            # append.  A conflict just means the next pass re-decides.
            {"metadata": {"resourceVersion":
                          obj["metadata"]["resourceVersion"]},
             "spec": {"workerGroupSpecs": groups}},
            patch_type="strategic", field_manager="tpu-autoscaler")
        return True
    except (Conflict, NotFound, Invalid):
        return False


class SliceAutoscaler:
    """Demand from queued TpuJobs + idleness from a pluggable tracker.

    A slice is "idle" when no running TpuJob claims its group.  The
    idleness clock starts when the claim disappears.
    """

    def __init__(self, store: ObjectStore, idle_timeout: float = 60.0,
                 audit: Optional[DecisionAudit] = None,
                 slo=None, clock=None):
        self.store = store
        self.idle_timeout = idle_timeout
        # Decision audit ring (``/debug/autoscaler``); None = unaudited.
        self.audit = audit
        # SLO signal path (controlplane/slo.ServeSloSignal): serve TTFT
        # p99 / queue-depth evaluated into a demand FLOOR for the
        # signal's policy group — merged max() with job demand, so a
        # breaching serve fleet scales up even with zero queued jobs and
        # a held one can't be idle-reaped mid-recovery.  Accepts one
        # signal or a list (disaggregated fleets run one per tier, each
        # bound to its own worker group); floors merge independently.
        self.slo = slo
        # Injectable clock (object with .now()) so idle bookkeeping and
        # SLO hysteresis run under the sim VirtualClock in tests.
        self._now = clock.now if clock is not None else time.time
        # (namespace, cluster, slice-name) -> idle-since timestamp
        self._idle_since: Dict[tuple, float] = {}

    def _demand_for(self, cluster_obj: dict) -> Dict[str, int]:
        """Slices wanted per group = max over jobs bound to this cluster of
        the group's spec replicas (jobs carry the desired scale in their
        clusterSpec) — queued-work-driven, not utilization-driven."""
        name = cluster_obj["metadata"]["name"]
        ns = cluster_obj["metadata"]["namespace"]
        demand: Dict[str, int] = {}
        for job in self.store.list(C.KIND_JOB, ns):
            st = job.get("status", {})
            if st.get("clusterName") != name:
                continue
            if st.get("jobDeploymentStatus") not in (
                    "Initializing", "Waiting", "Running"):
                continue
            spec_groups = (job.get("spec", {}).get("clusterSpec") or {}
                           ).get("workerGroupSpecs", [])
            for g in spec_groups:
                gname = g.get("groupName", "")
                demand[gname] = max(demand.get(gname, 0), g.get("replicas", 0))
        return demand

    def observe_slices(self, cluster_obj: dict,
                       demand: Dict[str, int]) -> List[SliceInfo]:
        name = cluster_obj["metadata"]["name"]
        ns = cluster_obj["metadata"]["namespace"]
        pods = self.store.list("Pod", ns, labels={C.LABEL_CLUSTER: name})
        by_slice: Dict[str, List[dict]] = {}
        for p in pods:
            sname = p["metadata"]["labels"].get(C.LABEL_SLICE_NAME)
            if sname:
                by_slice.setdefault(sname, []).append(p)
        now = self._now()
        # Idle bookkeeping is keyed per (ns, cluster, slice) so one
        # autoscaler instance can manage many clusters; prune only THIS
        # cluster's vanished slices — a stale entry would leak and make a
        # recreated same-name slice appear instantly idle.
        live_keys = {(ns, name, s) for s in by_slice}
        for key in [k for k in self._idle_since
                    if k[0] == ns and k[1] == name and k not in live_keys]:
            del self._idle_since[key]
        out = []
        for sname, plist in by_slice.items():
            key = (ns, name, sname)
            group = plist[0]["metadata"]["labels"].get(C.LABEL_GROUP, "")
            ready = all(p.get("status", {}).get("phase") == "Running"
                        for p in plist)
            claimed = demand.get(group, 0) > 0
            if claimed:
                self._idle_since.pop(key, None)
                idle = 0.0
            else:
                self._idle_since.setdefault(key, now)
                idle = now - self._idle_since[key]
            out.append(SliceInfo(sname, group, ready, idle))
        return out

    def forget_cluster(self, namespace: str, cluster_name: str):
        """Drop idle bookkeeping for a deleted cluster so a recreated
        same-name cluster doesn't inherit stale idle clocks."""
        for key in [k for k in self._idle_since
                    if k[0] == namespace and k[1] == cluster_name]:
            del self._idle_since[key]

    def prune_clusters(self, live: set):
        """Keep only bookkeeping for (ns, name) pairs in ``live``."""
        for key in [k for k in self._idle_since if (k[0], k[1]) not in live]:
            del self._idle_since[key]

    def reconcile(self, cluster_name: str, namespace: str = "default") -> bool:
        obj = self.store.try_get(C.KIND_CLUSTER, cluster_name, namespace)
        if obj is None:
            self.forget_cluster(namespace, cluster_name)
            return False
        if not obj.get("spec", {}).get("enableInTreeAutoscaling"):
            return False
        cluster = TpuCluster.from_dict(obj)
        opts = cluster.spec.autoscalerOptions
        idle_timeout = opts.idleTimeoutSeconds if opts else self.idle_timeout
        mode = opts.upscalingMode if opts else "Default"
        demand = self._demand_for(obj)
        slo_infos: Dict[str, dict] = {}
        signals = self.slo if isinstance(self.slo, (list, tuple)) \
            else ([self.slo] if self.slo is not None else [])
        for sig in signals:
            group = next((g for g in cluster.spec.workerGroupSpecs
                          if g.groupName == sig.policy.group), None)
            if group is not None:
                floor, info = sig.demand_floor(group.replicas)
                gname = group.groupName
                demand[gname] = max(demand.get(gname, 0), floor)
                slo_infos[gname] = info
        slices = self.observe_slices(obj, demand)
        decisions = decide(cluster, demand, slices, idle_timeout, mode)
        # kuberay-lint: disable-next-line=reconcile-exception-escape -- OSError/RuntimeError/PatchError here are store-internal infrastructure faults (native journal build, managed-fields corruption); the Manager's backoff IS the intended handling, and Conflict is already sanctioned
        applied = apply_decisions(self.store, cluster_name, namespace,
                                  decisions)
        if self.audit is not None and decisions:
            current = {g.groupName: g.replicas
                       for g in cluster.spec.workerGroupSpecs}
            for d in decisions:
                # Each decision carries ITS group's signal record — a
                # prefill-tier scale-up must not be attributed to the
                # decode tier's (quiet) signal in /debug/autoscaler.
                self.audit.record(namespace, cluster_name, d,
                                  current=current.get(d.group, 0),
                                  demand=demand, slices=slices,
                                  applied=applied,
                                  slo=slo_infos.get(d.group))
        return applied
