"""Admission webhooks (ref pkg/webhooks/v1: raycluster_webhook.go:20-80 +
rayservice_webhook.go — optional validating webhooks sharing
utils/validation).

The handler speaks the K8s AdmissionReview v1 protocol so the same module
serves a real API server's ValidatingWebhookConfiguration; embedded mode
(our apiserver) reuses ``validate_admission`` directly — one validation
surface, two front doors, exactly the reference's sharing arrangement.
"""

from __future__ import annotations

import threading
from http.server import ThreadingHTTPServer
from typing import Any, Dict, List

from kuberay_tpu.utils import constants as C
from kuberay_tpu.utils.httpjson import JsonHandler
from kuberay_tpu.utils.validation import (kind_validators,
                                          surface_create_only,
                                          waive_create_only)

_VALIDATORS = kind_validators()


def validate_admission(obj: Dict[str, Any],
                       old_obj: Dict[str, Any] = None) -> List[str]:
    """Validation + update-immutability rules (ref webhook Update checks:
    worker group names must not be renamed/removed in place)."""
    kind = obj.get("kind", "")
    validator = _VALIDATORS.get(kind)
    errs = validator(obj) if validator else []
    if old_obj is not None:
        # Create-only rules (currently: DNS-1035 letter-start) are
        # waived on update so objects that predate a tightened rule do
        # not become unmodifiable — every PUT/PATCH re-runs admission.
        errs = waive_create_only(errs)
    else:
        errs = surface_create_only(errs)
    if old_obj is not None and kind == C.KIND_CLUSTER:
        old_groups = [g.get("groupName") for g in
                      old_obj.get("spec", {}).get("workerGroupSpecs", [])]
        new_groups = {g.get("groupName") for g in
                      obj.get("spec", {}).get("workerGroupSpecs", [])}
        for g in old_groups:
            if g not in new_groups:
                errs.append(
                    f"worker group {g!r} cannot be removed or renamed "
                    "(delete and recreate the cluster instead)")
    return errs


def review_response(review: Dict[str, Any]) -> Dict[str, Any]:
    """AdmissionReview request -> AdmissionReview response."""
    req = review.get("request", {})
    obj = req.get("object") or {}
    old = req.get("oldObject")
    errs = validate_admission(obj, old)
    resp = {
        "uid": req.get("uid", ""),
        "allowed": not errs,
    }
    if errs:
        resp["status"] = {"code": 422, "message": "; ".join(errs)}
    return {"apiVersion": "admission.k8s.io/v1", "kind": "AdmissionReview",
            "response": resp}


class WebhookServer:
    """Endpoint for ValidatingWebhookConfiguration targets
    (``POST /validate``).

    Kubernetes requires webhook backends to serve HTTPS — pass
    ``certfile``/``keyfile`` (the serving cert whose CA goes in the
    configuration's ``caBundle``) for real-cluster use; plain HTTP is for
    embedded/tests only.
    """

    def __init__(self, certfile: str = "", keyfile: str = ""):
        self.certfile = certfile
        self.keyfile = keyfile

    def make_server(self, host="127.0.0.1", port=0) -> ThreadingHTTPServer:
        class Handler(JsonHandler):
            def do_POST(self):
                if self.path.rstrip("/") != "/validate":
                    return self._send(404, {"message": "unknown path"})
                try:
                    review = self._body()
                except Exception as e:
                    return self._send(400, {"message": f"bad body: {e}"})
                return self._send(200, review_response(review))

        srv = ThreadingHTTPServer((host, port), Handler)
        if self.certfile:
            import ssl
            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(self.certfile, self.keyfile or None)
            srv.socket = ctx.wrap_socket(srv.socket, server_side=True)
        return srv

    def serve_background(self, host="127.0.0.1", port=0):
        srv = self.make_server(host, port)
        threading.Thread(target=srv.serve_forever, daemon=True,
                         name="webhook-server").start()
        scheme = "https" if self.certfile else "http"
        return srv, f"{scheme}://{srv.server_address[0]}:{srv.server_address[1]}"
