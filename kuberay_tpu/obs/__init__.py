"""Observability: causal reconcile tracing + per-CR flight recorder.

The third leg of the tooling tripod (docs/observability.md):
``kuberay_tpu.analysis`` proves invariants statically, ``kuberay_tpu.sim``
exercises them under seeded chaos, and this package answers "where did
the time go / what sequence of events produced this state" — in
production and in sim-violation forensics — from one artifact.

- :mod:`kuberay_tpu.obs.trace`: Dapper-style parent-linked spans with
  explicit trace-context propagation through the manager's
  watch -> queue -> reconcile pipeline (queue-wait, reconcile,
  store-write, pod-start, slice-ready), a bounded in-memory
  :class:`SpanStore` and JSON export.  ``NOOP_TRACER`` makes every
  annotation free when tracing is off.
- :mod:`kuberay_tpu.obs.flight`: fixed-size per-(kind, ns, name) ring
  buffer of watch deliveries, state transitions, recorded Events,
  conflicts and requeues, queryable as a timeline
  (``/debug/flight/<kind>/<ns>/<name>`` on the API server).
- :mod:`kuberay_tpu.obs.profile`: critical-path analytics over the
  recorded spans — per-span-kind exclusive self-time profiles
  (``/debug/profile``, ``tpu-profile/v1`` artifacts) and the
  noise-gated baseline-vs-candidate trace diff the upgrade ramp and
  the benches use to name the guilty span kind in a regression.
- :mod:`kuberay_tpu.obs.alerts`: multi-window multi-burn-rate SLO
  alerting over ``MetricsRegistry`` snapshot deltas (TTFT p99,
  availability, goodput-ratio floor), firing into a bounded ring at
  ``/debug/alerts``.
- :mod:`kuberay_tpu.obs.goodput`: the goodput/badput ledger — every
  second of a TpuJob/TpuCluster's lifetime attributed to an exclusive,
  exhaustive phase set (queued / provisioning / bootstrap / productive
  / interrupted / recovery / teardown), served live at
  ``/debug/goodput`` and archived post-mortem by the history server.
- :mod:`kuberay_tpu.obs.steps`: the training-step straggler microscope
  — per-(job, host) heartbeat windows from the coordinator, cross-host
  skew, K-consecutive-step straggler verdicts, MFU attribution; splits
  the ledger's PRODUCTIVE into productive vs ``stalled-on-straggler``
  and serves ``/debug/steps[/<job>]``.
- :mod:`kuberay_tpu.obs.incident`: the incident forensics engine —
  any trigger (alert firing, sim invariant violation, upgrade
  rollback, preemption notice, straggler verdict, quota reclaim)
  becomes one windowed ``tpu-incident/v1`` bundle spanning every
  mounted evidence surface, with a deterministic first-deviation /
  causal-linkage root-cause ranking (``/debug/incidents``).
"""

from kuberay_tpu.obs.alerts import AlertEngine, SloSpec, default_slos
from kuberay_tpu.obs.flight import FlightRecorder
from kuberay_tpu.obs.incident import INCIDENT_SCHEMA, IncidentEngine
from kuberay_tpu.obs.goodput import (
    NOOP_TRANSITIONS,
    PHASES,
    GoodputLedger,
    NoopTransitionRecorder,
    TransitionRecorder,
)
from kuberay_tpu.obs.profile import (
    PROFILE_SCHEMA,
    RequestProfiler,
    diff_profiles,
    profile_spans,
    trace_records,
    worst_regression,
)
from kuberay_tpu.obs.steps import NOOP_STEPS, NoopStepTracker, StepTracker
from kuberay_tpu.obs.trace import (
    NOOP_TRACER,
    NoopTracer,
    Span,
    SpanStore,
    TraceContext,
    Tracer,
    span_tree,
)

__all__ = [
    "AlertEngine",
    "FlightRecorder",
    "GoodputLedger",
    "INCIDENT_SCHEMA",
    "IncidentEngine",
    "NOOP_STEPS",
    "NOOP_TRACER",
    "NOOP_TRANSITIONS",
    "NoopStepTracker",
    "NoopTracer",
    "NoopTransitionRecorder",
    "PHASES",
    "PROFILE_SCHEMA",
    "RequestProfiler",
    "SloSpec",
    "StepTracker",
    "Span",
    "SpanStore",
    "TraceContext",
    "Tracer",
    "TransitionRecorder",
    "default_slos",
    "diff_profiles",
    "profile_spans",
    "span_tree",
    "trace_records",
    "worst_regression",
]
