"""Incident forensics engine: cross-signal capture + root-cause ranking.

PRs 3/4/8/11/18 built six first-class evidence surfaces — traces,
flight rings, the goodput ledger, burn-rate alerts, step telemetry,
critical-path profiles — but they are islands: an alert links to one
exemplar trace and everything else (the autoscaler DecisionAudit, the
quota audit, upgrade verdicts, straggler verdicts, the profile diff)
must be correlated by hand.  This module makes the correlation itself a
subsystem: any trigger — an AlertEngine firing, a sim invariant
violation, an upgrade rollback, a preemption notice, a straggler
verdict, a quota reclaim — becomes one self-contained **incident
bundle** (schema ``tpu-incident/v1``): a windowed snapshot of every
mounted evidence surface scoped to the affected entity, plus a
deterministic root-cause ranking.

The ranker keeps a **first-deviation table**: for every signal the
engine can see (per-backend gateway errors/sheds, upgrade audit
verdicts, autoscale decisions, straggler verdicts, quota reclaim
decisions, preemption-notice feeds, active SLO breaches) it remembers
the first time that signal deviated.  When an incident opens, every
deviation inside the lookback window becomes a suspect, scored by
causal linkage to the trigger (shared entity, backend label, host,
trace ids) and ordered by ``(-linkage, first_ts, kind, key)`` — ties
broken lexicographically, so the same evidence always yields the same
byte-identical verdict prose.

Everything is observational: the engine reads the injectable clock and
the mounted surfaces (registry snapshots, audit rings, logs), never the
store or the rng — evaluating under simulation leaves the replay hash
byte-identical (the same contract the tracer, the goodput ledger and
the alert engine obey).  Incident ids are counters (``inc000001``), no
wall clock or uuid anywhere, so a (scenario, seed) pair exports the
same bundle bytes on every run.
"""

from __future__ import annotations

import copy
import json
import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Tuple

#: Bundle document schema tag.
INCIDENT_SCHEMA = "tpu-incident/v1"

#: Trigger kinds the engine opens bundles for (docs/observability.md).
TRIGGERS = ("alert", "rollback", "straggler", "preemption",
            "quota-reclaim", "violation")

#: Suspects kept per bundle (ranked; the tail is noise by definition).
MAX_SUSPECTS = 8

#: Upgrade audit actions that open an incident (the ramp gave up).
_ROLLBACK_ACTIONS = ("abort", "rollback")

#: Quota decision reasons that open an incident (capacity was clawed
#: back from a running workload).
_RECLAIM_REASONS = ("reclaim-evict", "reclaim-noticed")


def _series_key(series: Dict[str, Any]) -> str:
    return ",".join(f"{k}={v}" for k, v in sorted(series.items()))


def _entity_from_labels(labels: Dict[str, Any]
                        ) -> Optional[Tuple[str, str, str]]:
    if {"kind", "namespace", "name"} <= set(labels):
        return (str(labels["kind"]), str(labels["namespace"]),
                str(labels["name"]))
    return None


class IncidentEngine:
    """Turns triggers into ranked, windowed incident bundles.

    ``evaluate()`` is the single entry point — the operator calls it
    from its background tick right after ``AlertEngine.evaluate()``
    (passing the freshly fired alerts), the sim harness from its settle
    loop.  ``observe_violations()`` feeds invariant violations at check
    time.  All constructor surfaces are optional: an engine with only a
    clock still produces bundles, just with thinner evidence.
    """

    def __init__(self, clock=None, *,
                 registry=None, tracer=None, flight=None, goodput=None,
                 alerts=None, steps=None, audit=None, quota=None,
                 lookback_s: float = 120.0, capacity: int = 64):
        self._now: Callable[[], float] = (clock.now if clock is not None
                                          else time.time)
        self.registry = registry
        self.tracer = tracer
        self.flight = flight
        self.goodput = goodput
        self.alerts = alerts
        self.steps = steps
        self.audit = audit
        self.quota = quota
        self.lookback_s = lookback_s
        self.capacity = capacity
        self._lock = threading.Lock()
        self._seq = 0
        # id -> bundle, insertion-ordered; oldest evicted past capacity.
        self._bundles: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()
        # (kind, key) -> deviation entry; first_ts never moves once set.
        self._deviations: Dict[Tuple[str, str], Dict[str, Any]] = {}
        # Trigger identities already bundled (dedupe across ticks).
        self._seen_triggers: set = set()
        # External deviation feeds: callables returning dict rows
        # ({kind, key, ts, summary[, entity][, backend][, host]
        #   [, trigger]}) — the sim harness mounts its preemption
        # notice log this way.
        self._feeds: List[Callable[[], List[Dict[str, Any]]]] = []
        self.evaluations = 0
        if self.registry is not None:
            self.registry.describe(
                "tpu_incidents_total",
                "Incident bundles opened by the forensics engine, per "
                "trigger kind")
            self.registry.describe(
                "tpu_incident_bundle_bytes",
                "Serialized size in bytes of the most recently captured "
                "incident bundle")

    def add_feed(self, feed: Callable[[], List[Dict[str, Any]]]) -> None:
        """Mount an external deviation feed (evaluated every tick)."""
        self._feeds.append(feed)

    # -- first-deviation table ---------------------------------------------

    def _note_deviation(self, kind: str, key: str, ts: float,
                        summary: str,
                        entity: Optional[Tuple[str, str, str]] = None,
                        backend: str = "", host: str = "",
                        trace_ids: Optional[List[str]] = None) -> None:
        dkey = (kind, key)
        entry = self._deviations.get(dkey)
        if entry is None:
            self._deviations[dkey] = {
                "kind": kind, "key": key, "first_ts": ts,
                "summary": summary, "entity": entity,
                "backend": backend, "host": host,
                "trace_ids": set(trace_ids or ()),
            }
        else:
            # First-deviation time is sticky; linkage evidence grows.
            entry["trace_ids"].update(trace_ids or ())

    def _scan_registry(self, now: float) -> None:
        """Per-backend gateway error/shed series: the first evaluation
        tick that sees a series non-zero is its deviation time — under
        the sim's fixed settle cadence that instant is a pure function
        of the fault plan."""
        if self.registry is None:
            return
        for labels, value in self.registry.family_snapshot(
                "tpu_gateway_backend_errors_total"):
            if value <= 0:
                continue
            backend = str(labels.get("backend", ""))
            self._note_deviation(
                "backend-errors", backend or _series_key(labels), now,
                f"gateway errors on backend {backend or '?'}",
                backend=backend)
        for labels, value in self.registry.family_snapshot(
                "tpu_gateway_shed_total"):
            if value <= 0:
                continue
            self._note_deviation(
                "gateway-shed", _series_key(labels) or "all", now,
                "gateway load shedding")

    def _scan_audit(self) -> List[Dict[str, Any]]:
        """Upgrade verdicts + applied scale decisions from the shared
        DecisionAudit ring; returns the upgrade entries (oldest first)
        for trigger detection."""
        if self.audit is None:
            return []
        upgrades: List[Dict[str, Any]] = []
        for entry in reversed(self.audit.to_list()):   # oldest first
            if entry.get("kind") == "upgrade":
                ns = entry.get("namespace", "default")
                svc = entry.get("service", "")
                # Entity linkage only, deliberately NO backend label:
                # an upgrade verdict is a consequence of backend health,
                # not a cause of it — when a rollback trigger carries
                # the gating alert's backend, the per-backend error
                # deviation (earlier first_ts, +2 backend) must outrank
                # the ramp's own audit trail (+2 entity).
                self._note_deviation(
                    "upgrade", f"{ns}/{svc}:{entry.get('action', '')}",
                    float(entry.get("ts", 0.0)),
                    f"upgrade {entry.get('action', '')} on {svc} at "
                    f"green weight {entry.get('green_weight', 0)}%",
                    entity=("TpuService", ns, svc))
                upgrades.append(entry)
            elif entry.get("direction") in ("up", "down") \
                    and entry.get("applied"):
                ns = entry.get("namespace", "default")
                cname = entry.get("cluster", "")
                self._note_deviation(
                    "autoscale",
                    f"{ns}/{cname}:{entry.get('group', '')}"
                    f":{entry.get('direction', '')}",
                    float(entry.get("ts", 0.0)),
                    f"autoscale {entry.get('direction', '')} on {cname} "
                    f"group {entry.get('group', '')}",
                    entity=("TpuCluster", ns, cname))
        return upgrades

    def _scan_steps(self) -> List[Dict[str, Any]]:
        if self.steps is None:
            return []
        verdicts = self.steps.stragglers()
        for v in verdicts:
            job = str(v.get("job", ""))
            host = str(v.get("host", ""))
            ns, _, cname = job.partition("/")
            self._note_deviation(
                "straggler", f"{job}:{host}",
                float(v.get("first_slow_ts") or 0.0),
                f"host {host} straggling on {job} since step "
                f"{v.get('first_slow_step')}",
                entity=(("TpuCluster", ns, cname) if cname else None),
                host=host)
        return verdicts

    def _scan_quota(self) -> List[Dict[str, Any]]:
        if self.quota is None:
            return []
        decisions = list(reversed(
            self.quota.debug_snapshot().get("decisions") or []))
        for d in decisions:
            # Deviations: evictions, denials, and reclaim notices (a
            # notice is admitted=True/evict=False but still the first
            # observable sign of capacity being clawed back).
            if not (d.get("evict") or not d.get("admitted", True)
                    or d.get("reason") in _RECLAIM_REASONS):
                continue
            ns = d.get("namespace", "default")
            name = d.get("name", "")
            kind = d.get("kind") or "TpuCluster"
            self._note_deviation(
                "quota", f"{ns}/{name}:{d.get('reason', '')}",
                float(d.get("ts", 0.0)),
                f"quota {d.get('reason', '')} of {name} "
                f"({d.get('chips', 0)} chips, tenant "
                f"{d.get('tenant', '')})",
                entity=(kind, ns, name))
        return decisions

    def _scan_alerts(self) -> None:
        if self.alerts is None:
            return
        for a in self.alerts.active():
            series = a.get("series") or {}
            ex = a.get("exemplar") or {}
            self._note_deviation(
                "slo-breach",
                f"{a.get('name', '')}[{_series_key(series)}]/"
                f"{a.get('window', '')}",
                float(a.get("since", 0.0)),
                f"SLO {a.get('name', '')} {a.get('window', '')}-window "
                "burn",
                entity=_entity_from_labels(series),
                backend=str(series.get("backend", "")),
                trace_ids=([str(ex["trace_id"])]
                           if ex.get("trace_id") else None))

    def _scan_feeds(self) -> List[Dict[str, Any]]:
        rows: List[Dict[str, Any]] = []
        for feed in self._feeds:
            for row in feed():
                entity = row.get("entity")
                self._note_deviation(
                    str(row["kind"]), str(row["key"]),
                    float(row["ts"]), str(row.get("summary", "")),
                    entity=(tuple(entity) if entity else None),
                    backend=str(row.get("backend", "")),
                    host=str(row.get("host", "")))
                rows.append(row)
        return rows

    # -- ranking ------------------------------------------------------------

    def _rank(self, trigger_ts: float,
              entity: Optional[Tuple[str, str, str]],
              backend: str, host: str,
              trace_ids: set) -> List[Dict[str, Any]]:
        start = trigger_ts - self.lookback_s
        suspects = []
        for entry in self._deviations.values():
            ts = entry["first_ts"]
            if ts < start or ts > trigger_ts:
                continue
            linkage = 0
            if entity is not None and entry["entity"] == entity:
                linkage += 2
            if backend and entry["backend"] == backend:
                linkage += 2
            if trace_ids and entry["trace_ids"] & trace_ids:
                linkage += 1
            if host and entry["host"] == host:
                linkage += 1
            suspects.append((linkage, entry))
        suspects.sort(key=lambda le: (-le[0], le[1]["first_ts"],
                                      le[1]["kind"], le[1]["key"]))
        out = []
        for linkage, entry in suspects[:MAX_SUSPECTS]:
            out.append({
                "kind": entry["kind"], "key": entry["key"],
                "first_ts": round(entry["first_ts"], 3),
                "lead_s": round(trigger_ts - entry["first_ts"], 3),
                "linkage": linkage,
                "summary": entry["summary"],
                "entity": (list(entry["entity"])
                           if entry["entity"] else None),
                "backend": entry["backend"], "host": entry["host"],
                "trace_ids": sorted(entry["trace_ids"]),
            })
        return out

    @staticmethod
    def _verdict(trigger: str, suspects: List[Dict[str, Any]],
                 lookback_s: float) -> str:
        if not suspects:
            return (f"no correlated deviation found in the "
                    f"{lookback_s:.0f}s lookback window")
        top = suspects[0]
        return (f"{top['summary']} began {top['lead_s']:.1f}s before "
                f"{trigger}; {top['kind']} {top['key']} is the top "
                f"suspect")

    # -- evidence capture ---------------------------------------------------

    def _windowed(self, rows: List[Dict[str, Any]], start: float,
                  end: float, ts_field: str = "ts"
                  ) -> List[Dict[str, Any]]:
        return [copy.deepcopy(r) for r in rows
                if start <= float(r.get(ts_field, 0.0) or 0.0) <= end]

    def _capture_traces(self, trace_ids: set, start: float,
                        end: float) -> List[Dict[str, Any]]:
        if self.tracer is None:
            return []
        from kuberay_tpu.obs.trace import span_tree
        ids = set(trace_ids)
        if not ids:
            # Fallback exemplar: the latest closed serve-request (or any
            # root) span inside the window.
            spans = self.tracer.export()
            best = None
            for s in spans:
                if s["end"] is None or not (start <= s["start"] <= end):
                    continue
                if best is None or (s["name"] == "serve-request",
                                    s["start"], s["span_id"]) > \
                        (best["name"] == "serve-request", best["start"],
                         best["span_id"]):
                    best = s
            if best is not None:
                ids = {best["trace_id"]}
        return [{"trace_id": tid,
                 "tree": span_tree(self.tracer.export(tid))}
                for tid in sorted(ids)]

    def _capture_profile_diff(self, start: float) -> Optional[Dict[str, Any]]:
        """Noise-gated critical-path diff: the incident window's spans
        vs the pre-incident baseline (everything closed before the
        window opened)."""
        if self.tracer is None:
            return None
        from kuberay_tpu.obs.profile import diff_profiles, profile_spans
        spans = [s for s in self.tracer.export() if s["end"] is not None]
        base = [s for s in spans if s["end"] <= start]
        window = [s for s in spans if s["end"] > start]
        if not base or not window:
            return None
        base_prof = profile_spans(base)
        win_prof = profile_spans(window)
        if not base_prof.get("shapes") or not win_prof.get("shapes"):
            return None
        return diff_profiles(base_prof, win_prof)

    def _capture(self, trigger: str, trigger_ts: float, now: float,
                 entity: Optional[Tuple[str, str, str]], detail: str,
                 alert: Optional[Dict[str, Any]] = None,
                 backend: str = "", host: str = "",
                 trace_ids: Optional[set] = None) -> Dict[str, Any]:
        start = trigger_ts - self.lookback_s
        end = max(trigger_ts, now)
        tids = set(trace_ids or ())
        suspects = self._rank(trigger_ts, entity, backend, host, tids)
        for s in suspects:
            tids.update(s["trace_ids"])
        evidence: Dict[str, Any] = {}
        if self.alerts is not None:
            doc = self.alerts.to_dict()
            evidence["alerts"] = {
                "active": copy.deepcopy(doc["active"]),
                "ring": self._windowed(doc["ring"], start, end, "since"),
            }
        traces = self._capture_traces(tids, start, end)
        if traces:
            evidence["traces"] = traces
        if self.flight is not None and entity is not None:
            evidence["flight"] = {
                "key": "%s/%s/%s" % entity,
                "records": [r for r in self.flight.timeline(*entity)
                            if start <= r.get("ts", 0.0) <= end],
            }
        if self.goodput is not None and entity is not None:
            roll = self.goodput.rollup(*entity)
            if roll is not None:
                evidence["goodput"] = {
                    "intervals": [
                        iv for iv in self.goodput.intervals(*entity)
                        if iv["end"] is None or iv["end"] >= start],
                    "rollup": roll,
                }
        if self.audit is not None:
            evidence["autoscaler"] = self._windowed(
                self.audit.to_list(), start, end)
        if self.quota is not None:
            evidence["quota"] = self._windowed(
                self.quota.debug_snapshot().get("decisions") or [],
                start, end)
        if self.steps is not None:
            evidence["steps"] = [
                copy.deepcopy(v) for v in self.steps.stragglers()
                if start <= float(v.get("first_slow_ts") or 0.0) <= end]
        diff = self._capture_profile_diff(start)
        if diff is not None:
            evidence["profile_diff"] = diff
        self._seq += 1
        bundle: Dict[str, Any] = {
            "schema": INCIDENT_SCHEMA,
            "id": f"inc{self._seq:06d}",
            "trigger": trigger,
            "ts": round(trigger_ts, 3),
            "window": {"start": round(start, 3), "end": round(end, 3)},
            "entity": ({"kind": entity[0], "namespace": entity[1],
                        "name": entity[2]} if entity else None),
            "detail": detail,
            "suspects": suspects,
            "verdict": self._verdict(trigger, suspects, self.lookback_s),
            "evidence": evidence,
        }
        if alert is not None:
            bundle["alert"] = copy.deepcopy(alert)
        self._bundles[bundle["id"]] = bundle
        while len(self._bundles) > self.capacity:
            self._bundles.popitem(last=False)
        return bundle

    def _emit_metrics(self, opened: List[Dict[str, Any]]) -> None:
        """Counter + size gauge for freshly opened bundles; called
        OUTSIDE the engine lock (serialization is I/O-shaped work the
        lock must not hold)."""
        if self.registry is None or not opened:
            return
        for bundle in opened:
            self.registry.inc("tpu_incidents_total",
                              {"trigger": bundle["trigger"]})
        self.registry.set_gauge(
            "tpu_incident_bundle_bytes",
            float(len(json.dumps(opened[-1], sort_keys=True))))

    # -- the tick -----------------------------------------------------------

    def evaluate(self, fired: Optional[List[Dict[str, Any]]] = None
                 ) -> List[Dict[str, Any]]:
        """One pass: refresh the first-deviation table from every
        mounted surface, then open a bundle for each unseen native
        trigger (upgrade rollback/abort, straggler verdict, preemption
        feed row, quota reclaim) and each freshly fired alert.  Returns
        the bundles opened this tick."""
        now = self._now()
        opened: List[Dict[str, Any]] = []
        with self._lock:
            self.evaluations += 1
            self._scan_registry(now)
            upgrades = self._scan_audit()
            verdicts = self._scan_steps()
            feed_rows = self._scan_feeds()
            decisions = self._scan_quota()
            self._scan_alerts()
            for entry in upgrades:
                if entry.get("action") not in _ROLLBACK_ACTIONS:
                    continue
                ident = ("rollback", round(float(entry.get("ts", 0.0)), 6),
                         entry.get("service", ""), entry.get("action", ""))
                if ident in self._seen_triggers:
                    continue
                self._seen_triggers.add(ident)
                ns = entry.get("namespace", "default")
                svc = entry.get("service", "")
                alert = entry.get("alert")
                backend = str(((alert or {}).get("series") or {})
                              .get("backend", ""))
                ex = (alert or {}).get("exemplar") or {}
                opened.append(self._capture(
                    "rollback", float(entry.get("ts", 0.0)), now,
                    ("TpuService", ns, svc),
                    f"upgrade {entry.get('action', '')} on {svc}: "
                    f"{entry.get('reason', '')}",
                    alert=alert, backend=backend,
                    trace_ids=({str(ex["trace_id"])}
                               if ex.get("trace_id") else None)))
            for v in verdicts:
                ident = ("straggler", v.get("job", ""),
                         v.get("host", ""),
                         round(float(v.get("first_slow_ts") or 0.0), 6))
                if ident in self._seen_triggers:
                    continue
                self._seen_triggers.add(ident)
                job = str(v.get("job", ""))
                ns, _, cname = job.partition("/")
                opened.append(self._capture(
                    "straggler", float(v.get("first_slow_ts") or 0.0),
                    now, (("TpuCluster", ns, cname) if cname else None),
                    f"straggler verdict: host {v.get('host', '')} on "
                    f"{job} since step {v.get('first_slow_step')}",
                    host=str(v.get("host", ""))))
            for row in feed_rows:
                if not row.get("trigger"):
                    continue
                ident = (str(row["kind"]), str(row["key"]),
                         round(float(row["ts"]), 6))
                if ident in self._seen_triggers:
                    continue
                self._seen_triggers.add(ident)
                entity = row.get("entity")
                opened.append(self._capture(
                    "preemption", float(row["ts"]), now,
                    (tuple(entity) if entity else None),
                    str(row.get("summary", "")),
                    host=str(row.get("host", ""))))
            for d in decisions:
                if d.get("reason") not in _RECLAIM_REASONS:
                    continue
                ident = ("quota-reclaim",
                         round(float(d.get("ts", 0.0)), 6),
                         d.get("name", ""), d.get("reason", ""))
                if ident in self._seen_triggers:
                    continue
                self._seen_triggers.add(ident)
                ns = d.get("namespace", "default")
                kind = d.get("kind") or "TpuCluster"
                opened.append(self._capture(
                    "quota-reclaim", float(d.get("ts", 0.0)), now,
                    (kind, ns, d.get("name", "")),
                    f"quota {d.get('reason', '')} of "
                    f"{d.get('name', '')} (tenant {d.get('tenant', '')},"
                    f" {d.get('chips', 0)} chips)"))
            for a in (fired or []):
                series = a.get("series") or {}
                ident = ("alert", a.get("name", ""),
                         a.get("window", ""), _series_key(series),
                         round(float(a.get("since", 0.0)), 6))
                if ident in self._seen_triggers:
                    continue
                self._seen_triggers.add(ident)
                ex = a.get("exemplar") or {}
                opened.append(self._capture(
                    "alert", float(a.get("since", now)), now,
                    _entity_from_labels(series),
                    f"SLO {a.get('name', '')} {a.get('window', '')}"
                    f"-window burn {a.get('burn_rate', 0)}x",
                    alert=a, backend=str(series.get("backend", "")),
                    trace_ids=({str(ex["trace_id"])}
                               if ex.get("trace_id") else None)))
        self._emit_metrics(opened)
        return opened

    def observe_violations(self, violations) -> List[Dict[str, Any]]:
        """Sim seam: each invariant violation opens a bundle (deduped on
        its rendered text, so re-checks don't double-report)."""
        now = self._now()
        opened: List[Dict[str, Any]] = []
        with self._lock:
            for v in violations:
                ident = ("violation", str(v))
                if ident in self._seen_triggers:
                    continue
                self._seen_triggers.add(ident)
                opened.append(self._capture(
                    "violation", now, now, None, str(v)))
        self._emit_metrics(opened)
        return opened

    # -- querying -----------------------------------------------------------

    def get(self, incident_id: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            b = self._bundles.get(incident_id)
            return copy.deepcopy(b) if b is not None else None

    def bundles(self) -> List[Dict[str, Any]]:
        """Full bundles, newest first."""
        with self._lock:
            return [copy.deepcopy(b)
                    for b in reversed(self._bundles.values())]

    def for_entity(self, namespace: str, name: str
                   ) -> List[Dict[str, Any]]:
        """Bundles whose entity matches (namespace, name), any kind,
        oldest first — the history archive document body."""
        with self._lock:
            return [copy.deepcopy(b) for b in self._bundles.values()
                    if b["entity"] is not None
                    and b["entity"]["namespace"] == namespace
                    and b["entity"]["name"] == name]

    def to_dict(self) -> Dict[str, Any]:
        """The /debug/incidents index: one summary row per bundle,
        newest first."""
        with self._lock:
            rows = []
            for b in reversed(self._bundles.values()):
                top = b["suspects"][0] if b["suspects"] else None
                rows.append({
                    "id": b["id"], "trigger": b["trigger"],
                    "ts": b["ts"], "entity": b["entity"],
                    "detail": b["detail"],
                    "top_suspect": ({"kind": top["kind"],
                                     "key": top["key"],
                                     "lead_s": top["lead_s"]}
                                    if top else None),
                    "verdict": b["verdict"],
                })
            return {"incidents": rows, "count": len(rows),
                    "evaluations": self.evaluations}
