"""Goodput/badput ledger: per-job wall-clock attribution.

The canonical TPU-fleet question — of a TPUJob/TpuCluster's total
wall-clock, how many seconds were productive steps vs. lost to
queueing, slice provisioning, multi-host bootstrap, interruptions and
recovery — answered by a per-(kind, namespace, name)
:class:`GoodputLedger` that attributes **every second** of an object's
lifetime to exactly one phase of an exclusive, exhaustive set:

- ``queued``        — the CR exists but nothing acts on it yet (also:
                      suspended/parked objects);
- ``provisioning``  — the controller has started acting (services,
                      cluster creation for a job) but no pod exists;
- ``bootstrap``     — first pod created → every TPU_WORKER_ID of every
                      slice Running (the multi-host ICI bring-up);
- ``productive``    — full strength: every expected host Running;
- ``stalled-on-straggler`` — full strength on paper, but the step
                      telemetry (obs/steps.py) has flagged a straggler
                      host: every synchronous step runs at the slow
                      host's pace, so these seconds are badput even
                      though every pod is Running;
- ``interrupted``   — any worker of a slice down (a killed host costs
                      the *whole slice's* step time — this phase makes
                      that cost visible);
- ``recovery``      — reprovision/re-bootstrap after an interruption
                      (failed pods cleared, replacements coming up);
- ``teardown``      — deletionTimestamp set / suspend drain → gone.

Intervals are constructed so they **partition** the object's lifetime:
each ``transition`` closes the open interval at the same instant the
next one opens — no gaps, no overlaps, ``sum(phases) == elapsed`` by
construction (the chaos-sim exactness gate in tests/test_goodput.py).

Feeds (all stamped with the *server-side* clock — attribution never
trusts client timestamps):

- store watch events (:meth:`GoodputLedger.observe_event`): CR
  lifecycle + pod phase accounting for pod-backed kinds (TpuCluster);
- controller state transitions via :class:`TransitionRecorder`, the
  single seam every ``.status.state``/phase write routes through
  (enforced by analysis rule #7 ``phase-transition-recorded``) — the
  phase authority for pod-less kinds (TpuJob, TpuService);
- CoordinatorServer job events (``record_events`` → ``received_at``).

Purely observational: the ledger never touches the store, the rng or
the clock's state, so a chaos-sim journal hash is byte-identical with
the ledger on or off.  Bounded: ``max_objects`` tracked objects with
LRU eviction, like the flight recorder.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

from kuberay_tpu.topology import SliceTopology
from kuberay_tpu.utils import constants as C

Key = Tuple[str, str, str]          # (kind, namespace, name)

PHASE_QUEUED = "queued"
PHASE_PROVISIONING = "provisioning"
PHASE_BOOTSTRAP = "bootstrap"
PHASE_PRODUCTIVE = "productive"
PHASE_STALLED = "stalled-on-straggler"
PHASE_INTERRUPTED = "interrupted"
PHASE_RECOVERY = "recovery"
PHASE_TEARDOWN = "teardown"

#: The exclusive, exhaustive phase set, in canonical lifecycle order.
PHASES = (PHASE_QUEUED, PHASE_PROVISIONING, PHASE_BOOTSTRAP,
          PHASE_PRODUCTIVE, PHASE_STALLED, PHASE_INTERRUPTED,
          PHASE_RECOVERY, PHASE_TEARDOWN)

#: Kinds whose phase is derived from pod accounting (watch events); a
#: controller-state transition on these is recorded on the flight ring
#: but does not drive the ledger (one authority per kind).
_POD_DRIVEN_KINDS = (C.KIND_CLUSTER,)

#: Controller-state → phase maps for pod-less kinds (the
#: TransitionRecorder feed).
_STATE_PHASES: Dict[str, Dict[str, str]] = {
    C.KIND_JOB: {
        "New": PHASE_QUEUED,
        "Initializing": PHASE_PROVISIONING,
        "Waiting": PHASE_BOOTSTRAP,
        "Running": PHASE_PRODUCTIVE,
        "Retrying": PHASE_RECOVERY,
        "Suspending": PHASE_TEARDOWN,
        "Suspended": PHASE_QUEUED,
        "Complete": PHASE_TEARDOWN,
        "Failed": PHASE_INTERRUPTED,
    },
    C.KIND_SERVICE: {
        "": PHASE_QUEUED,
        "WaitForServeDeploymentReady": PHASE_BOOTSTRAP,
        "Running": PHASE_PRODUCTIVE,
        "Suspended": PHASE_QUEUED,
    },
}

#: Pod phases that mean "this host is down" (a Succeeded worker is as
#: dead to the ICI ring as a Failed one — ref shouldDeletePod).
_POD_DOWN_PHASES = ("Failed", "Succeeded")


def _expected_pods(obj: Dict[str, Any]) -> Optional[int]:
    """Pods a TpuCluster needs at full strength: 1 head + replicas ×
    hosts-per-slice per worker group.  None when the spec is unreadable
    (the machine falls back to all-running heuristics)."""
    spec = obj.get("spec") or {}
    if spec.get("suspend"):
        return 0
    try:
        n = 1                                   # head
        for g in spec.get("workerGroupSpecs") or []:
            if g.get("suspend"):
                continue
            topo = SliceTopology.create(g.get("accelerator", "v5e"),
                                        g.get("topology", "2x2"))
            n += max(0, int(g.get("replicas", 1))) * topo.num_hosts
        return n
    except Exception:
        return None


class _Entry:
    """Per-object ledger state.  Intervals are ``[phase, start, end]``
    with ``end is None`` only on the last (open) interval."""

    __slots__ = ("intervals", "pods", "expected", "reached_productive",
                 "growing", "closed", "stalled")

    def __init__(self):
        self.intervals: List[List[Any]] = []
        self.pods: Dict[str, str] = {}          # pod name -> phase
        self.expected: Optional[int] = None
        self.reached_productive = False
        self.growing = False
        self.closed = False
        self.stalled = False        # step telemetry flagged a straggler


class GoodputLedger:
    def __init__(self, clock=None, metrics=None, max_objects: int = 2048):
        # ``clock``: duck-typed .now() (the sim passes its VirtualClock);
        # defaults to wall time.  This is THE timestamp authority: every
        # transition is stamped server-side, never from client payloads.
        self._now = clock.now if clock is not None else time.time
        # Optional ControlPlaneMetrics: closed intervals feed
        # tpu_goodput_seconds_total{kind,phase}; every transition
        # refreshes the per-object tpu_goodput_ratio gauge.
        self.metrics = metrics
        self.max_objects = max_objects
        self._lock = threading.Lock()
        self._objs: "OrderedDict[Key, _Entry]" = OrderedDict()

    # -- core primitive ------------------------------------------------------

    def _entry(self, key: Key) -> _Entry:
        e = self._objs.get(key)
        if e is None:
            e = _Entry()
            self._objs[key] = e
            if len(self._objs) > self.max_objects:
                self._objs.popitem(last=False)
        else:
            self._objs.move_to_end(key)
        return e

    def _current_phase(self, e: _Entry) -> Optional[str]:
        return e.intervals[-1][0] if e.intervals else None

    def transition(self, kind: str, namespace: str, name: str, phase: str,
                   ts: Optional[float] = None) -> None:
        """Close the open interval and open ``phase`` at the same
        instant.  Idempotent on an unchanged phase; ignored after
        ``close``.  ``ts`` must come from a server-side clock (defaults
        to this ledger's); it is clamped so intervals never run
        backwards."""
        with self._lock:
            key = (kind, namespace, name)
            e = self._entry(key)
            self._transition_locked(key, e, phase, ts)

    def _transition_locked(self, key: Key, e: _Entry, phase: str,
                           ts: Optional[float]) -> None:
        if e.closed or phase not in PHASES:
            return
        now = self._now() if ts is None else ts
        if not e.intervals:
            e.intervals.append([phase, now, None])
            self._refresh_gauge(key, e, now)
            return
        last = e.intervals[-1]
        if last[0] == phase:
            return
        now = max(now, last[1])                 # monotonic partition
        last[2] = now
        self._emit_interval(key, last)
        e.intervals.append([phase, now, None])
        self._refresh_gauge(key, e, now)

    def close(self, kind: str, namespace: str, name: str,
              ts: Optional[float] = None) -> None:
        """End of life (the object was DELETED): close the open interval
        and freeze the ledger — the rollup stops extending with the
        clock, which is what the history archive snapshots."""
        with self._lock:
            e = self._objs.get((kind, namespace, name))
            if e is None or e.closed or not e.intervals:
                return
            now = self._now() if ts is None else ts
            last = e.intervals[-1]
            if last[2] is None:
                last[2] = max(now, last[1])
                self._emit_interval((kind, namespace, name), last)
            e.closed = True
            self._refresh_gauge((kind, namespace, name), e, last[2])

    def _emit_interval(self, key: Key, interval: List[Any]) -> None:
        if self.metrics is not None:
            self.metrics.goodput_seconds(key[0], interval[0],
                                         interval[2] - interval[1])

    def _refresh_gauge(self, key: Key, e: _Entry, now: float) -> None:
        if self.metrics is None:
            return
        roll = self._rollup_locked(key, e, now)
        self.metrics.set_goodput_ratio(key[0], key[1], key[2],
                                       roll["goodput_ratio"])

    # -- step-telemetry feed (StepTracker) -----------------------------------

    def set_stalled(self, kind: str, namespace: str, name: str,
                    stalled: bool, ts: Optional[float] = None) -> None:
        """Sub-attribution inside full strength (the obs/steps.py
        feed): while a straggler host is flagged, seconds that would
        read PRODUCTIVE read ``stalled-on-straggler`` instead — the
        slice runs, but at the slow host's pace.  The flag persists, so
        pod-driven recomputes keep honoring it until cleared; the
        partition discipline is untouched (the phase swap reuses
        ``_transition_locked``, so intervals still tile the lifetime).
        ``ts`` lets the caller backdate the edge to the first observed
        slow step — server-side clocks only, clamped monotonic as
        always."""
        with self._lock:
            key = (kind, namespace, name)
            e = self._objs.get(key)
            if e is None or e.closed or e.stalled == bool(stalled):
                return
            e.stalled = bool(stalled)
            cur = self._current_phase(e)
            if e.stalled and cur == PHASE_PRODUCTIVE:
                self._transition_locked(key, e, PHASE_STALLED, ts)
            elif not e.stalled and cur == PHASE_STALLED:
                self._transition_locked(key, e, PHASE_PRODUCTIVE, ts)

    # -- controller-state feed (TransitionRecorder) --------------------------

    def observe_state(self, kind: str, namespace: str, name: str,
                      state: str, ts: Optional[float] = None) -> None:
        """Fold a controller ``.status.state`` transition.  Pod-backed
        kinds are ignored here (their authority is pod accounting via
        ``observe_event``); pod-less kinds map controller states to
        phases via ``_STATE_PHASES``."""
        if kind in _POD_DRIVEN_KINDS:
            return
        phase = _STATE_PHASES.get(kind, {}).get(state)
        if phase is None:
            return
        self.transition(kind, namespace, name, phase, ts)

    # -- store watch feed ----------------------------------------------------

    def observe_event(self, ev) -> None:
        """Store watch hook (install with ``store.watch``).  Reads only;
        never mutates the event or the store — safe under the store
        lock, and invisible to the sim journal hash."""
        kind = ev.kind
        if kind == "Event" or ev.type == "BOOKMARK":
            return   # telemetry / progress markers, not lifecycle state
        obj = ev.obj
        md = obj.get("metadata", {}) or {}
        ns = md.get("namespace", "default")
        name = md.get("name", "")
        now = self._now()

        if kind in _POD_DRIVEN_KINDS:
            self._observe_tracked_cr(kind, ns, name, ev.type, obj, now)
            return
        if kind in _STATE_PHASES or kind == C.KIND_CRONJOB:
            self._observe_stateful_cr(kind, ns, name, ev.type, obj, now)
            return
        if kind == "Pod":
            self._observe_pod(ev.type, obj, md, ns, now)
            return
        # Any other owned object (head Service, Secret, Ingress…)
        # appearing for a queued cluster means the controller has begun
        # acting: queued -> provisioning.
        owner = (md.get("labels", {}) or {}).get(C.LABEL_CLUSTER)
        if owner and ev.type == "ADDED":
            with self._lock:
                key = (C.KIND_CLUSTER, ns, owner)
                e = self._objs.get(key)
                if e is not None and \
                        self._current_phase(e) == PHASE_QUEUED:
                    self._transition_locked(key, e, PHASE_PROVISIONING, now)

    def _observe_tracked_cr(self, kind: str, ns: str, name: str,
                            etype: str, obj: Dict[str, Any],
                            now: float) -> None:
        with self._lock:
            key = (kind, ns, name)
            if etype == "DELETED":
                e = self._objs.get(key)
                if e is None:
                    return
                self._transition_locked(key, e, PHASE_TEARDOWN, now)
                if not e.closed and e.intervals:
                    last = e.intervals[-1]
                    if last[2] is None:
                        last[2] = max(now, last[1])
                        self._emit_interval(key, last)
                    e.closed = True
                    self._refresh_gauge(key, e, last[2])
                return
            e = self._entry(key)
            exp = _expected_pods(obj)
            if etype == "ADDED":
                e.expected = exp
                if not e.intervals:
                    self._transition_locked(key, e, PHASE_QUEUED, now)
                return
            # MODIFIED
            if obj.get("metadata", {}).get("deletionTimestamp"):
                self._transition_locked(key, e, PHASE_TEARDOWN, now)
                return
            if exp is not None and e.expected is not None and \
                    exp > e.expected and \
                    self._current_phase(e) == PHASE_PRODUCTIVE:
                # Capacity growth from full strength is provisioning/
                # bootstrap of the new slices, not an interruption.
                e.growing = True
            e.expected = exp
            self._recompute_locked(key, e, now)

    def _observe_stateful_cr(self, kind: str, ns: str, name: str,
                             etype: str, obj: Dict[str, Any],
                             now: float) -> None:
        with self._lock:
            key = (kind, ns, name)
            if etype == "DELETED":
                e = self._objs.get(key)
                if e is None:
                    return
                self._transition_locked(key, e, PHASE_TEARDOWN, now)
                if not e.closed and e.intervals:
                    last = e.intervals[-1]
                    if last[2] is None:
                        last[2] = max(now, last[1])
                        self._emit_interval(key, last)
                    e.closed = True
                return
            e = self._entry(key)
            if etype == "ADDED" and not e.intervals:
                self._transition_locked(key, e, PHASE_QUEUED, now)
            elif obj.get("metadata", {}).get("deletionTimestamp"):
                self._transition_locked(key, e, PHASE_TEARDOWN, now)

    def _observe_pod(self, etype: str, obj: Dict[str, Any],
                     md: Dict[str, Any], ns: str, now: float) -> None:
        labels = md.get("labels", {}) or {}
        cluster = labels.get(C.LABEL_CLUSTER)
        if not cluster:
            return
        key = (C.KIND_CLUSTER, ns, cluster)
        pod_name = md.get("name", "")
        with self._lock:
            e = self._objs.get(key)
            if e is None or e.closed:
                return
            if etype == "DELETED":
                e.pods.pop(pod_name, None)
            else:
                e.pods[pod_name] = (obj.get("status", {}) or {}).get(
                    "phase", "Pending")
            self._recompute_locked(key, e, now)

    def _recompute_locked(self, key: Key, e: _Entry, now: float) -> None:
        """The pod-accounting phase machine (TpuCluster)."""
        if e.closed:
            return
        cur = self._current_phase(e)
        if cur == PHASE_TEARDOWN:
            return
        down = any(p in _POD_DOWN_PHASES for p in e.pods.values())
        n_running = sum(1 for p in e.pods.values() if p == "Running")
        n_starting = len(e.pods) - n_running - sum(
            1 for p in e.pods.values() if p in _POD_DOWN_PHASES)
        exp = e.expected
        if exp == 0:
            # Suspend: draining counts as teardown, parked as queued.
            nxt = PHASE_QUEUED if not e.pods else PHASE_TEARDOWN
            self._transition_locked(key, e, nxt, now)
            return
        # Full strength: when the expected count is known, surplus
        # starting pods on top of it (a pre-provisioned preemption
        # replacement building while the old slice still runs) must not
        # demote the cluster out of PRODUCTIVE — training is running at
        # strength the whole time.  Without an expected count, any
        # starting pod still means bootstrap.
        full = (n_running > 0 and not down
                and ((exp is not None and n_running >= exp)
                     or (exp is None and n_starting == 0)))
        if full:
            e.reached_productive = True
            e.growing = False
            nxt = PHASE_STALLED if e.stalled else PHASE_PRODUCTIVE
        elif down:
            # A host down before first full strength is still bootstrap
            # (the bring-up has not completed); after it, the whole
            # slice's step time is lost: interrupted.
            nxt = (PHASE_INTERRUPTED if e.reached_productive
                   else PHASE_BOOTSTRAP)
        elif not e.reached_productive:
            if not e.pods:
                return                          # still queued/provisioning
            nxt = PHASE_BOOTSTRAP
        elif e.growing:
            nxt = PHASE_BOOTSTRAP
        elif cur in (PHASE_INTERRUPTED, PHASE_RECOVERY):
            # Failed pods cleared, replacements coming up.
            nxt = PHASE_RECOVERY
        else:
            # Capacity silently dropped below full strength (delete
            # race, vanished pod): the slice is down.
            nxt = PHASE_INTERRUPTED
        self._transition_locked(key, e, nxt, now)

    # -- querying ------------------------------------------------------------

    def keys(self) -> List[Key]:
        with self._lock:
            return list(self._objs)

    def intervals(self, kind: str, namespace: str, name: str
                  ) -> List[Dict[str, Any]]:
        with self._lock:
            e = self._objs.get((kind, namespace, name))
            if e is None:
                return []
            return [{"phase": p, "start": s, "end": t}
                    for p, s, t in e.intervals]

    def _rollup_locked(self, key: Key, e: _Entry,
                       now: Optional[float] = None) -> Dict[str, Any]:
        now = self._now() if now is None else now
        phases = {p: 0.0 for p in PHASES}
        start = e.intervals[0][1] if e.intervals else None
        end = start
        for p, s, t in e.intervals:
            t = s if t is None and now < s else (now if t is None else t)
            phases[p] += t - s
            end = t
        total = (end - start) if start is not None else 0.0
        productive = phases[PHASE_PRODUCTIVE]
        return {
            "kind": key[0], "namespace": key[1], "name": key[2],
            "phases": phases,
            "start": start, "end": end, "total": total,
            "goodput_ratio": (productive / total) if total > 0 else 0.0,
            "current_phase": self._current_phase(e),
            "closed": e.closed,
        }

    def rollup(self, kind: str, namespace: str, name: str,
               now: Optional[float] = None) -> Optional[Dict[str, Any]]:
        """Per-phase seconds + goodput ratio.  Open intervals extend to
        ``now``; ``sum(phases) == total`` by construction."""
        with self._lock:
            key = (kind, namespace, name)
            e = self._objs.get(key)
            if e is None:
                return None
            return self._rollup_locked(key, e, now)

    def to_doc(self, kind: str, namespace: str, name: str
               ) -> Optional[Dict[str, Any]]:
        """The archive document (``meta/{ns}/{cluster}/goodput.json``):
        interval list + rollup, JSON-ready."""
        roll = self.rollup(kind, namespace, name)
        if roll is None:
            return None
        return {"kind": kind, "namespace": namespace, "name": name,
                "intervals": self.intervals(kind, namespace, name),
                "rollup": roll}

    def to_dict(self) -> Dict[str, Any]:
        """Whole-ledger snapshot (sim failure reports / export_trace)."""
        out = {}
        for kind, ns, name in self.keys():
            doc = self.to_doc(kind, ns, name)
            if doc is not None:
                out[f"{kind}/{ns}/{name}"] = doc
        return out


class TransitionRecorder:
    """The single seam controller ``.status.state``/phase writes route
    through (analysis rule #7 ``phase-transition-recorded``): records
    the transition on the flight ring (source=controller, alongside the
    watch-derived record) and feeds the goodput ledger — with the
    recorder's server-side clock, never the caller's."""

    enabled = True

    def __init__(self, flight=None, ledger=None, clock=None):
        self.flight = flight
        self.ledger = ledger
        self._now = clock.now if clock is not None else time.time

    def record(self, kind: str, namespace: str, name: str, new_state: str,
               old_state: str = "") -> None:
        ts = self._now()
        if self.flight is not None:
            self.flight.record(kind, namespace, name, "state",
                               f"{old_state or '<none>'} -> "
                               f"{new_state or '<none>'}",
                               source="controller")
        if self.ledger is not None:
            self.ledger.observe_state(kind, namespace, name, new_state, ts)


class NoopTransitionRecorder:
    """Default for every controller ``transitions=`` parameter: the
    annotation costs one attribute lookup when the ledger is off."""

    enabled = False

    def record(self, kind: str, namespace: str, name: str, new_state: str,
               old_state: str = "") -> None:
        pass


NOOP_TRANSITIONS = NoopTransitionRecorder()
