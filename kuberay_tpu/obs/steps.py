"""Training-step telemetry: per-host heartbeats -> straggler microscope.

The goodput ledger (obs/goodput.py) marks whole intervals PRODUCTIVE
the moment pods run; this module sees *inside* those intervals.  Every
training host posts lightweight per-step heartbeats (step index, step
wall time, tokens processed, collective wait) through the existing
``CoordinatorClient`` -> ``CoordinatorServer.record_events`` path —
server-side ``received_at`` is the timestamp authority, client clocks
are display-only — and the coordinator feeds them into a per-(job,
host) :class:`StepTracker` which computes:

- **windowed step-time distributions** per host (p50/p90/mean over the
  last ``window`` steps, via the shared ``utils.quantiles`` estimator);
- **cross-host skew**: each host's windowed median over the fleet
  median of those medians (1.0 = lockstep; synchronous data-parallel
  training runs at the speed of its slowest host, so skew IS lost
  goodput);
- a **straggler verdict**: a host whose step time exceeds the fleet
  median by ``straggler_ratio`` for ``straggler_steps`` consecutive
  steps is flagged.  Verdicts backdate to the *first* slow step — the
  stall began when the host slowed down, not when the evidence
  finished accumulating — and clear on the first step back under the
  ratio.  Single-host jobs never flag (no fleet to skew against).
- **MFU** (model-FLOPs-utilization) from the heartbeat's model config:
  ``6 * n_params * fleet_tokens_per_sec / 1e12 / device_count /
  peak_tflops_per_chip`` — the same estimate train/launcher.py
  publishes locally, now attributed fleet-wide by the coordinator.

Fan-out on every verdict edge: ``tpu_train_*`` metrics (histogram with
exemplars pointing at the offending heartbeat event id), a straggler
record in the flight ring under the job's goodput key, and a
``GoodputLedger.set_stalled`` edge that splits PRODUCTIVE time into
``productive`` vs ``stalled-on-straggler`` while keeping the
exclusive+exhaustive interval discipline (sum(phases) == total).

Observational-only contract (the same one tracer/flight/goodput obey):
the tracker reads timestamps and heartbeats, never touches the store
or any RNG — a sim run produces byte-identical journal hashes with
telemetry on or off.  :class:`NoopStepTracker` is the
bench-measurable zero: same surface, no work.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from kuberay_tpu.utils.quantiles import quantile, sorted_quantile

#: Verdict defaults: flag a host at straggler_ratio x the fleet median
#: held for straggler_steps consecutive steps.  K=5 keeps one GC pause
#: from paging anyone while catching a real slow host within seconds.
STRAGGLER_RATIO = 1.5
STRAGGLER_STEPS = 5


def default_goodput_key(job_id: str) -> Tuple[str, str, str]:
    """Goodput/flight key for a coordinator job — the same
    ("CoordinatorJob", "head", job) triple the coordinator's own
    job_started/job_finished feed uses (runtime/coordinator_server.py),
    so step attribution lands on the interval it refines."""
    return ("CoordinatorJob", "head", job_id)


class _Host:
    __slots__ = ("durs", "tokens", "waits", "med_dur", "med_tok",
                 "dur_uniform", "tok_uniform",
                 "tok_rate", "last_skew", "last_step", "last_ts",
                 "last_dur", "steps_observed", "consecutive_slow",
                 "first_slow_step", "first_slow_ts", "flagged")

    def __init__(self, window: int):
        self.durs: deque = deque(maxlen=window)
        self.tokens: deque = deque(maxlen=window)
        self.waits: deque = deque(maxlen=window)
        # Windowed medians, cached at append time: the fleet median and
        # MFU read every host on every heartbeat, and recomputing each
        # host's quantile there would make ingestion O(hosts * window
        # log window) per beat (the telemetry bench gates this).
        self.med_dur = 0.0
        self.med_tok = 0.0
        # A window whose min == max has a known median: appending the
        # same value again keeps it, no re-sort (steady-state training
        # emits near-constant durations/token counts).
        self.dur_uniform = False
        self.tok_uniform = False
        self.tok_rate = 0.0      # cached med_tok/med_dur contribution
        self.last_skew = -1.0    # last gauge value emitted (throttle)
        self.last_step = 0
        self.last_ts = 0.0
        self.last_dur = 0.0
        self.steps_observed = 0
        self.consecutive_slow = 0
        self.first_slow_step: Optional[int] = None
        self.first_slow_ts: Optional[float] = None
        self.flagged = False


class _Job:
    __slots__ = ("hosts", "n_params", "device_count", "peak_tflops",
                 "verdicts", "stalled", "fleet_med", "fleet_dirty",
                 "tok_s_sum", "last_mfu", "gkey")

    def __init__(self, max_hosts: int):
        self.hosts: "OrderedDict[str, _Host]" = OrderedDict()
        self.n_params: Optional[float] = None
        self.device_count: Optional[int] = None
        self.peak_tflops: Optional[float] = None
        # Closed + open straggler verdicts, oldest dropped first.
        self.verdicts: deque = deque(maxlen=64)
        self.stalled = False        # >=1 host currently flagged
        # Ingestion-path caches: the fleet median is recomputed only
        # when some host's cached windowed median actually moved, and
        # the fleet tokens/s sum is maintained by per-host deltas, so
        # a steady-state heartbeat costs O(window) for its own host
        # rather than O(hosts * window) across the fleet.
        self.fleet_med = 0.0
        self.fleet_dirty = True
        self.tok_s_sum = 0.0
        self.last_mfu = -1.0        # last gauge value emitted (throttle)
        self.gkey: Optional[Tuple[str, str, str]] = None


class StepTracker:
    """Per-(job, host) step-telemetry aggregator.  Thread-safe; bounded
    everywhere (LRU jobs, LRU hosts per job, fixed windows, capped
    verdict ring) — heartbeat floods cannot grow it without bound."""

    def __init__(self, clock=None, metrics=None, flight=None,
                 goodput=None,
                 goodput_key: Callable[[str], Tuple[str, str, str]]
                 = default_goodput_key,
                 window: int = 64,
                 straggler_ratio: float = STRAGGLER_RATIO,
                 straggler_steps: int = STRAGGLER_STEPS,
                 max_jobs: int = 64, max_hosts: int = 512):
        self._now = clock.now if clock is not None else time.time
        self.metrics = metrics
        self.flight = flight
        self.goodput = goodput
        self.goodput_key = goodput_key
        self.window = window
        self.straggler_ratio = straggler_ratio
        self.straggler_steps = straggler_steps
        self.max_jobs = max_jobs
        self.max_hosts = max_hosts
        self._lock = threading.Lock()
        self._jobs: "OrderedDict[str, _Job]" = OrderedDict()

    # -- ingestion ---------------------------------------------------------

    def observe(self, job_id: str, host: str, step: int, dur_s: float,
                tokens: float = 0.0, collective_wait_s: float = 0.0,
                ts: Optional[float] = None,
                n_params: Optional[float] = None,
                device_count: Optional[int] = None,
                peak_tflops: Optional[float] = None,
                exemplar: Optional[str] = None) -> None:
        """Ingest one heartbeat.  ``ts`` is the server's ``received_at``
        (the timestamp authority); ``exemplar`` the coordinator-minted
        event id, threaded into the duration histogram so a p99 bucket
        links back to the exact offending heartbeat."""
        if not job_id or not host or dur_s < 0:
            return
        ts = self._now() if ts is None else ts
        with self._lock:
            job = self._job_locked(job_id)
            h = self._host_locked(job, host)
            self._absorb_beat_locked(job, h, step, dur_s, tokens,
                                     collective_wait_s, ts)
            if n_params is not None:
                job.n_params = float(n_params)
            if device_count is not None:
                job.device_count = int(device_count)
            if peak_tflops is not None:
                job.peak_tflops = float(peak_tflops)
            if job.fleet_dirty:
                job.fleet_med = self._fleet_median_locked(job)
                job.fleet_dirty = False
            fleet_median = job.fleet_med
            skew = (h.last_dur / fleet_median) if fleet_median > 0 else 0.0
            # A fleet of one (or an empty fleet) has no median to skew
            # against.  The steady case (not slow, nothing to clear)
            # skips the verdict machinery entirely.
            slow = (fleet_median > 0
                    and dur_s > self.straggler_ratio * fleet_median
                    and len(job.hosts) >= 2)
            if slow or h.flagged or h.consecutive_slow:
                edge = self._verdict_locked(job, host, h, step, dur_s,
                                            ts, slow)
            else:
                edge = None
            mfu = self._mfu_fast_locked(job)
            # Gauge throttle: skew/MFU re-emit only when the value
            # actually moved (>0.5%); a steady-state heartbeat costs
            # one histogram observe, not three registry round-trips.
            emit_skew = abs(skew - h.last_skew) > 0.005
            if emit_skew:
                h.last_skew = skew
            emit_mfu = mfu is not None and abs(mfu - job.last_mfu) > \
                0.005 * max(abs(job.last_mfu), 1e-9)
            if emit_mfu:
                job.last_mfu = mfu
            if job.gkey is None:
                job.gkey = self.goodput_key(job_id)
            kind, ns, name = job.gkey
        # Fan-out outside the tracker lock: metrics/flight/goodput each
        # take their own locks.
        m = self.metrics
        if m is not None:
            m.observe_train_step(job_id, host, dur_s,
                                 exemplar=exemplar, exemplar_ts=ts)
            if emit_skew:
                m.set_train_skew(job_id, kind, ns, name, host, skew)
            if emit_mfu:
                m.set_train_mfu(job_id, kind, ns, name, mfu)
        if edge is not None:
            self._fanout_edge(job_id, kind, ns, name, edge)

    def observe_fleet_step(self, job_id: str, step: int,
                           beats: List[Tuple],
                           ts: Optional[float] = None,
                           n_params: Optional[float] = None,
                           device_count: Optional[int] = None,
                           peak_tflops: Optional[float] = None) -> None:
        """One synchronous training step for the whole fleet: ``beats``
        is ``[(host, dur_s, tokens, collective_wait_s, exemplar), ...]``
        sharing one step index and one server timestamp — the shape the
        sim's heartbeat emission produces.  Equivalent to ``observe``
        per host, but the lock, the fleet-median/MFU recomputes, the
        model config, and the goodput key amortize across the fleet,
        and every host's verdict is judged against the same post-step
        fleet median (cleaner than the per-beat path's incremental
        view, where earlier hosts see later hosts' previous window)."""
        if not job_id or not beats:
            return
        ts = self._now() if ts is None else ts
        edges: List[Dict[str, Any]] = []
        skews: List[Tuple[str, float]] = []
        with self._lock:
            job = self._job_locked(job_id)
            if n_params is not None:
                job.n_params = float(n_params)
            if device_count is not None:
                job.device_count = int(device_count)
            if peak_tflops is not None:
                job.peak_tflops = float(peak_tflops)
            for host, dur_s, tokens, wait, _ in beats:
                if not host or dur_s < 0:
                    continue
                h = self._host_locked(job, host)
                self._absorb_beat_locked(job, h, step, dur_s, tokens,
                                         wait, ts)
            if job.fleet_dirty:
                job.fleet_med = self._fleet_median_locked(job)
                job.fleet_dirty = False
            fm = job.fleet_med
            judge = len(job.hosts) >= 2 and fm > 0
            for host, dur_s, tokens, wait, _ in beats:
                h = job.hosts.get(host)
                if h is None or dur_s < 0:
                    continue
                skew = (h.last_dur / fm) if fm > 0 else 0.0
                slow = judge and dur_s > self.straggler_ratio * fm
                if slow or h.flagged or h.consecutive_slow:
                    edge = self._verdict_locked(job, host, h, step,
                                                dur_s, ts, slow)
                    if edge is not None:
                        edges.append(edge)
                if abs(skew - h.last_skew) > 0.005:
                    h.last_skew = skew
                    skews.append((host, skew))
            mfu = self._mfu_fast_locked(job)
            emit_mfu = mfu is not None and abs(mfu - job.last_mfu) > \
                0.005 * max(abs(job.last_mfu), 1e-9)
            if emit_mfu:
                job.last_mfu = mfu
            if job.gkey is None:
                job.gkey = self.goodput_key(job_id)
            kind, ns, name = job.gkey
        m = self.metrics
        if m is not None:
            m.observe_train_steps(
                job_id,
                [(host, dur_s, exemplar)
                 for host, dur_s, tokens, wait, exemplar in beats
                 if host and dur_s >= 0],
                ts=ts)
            for host, skew in skews:
                m.set_train_skew(job_id, kind, ns, name, host, skew)
            if emit_mfu:
                m.set_train_mfu(job_id, kind, ns, name, mfu)
        for edge in edges:
            self._fanout_edge(job_id, kind, ns, name, edge)

    # -- internals (under self._lock) --------------------------------------

    def _absorb_beat_locked(self, job: _Job, h: _Host, step: int,
                            dur_s: float, tokens: float, wait: float,
                            ts: float) -> None:
        """Fold one heartbeat into a host's windows + cached medians."""
        fd = float(dur_s)
        if h.dur_uniform and h.durs and fd == h.med_dur:
            h.durs.append(fd)           # median provably unchanged
        else:
            old_med = h.med_dur
            h.durs.append(fd)
            xs = sorted(h.durs)
            h.med_dur = sorted_quantile(xs, 0.5)
            h.dur_uniform = xs[0] == xs[-1]
            if h.med_dur != old_med:
                job.fleet_dirty = True
        if tokens:
            tv = float(tokens)
            if h.tok_uniform and h.tokens and tv == h.med_tok:
                h.tokens.append(tv)
            else:
                h.tokens.append(tv)
                xs = sorted(h.tokens)
                h.med_tok = sorted_quantile(xs, 0.5)
                h.tok_uniform = xs[0] == xs[-1]
        rate = (h.med_tok / h.med_dur
                if h.tokens and h.med_dur > 0 else 0.0)
        if rate != h.tok_rate:
            job.tok_s_sum += rate - h.tok_rate
            h.tok_rate = rate
        h.waits.append(float(wait))
        h.last_step = int(step)
        h.last_ts = ts
        h.last_dur = fd
        h.steps_observed += 1

    def _job_locked(self, job_id: str) -> _Job:
        job = self._jobs.get(job_id)
        if job is None:
            job = self._jobs[job_id] = _Job(self.max_hosts)
        self._jobs.move_to_end(job_id)
        while len(self._jobs) > self.max_jobs:
            self._jobs.popitem(last=False)
        return job

    def _host_locked(self, job: _Job, host: str) -> _Host:
        h = job.hosts.get(host)
        if h is None:
            h = job.hosts[host] = _Host(self.window)
        job.hosts.move_to_end(host)
        while len(job.hosts) > self.max_hosts:
            _, evicted = job.hosts.popitem(last=False)
            job.tok_s_sum -= evicted.tok_rate
            job.fleet_dirty = True
        return h

    def _fleet_median_locked(self, job: _Job) -> float:
        meds = [h.med_dur for h in job.hosts.values() if h.durs]
        return quantile(meds, 0.5) if meds else 0.0

    def _verdict_locked(self, job: _Job, host: str, h: _Host, step: int,
                        dur_s: float, ts: float,
                        slow: bool) -> Optional[Dict[str, Any]]:
        """Advance the consecutive-slow counter; return a fan-out edge
        dict on flag/clear transitions, else None.  ``slow`` is the
        caller's ratio-vs-fleet-median judgment (computed inline on the
        hot path so the steady case never enters this function)."""
        if slow:
            if h.consecutive_slow == 0:
                h.first_slow_step = int(step)
                h.first_slow_ts = ts
            h.consecutive_slow += 1
            if not h.flagged and h.consecutive_slow >= self.straggler_steps:
                h.flagged = True
                verdict = {
                    "host": host,
                    "first_slow_step": h.first_slow_step,
                    "first_slow_ts": h.first_slow_ts,
                    "detected_step": int(step),
                    "detected_ts": ts,
                    "skew": round(dur_s / job.fleet_med, 4),
                    "fleet_median_s": round(job.fleet_med, 6),
                    "cleared_step": None,
                    "cleared_ts": None,
                }
                job.verdicts.append(verdict)
                was_stalled = job.stalled
                job.stalled = True
                return {"kind": "flagged", "verdict": verdict,
                        "stall_edge": not was_stalled,
                        "ts": h.first_slow_ts}
        else:
            h.consecutive_slow = 0
            h.first_slow_step = None
            h.first_slow_ts = None
            if h.flagged:
                h.flagged = False
                verdict = None
                for v in reversed(job.verdicts):
                    if v["host"] == host and v["cleared_step"] is None:
                        verdict = v
                        break
                if verdict is not None:
                    verdict["cleared_step"] = int(step)
                    verdict["cleared_ts"] = ts
                still = any(o.flagged for o in job.hosts.values())
                job.stalled = still
                return {"kind": "cleared", "verdict": verdict,
                        "stall_edge": not still, "ts": ts}
        return None

    def _mfu_fast_locked(self, job: _Job) -> Optional[float]:
        """Ingestion-path MFU from the incrementally maintained fleet
        tokens/s sum (read paths recompute exactly via _mfu_locked)."""
        if not job.n_params or not job.peak_tflops or job.tok_s_sum <= 0:
            return None
        devices = max(1, job.device_count or 1)
        achieved = 6.0 * job.n_params * job.tok_s_sum / 1e12 / devices
        return achieved / job.peak_tflops

    def _mfu_locked(self, job: _Job) -> Optional[float]:
        if not job.n_params or not job.peak_tflops:
            return None
        devices = max(1, job.device_count or 1)
        tok_s = 0.0
        for h in job.hosts.values():
            if h.tokens and h.durs and h.med_dur > 0:
                tok_s += h.med_tok / h.med_dur
        if tok_s <= 0:
            return None
        achieved = 6.0 * job.n_params * tok_s / 1e12 / devices
        return achieved / job.peak_tflops

    def _fanout_edge(self, job_id: str, kind: str, ns: str, name: str,
                     edge: Dict[str, Any]) -> None:
        v = edge["verdict"]
        if self.metrics is not None and edge["kind"] == "flagged":
            self.metrics.train_straggler(job_id)
        if self.flight is not None:
            if edge["kind"] == "flagged":
                detail = (f"host {v['host']} {v['skew']:.2f}x fleet "
                          f"median for "
                          f"{self.straggler_steps} steps "
                          f"(since step {v['first_slow_step']})")
            else:
                detail = (f"host {v['host']} recovered at step "
                          f"{v['cleared_step']}")
            self.flight.record(kind, ns, name, "straggler", detail,
                               host=v["host"], edge=edge["kind"],
                               skew=v["skew"])
        if self.goodput is not None and edge["stall_edge"]:
            self.goodput.set_stalled(kind, ns, name,
                                     edge["kind"] == "flagged",
                                     ts=edge["ts"])

    # -- read side ---------------------------------------------------------

    def jobs(self) -> List[str]:
        with self._lock:
            return list(self._jobs)

    def stragglers(self, job_id: Optional[str] = None
                   ) -> List[Dict[str, Any]]:
        """All verdicts (open and cleared), oldest first."""
        with self._lock:
            out: List[Dict[str, Any]] = []
            for jid, job in self._jobs.items():
                if job_id is not None and jid != job_id:
                    continue
                for v in job.verdicts:
                    out.append(dict(v, job=jid))
            return out

    def to_dict(self) -> Dict[str, Any]:
        """/debug/steps index: one summary row per job."""
        with self._lock:
            jobs = []
            for jid, job in self._jobs.items():
                fleet = self._fleet_median_locked(job)
                worst = 0.0
                last_step = 0
                for h in job.hosts.values():
                    med = quantile(h.durs, 0.5) if h.durs else 0.0
                    if fleet > 0:
                        worst = max(worst, med / fleet)
                    last_step = max(last_step, h.last_step)
                jobs.append({
                    "job": jid,
                    "hosts": len(job.hosts),
                    "last_step": last_step,
                    "fleet_median_s": round(fleet, 6),
                    "max_skew_ratio": round(worst, 4),
                    "stragglers": [v["host"] for v in job.verdicts
                                   if v["cleared_step"] is None],
                    "mfu": self._mfu_locked(job),
                })
            return {"jobs": jobs}

    def job_doc(self, job_id: str) -> Optional[Dict[str, Any]]:
        """/debug/steps/<job>: per-host windowed distributions + the
        verdict ring."""
        with self._lock:
            job = self._jobs.get(job_id)
            if job is None:
                return None
            fleet = self._fleet_median_locked(job)
            hosts = []
            for hid, h in job.hosts.items():
                durs = list(h.durs)
                med = quantile(durs, 0.5) if durs else 0.0
                tok_s = 0.0
                if h.tokens and med > 0:
                    tok_s = quantile(h.tokens, 0.5) / med
                wait = quantile(h.waits, 0.5) if h.waits else 0.0
                hosts.append({
                    "host": hid,
                    "last_step": h.last_step,
                    "last_ts": h.last_ts,
                    "steps_observed": h.steps_observed,
                    "window": len(durs),
                    "p50_s": round(med, 6),
                    "p90_s": round(quantile(durs, 0.9), 6) if durs
                    else 0.0,
                    "mean_s": round(sum(durs) / len(durs), 6) if durs
                    else 0.0,
                    "tokens_per_sec": round(tok_s, 2),
                    "collective_wait_p50_s": round(wait, 6),
                    "skew_ratio": round(med / fleet, 4) if fleet > 0
                    else 0.0,
                    "consecutive_slow": h.consecutive_slow,
                    "straggler": h.flagged,
                })
            return {
                "job": job_id,
                "fleet_median_s": round(fleet, 6),
                "mfu": self._mfu_locked(job),
                "straggler_ratio": self.straggler_ratio,
                "straggler_steps": self.straggler_steps,
                "hosts": hosts,
                "verdicts": [dict(v) for v in job.verdicts],
            }


class NoopStepTracker:
    """Surface-compatible zero: the benchmark's overhead leg swaps this
    in for the real tracker on the same seeded run (gated < 5%)."""

    metrics = None
    flight = None
    goodput = None

    def observe(self, *args, **kwargs) -> None:
        return None

    def observe_fleet_step(self, *args, **kwargs) -> None:
        return None

    def jobs(self) -> List[str]:
        return []

    def stragglers(self, job_id=None) -> List[Dict[str, Any]]:
        return []

    def to_dict(self) -> Dict[str, Any]:
        return {"jobs": []}

    def job_doc(self, job_id) -> Optional[Dict[str, Any]]:
        return None


NOOP_STEPS = NoopStepTracker()
