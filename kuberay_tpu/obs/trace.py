"""Causal reconcile tracing: explicit trace-context propagation.

The control plane's north-star metric is slice-ready latency, but a
histogram only says *that* a slice took N seconds.  This module says
*where* the time went, Dapper-style: a :class:`TraceContext` is minted
when a watch event enters ``Manager._on_event`` (via ``enqueue``),
carried through ``_pop``/``_process`` and into controller store writes
and FakeKubelet actions, producing parent-linked spans:

- ``chain:<kind>/<ns>/<name>`` — the root span of an object's reconcile
  chain (open-ended; its end extends as children finish);
- ``queue-wait`` — from when a key was (re)scheduled (including timed
  requeue backoff) to when a worker picked it up;
- ``reconcile`` — one reconciler invocation, with its outcome
  (ok / conflict / error / requeue-after);
- ``store-write`` — a controller's status/spec write;
- ``pod-start`` — pod creation to Running (recorded by FakeKubelet
  against the owning CR's chain);
- ``slice-ready`` — first pod creation of a slice to all hosts Running
  (the north-star decomposition anchor).

Everything is observational: the tracer never touches the store, the
rng, or the clock's state, so a chaos-sim replay hash is byte-identical
with tracing on and off (the tier-1 contract in tests/test_obs_trace.py).

``NOOP_TRACER`` is the default everywhere a ``tracer`` parameter is
accepted — annotations cost one attribute lookup when tracing is off.
Span/trace ids come from a plain counter (not uuid) so traces of a
deterministic sim run are themselves deterministic.
"""

from __future__ import annotations

import itertools
import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, Iterator, List, Optional, Tuple

Key = Tuple[str, str, str]          # (kind, namespace, name)


class TraceContext:
    """The propagation token: which trace, and which span to parent new
    children under.  Minted per reconcile-chain key; carried implicitly
    through the manager queue (keyed maps) and a thread-local stack for
    code running inside a reconcile."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: str, span_id: str):
        self.trace_id = trace_id
        self.span_id = span_id

    def __repr__(self):
        return f"TraceContext({self.trace_id}, {self.span_id})"

    # -- cross-process propagation (serve data plane) ----------------------

    def to_traceparent(self) -> str:
        """W3C-style traceparent header: version 00, sampled flag 01.
        The ids are this tracer's deterministic counter ids rather than
        random hex, so a traced sim/bench run replays identically."""
        return f"00-{self.trace_id}-{self.span_id}-01"

    #: Conservative header-size cap: a real traceparent is ~55 bytes;
    #: anything past this is garbage and not worth parsing.
    _MAX_HEADER_LEN = 200

    @classmethod
    def from_traceparent(cls, header: Optional[str]
                         ) -> Optional["TraceContext"]:
        """Parse a traceparent header into the remote parent context;
        malformed or absent headers yield None (the request simply runs
        untraced — propagation must never fail a request).  Strict on
        shape: exactly 4 fields, version exactly ``00``, bounded total
        length, ids lowercase alphanumeric (covering both W3C hex ids
        and this tracer's ``t000001``/``s000002`` counter ids)."""
        if not header:
            return None
        text = str(header).strip()
        if len(text) > cls._MAX_HEADER_LEN:
            return None
        parts = text.split("-")
        if len(parts) != 4 or parts[0] != "00":
            return None
        _, trace_id, span_id, _flags = parts
        for field in (trace_id, span_id):
            if not 1 <= len(field) <= 64:
                return None
            if not all(c.isascii() and (c.isdigit() or c.islower())
                       for c in field):
                return None
        return cls(trace_id, span_id)


class Span:
    """One timed operation.  ``end is None`` means still open (only the
    chain roots stay open; everything else is recorded at finish)."""

    __slots__ = ("trace_id", "span_id", "parent_id", "name",
                 "start", "end", "attrs", "status", "error")

    def __init__(self, trace_id: str, span_id: str, parent_id: str,
                 name: str, start: float, end: Optional[float] = None,
                 attrs: Optional[Dict[str, Any]] = None,
                 status: str = "ok", error: str = ""):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.start = start
        self.end = end
        self.attrs = attrs or {}
        self.status = status
        self.error = error

    @property
    def duration(self) -> Optional[float]:
        return None if self.end is None else self.end - self.start

    def to_dict(self) -> Dict[str, Any]:
        out = {"trace_id": self.trace_id, "span_id": self.span_id,
               "parent_id": self.parent_id, "name": self.name,
               "start": self.start, "end": self.end,
               "duration": self.duration, "status": self.status,
               "attrs": dict(self.attrs)}
        if self.error:
            out["error"] = self.error
        return out


class SpanStore:
    """Bounded in-memory span sink with tail-sampling retention: once
    ``max_spans`` is exceeded, fast successful spans are dropped first —
    error/shed spans (status != ok), still-open spans, and the slowest
    decile of durations survive longest, so the traces a p99 exemplar
    points at are the ones still inspectable at /debug/traces.  Eviction
    is counted; tracing must never become the memory leak it exists to
    debug."""

    def __init__(self, max_spans: int = 8192):
        self.max_spans = max_spans
        self._lock = threading.Lock()
        self._spans: List[Span] = []
        self._dropped = 0

    def add(self, span: Span) -> None:
        with self._lock:
            self._spans.append(span)
            overflow = len(self._spans) - self.max_spans
            if overflow > 0:
                # Evict in amortized batches: one O(n log n) retention
                # pass per ~max/16 adds instead of per add.
                self._evict_locked(max(overflow, self.max_spans // 16))

    def _evict_locked(self, n: int) -> None:
        """Drop ``n`` spans, least interesting first: closed ok spans
        below the p90 duration, then closed ok spans oldest-first, then
        closed errors, then (only under extreme pressure) open spans."""
        spans = self._spans
        ok = [i for i, s in enumerate(spans)
              if s.end is not None and s.status == "ok"]
        durs = sorted(spans[i].duration for i in ok)
        thresh = durs[(len(durs) * 9) // 10] if len(durs) >= 10 \
            else float("inf")
        victims = [i for i in ok if spans[i].duration < thresh][:n]
        if len(victims) < n:
            chosen = set(victims)
            rest = [i for i in range(len(spans)) if i not in chosen]
            rest.sort(key=lambda i: (spans[i].end is None,
                                     spans[i].status != "ok", i))
            victims.extend(rest[:n - len(victims)])
        for i in sorted(victims, reverse=True):
            del spans[i]
        self._dropped += len(victims)

    @property
    def dropped(self) -> int:
        with self._lock:
            return self._dropped

    def __len__(self) -> int:
        with self._lock:
            return len(self._spans)

    def stats(self) -> Dict[str, int]:
        """Retention envelope for the /debug/traces response: current
        span count, the cap, and the lifetime eviction count — so a
        truncated profile is detectable instead of silently biased."""
        with self._lock:
            return {"spans": len(self._spans),
                    "max_spans": self.max_spans,
                    "dropped": self._dropped}

    def export(self, trace_id: Optional[str] = None) -> List[Dict[str, Any]]:
        with self._lock:
            spans = list(self._spans)
        return [s.to_dict() for s in spans
                if trace_id is None or s.trace_id == trace_id]

    def trace_ids(self) -> List[str]:
        with self._lock:
            seen: Dict[str, None] = {}
            for s in self._spans:
                seen.setdefault(s.trace_id, None)
        return list(seen)


def span_tree(spans: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Nest exported span dicts by parent link: returns the roots, each
    with a ``children`` list (sorted by start time).  Orphans whose
    parent was dropped from the bounded store surface as roots."""
    by_id = {s["span_id"]: {**s, "children": []} for s in spans}
    roots = []
    for node in by_id.values():
        parent = by_id.get(node["parent_id"])
        if parent is not None and parent is not node:
            parent["children"].append(node)
        else:
            roots.append(node)
    def sort(nodes):
        nodes.sort(key=lambda n: (n["start"], n["span_id"]))
        for n in nodes:
            sort(n["children"])
    sort(roots)
    return roots


class _SpanHandle:
    """The mutable in-flight span yielded by ``tracer.span(...)`` /
    ``tracer.reconcile(...)``: annotate with ``set``, mark failure with
    ``error`` — finalized into an immutable :class:`Span` on exit."""

    __slots__ = ("ctx", "span_id", "name", "start", "attrs",
                 "status", "error_message")

    def __init__(self, ctx: TraceContext, span_id: str, name: str,
                 start: float):
        self.ctx = ctx
        self.span_id = span_id
        self.name = name
        self.start = start
        self.attrs: Dict[str, Any] = {}
        self.status = "ok"
        self.error_message = ""

    def set(self, **attrs) -> None:
        self.attrs.update(attrs)

    def error(self, message: str) -> None:
        self.status = "error"
        self.error_message = message


class _NoopSpan:
    __slots__ = ()

    def set(self, **attrs) -> None:
        pass

    def error(self, message: str) -> None:
        pass


_NOOP_SPAN = _NoopSpan()


class NoopTracer:
    """The default tracer: every hook is a no-op, every span a shared
    sentinel — controllers and the manager annotate unconditionally and
    pay nothing when tracing is off."""

    enabled = False

    def context_for(self, key: Key) -> Optional[TraceContext]:
        return None

    def queued(self, key: Key, ts: Optional[float] = None,
               delayed: bool = False) -> None:
        pass

    def dequeued(self, key: Key, ts: Optional[float] = None) -> None:
        pass

    @contextmanager
    def reconcile(self, key: Key, **attrs) -> Iterator[_NoopSpan]:
        yield _NOOP_SPAN

    @contextmanager
    def span(self, name: str, **attrs) -> Iterator[_NoopSpan]:
        yield _NOOP_SPAN

    def record_error(self, scope: str, message: str) -> None:
        pass

    def record_for_key(self, key: Key, name: str, start: float, end: float,
                       **attrs) -> None:
        pass

    def start_request(self, name: str, ts: Optional[float] = None,
                      **attrs) -> Optional[TraceContext]:
        return None

    def finish_request(self, ctx: Optional[TraceContext],
                       ts: Optional[float] = None, status: str = "ok",
                       error: str = "") -> None:
        pass

    def record_span(self, ctx: Optional[TraceContext], name: str,
                    start: float, end: float, parent_id: str = "",
                    status: str = "ok", error: str = "",
                    **attrs) -> None:
        pass

    def current(self) -> Optional[TraceContext]:
        return None

    def export(self, trace_id: Optional[str] = None) -> List[Dict[str, Any]]:
        return []


NOOP_TRACER = NoopTracer()


class Tracer(NoopTracer):
    """The real tracer.  One *chain* (= one trace) per reconcile key:
    the chain root is an open span that extends as children finish, so
    every queue-wait/reconcile/store-write/pod-start of an object links
    into one causal timeline.  Chains are LRU-bounded; the span sink is
    size-bounded (:class:`SpanStore`)."""

    enabled = True

    def __init__(self, clock=None, max_spans: int = 8192,
                 max_chains: int = 2048):
        # ``clock``: duck-typed .now() (the sim passes its VirtualClock);
        # defaults to wall time.
        self._now = clock.now if clock is not None else time.time
        self.store = SpanStore(max_spans)
        self._lock = threading.Lock()
        self._ids = itertools.count(1)
        self._chains: Dict[Key, TraceContext] = {}      # insertion = LRU
        self._roots: Dict[str, Span] = {}               # root span_id -> Span
        self._pending: Dict[Key, Tuple[float, bool]] = {}
        self._max_chains = max_chains
        self._tls = threading.local()

    # -- context propagation ----------------------------------------------

    def context_for(self, key: Key) -> TraceContext:
        """The chain context for a reconcile key, minted on first use."""
        with self._lock:
            ctx = self._chains.get(key)
            if ctx is not None:
                return ctx
            tid = f"t{next(self._ids):06d}"
            sid = f"s{next(self._ids):06d}"
            root = Span(tid, sid, "", "chain:%s/%s/%s" % key,
                        start=self._now())
            self._roots[sid] = root
            ctx = TraceContext(tid, sid)
            self._chains[key] = ctx
            if len(self._chains) > self._max_chains:
                old_key = next(iter(self._chains))
                old = self._chains.pop(old_key)
                self._roots.pop(old.span_id, None)
                self._pending.pop(old_key, None)
        self.store.add(root)
        return ctx

    def _next_span_id(self) -> str:
        with self._lock:
            return f"s{next(self._ids):06d}"

    def _extend_root(self, parent_id: str, end: float) -> None:
        with self._lock:
            root = self._roots.get(parent_id)
            if root is not None and (root.end is None or end > root.end):
                root.end = end

    def _finish(self, ctx: Optional[TraceContext], parent_id: str,
                name: str, start: float, end: float,
                attrs: Optional[Dict[str, Any]] = None,
                status: str = "ok", error: str = "") -> Span:
        span = Span(ctx.trace_id if ctx else "", self._next_span_id(),
                    parent_id, name, start, end, attrs, status, error)
        self.store.add(span)
        if parent_id:
            self._extend_root(parent_id, end)
        return span

    # -- manager hooks ------------------------------------------------------

    def queued(self, key: Key, ts: Optional[float] = None,
               delayed: bool = False) -> None:
        """A key entered the work queue (or a timed requeue was
        scheduled).  The EARLIEST pending instant wins — dedup keeps the
        first cause, and the eventual queue-wait span covers any backoff
        delay (that wait is real slice-ready latency)."""
        ts = self._now() if ts is None else ts
        self.context_for(key)
        with self._lock:
            self._pending.setdefault(key, (ts, delayed))

    def dequeued(self, key: Key, ts: Optional[float] = None) -> None:
        """A worker picked the key up: emit the queue-wait span."""
        ts = self._now() if ts is None else ts
        with self._lock:
            ctx = self._chains.get(key)
            pending = self._pending.pop(key, None)
        if ctx is None or pending is None:
            return
        start, delayed = pending
        self._finish(ctx, ctx.span_id, "queue-wait", start, ts,
                     attrs={"delayed": delayed} if delayed else None)

    @contextmanager
    def reconcile(self, key: Key, **attrs) -> Iterator[_SpanHandle]:
        """The span around one reconciler invocation; installs itself as
        the thread-local current span so controller ``span()`` calls and
        ``record_error`` nest under it."""
        ctx = self.context_for(key)
        handle = _SpanHandle(ctx, self._next_span_id(), "reconcile",
                             self._now())
        handle.attrs.update(attrs)
        stack = self._stack()
        stack.append(handle)
        try:
            yield handle
        except BaseException as e:
            handle.error(f"{type(e).__name__}: {e}")
            raise
        finally:
            stack.pop()
            self._finalize(handle, parent_id=ctx.span_id)

    @contextmanager
    def span(self, name: str, **attrs) -> Iterator[_SpanHandle]:
        """A child span under the thread-local current span (a
        controller's store-write inside a reconcile); standalone code
        gets a trace-less root span."""
        parent = self._stack_top()
        ctx = parent.ctx if parent is not None else None
        parent_id = parent.span_id if parent is not None else ""
        handle = _SpanHandle(ctx, self._next_span_id(), name, self._now())
        handle.attrs.update(attrs)
        stack = self._stack()
        stack.append(handle)
        try:
            yield handle
        except BaseException as e:
            handle.error(f"{type(e).__name__}: {e}")
            raise
        finally:
            stack.pop()
            self._finalize(handle, parent_id=parent_id)

    def _finalize(self, handle: _SpanHandle, parent_id: str) -> None:
        end = self._now()
        span = Span(handle.ctx.trace_id if handle.ctx else "",
                    handle.span_id, parent_id, handle.name, handle.start,
                    end, handle.attrs, handle.status, handle.error_message)
        self.store.add(span)
        root_id = handle.ctx.span_id if handle.ctx else parent_id
        if root_id:
            self._extend_root(root_id, end)

    # -- annotation from anywhere ------------------------------------------

    def record_error(self, scope: str, message: str) -> None:
        """Mark the current span as failed (the span-error half of the
        ``requeue-observability`` lint contract); without an active span
        a zero-duration error span is recorded so the failure is never
        silently dropped."""
        top = self._stack_top()
        if top is not None:
            top.error(f"{scope}: {message}")
            return
        now = self._now()
        self._finish(None, "", f"error:{scope}", now, now,
                     status="error", error=message)

    def record_for_key(self, key: Key, name: str, start: float, end: float,
                       **attrs) -> None:
        """Record an externally-measured span (pod-start, slice-ready)
        against a chain's trace — the seam for components that act on a
        key's behalf without running inside its reconcile (FakeKubelet)."""
        ctx = self.context_for(key)
        self._finish(ctx, ctx.span_id, name, start, end, attrs=attrs)

    # -- per-request serve tracing ------------------------------------------
    #
    # Reconcile chains are keyed (one trace per object, LRU-bounded);
    # serve requests are the opposite shape — a fresh trace per request,
    # recorded with EXPLICIT contexts because the gateway handler thread,
    # the replica HTTP thread and the engine loop never share a
    # thread-local stack.  The context crosses the process boundary as a
    # traceparent header (TraceContext.to_traceparent).

    def start_request(self, name: str, ts: Optional[float] = None,
                      **attrs) -> TraceContext:
        """Mint a fresh trace with an open root span (the serve-request
        envelope); close it with :meth:`finish_request`."""
        ts = self._now() if ts is None else ts
        with self._lock:
            tid = f"t{next(self._ids):06d}"
            sid = f"s{next(self._ids):06d}"
            root = Span(tid, sid, "", name, start=ts,
                        attrs=dict(attrs) if attrs else None)
            self._roots[sid] = root
        self.store.add(root)
        return TraceContext(tid, sid)

    def finish_request(self, ctx: Optional[TraceContext],
                       ts: Optional[float] = None, status: str = "ok",
                       error: str = "") -> None:
        """Close a request's root span (idempotent; no-op for remote or
        absent contexts)."""
        if ctx is None:
            return
        ts = self._now() if ts is None else ts
        with self._lock:
            root = self._roots.pop(ctx.span_id, None)
            if root is None:
                return
            if root.end is None or ts > root.end:
                root.end = ts
            if status != "ok":
                root.status = status
                root.error = error

    def record_span(self, ctx: Optional[TraceContext], name: str,
                    start: float, end: float, parent_id: str = "",
                    status: str = "ok", error: str = "",
                    **attrs) -> None:
        """Record a completed span under an explicit context — the
        cross-thread seam the serve path uses (gateway-queue,
        route-decision, forward on the gateway; engine-queue, prefill,
        decode, kv-alloc on the replica, parented on the traceparent's
        remote span id)."""
        if ctx is None:
            return
        self._finish(ctx, parent_id or ctx.span_id, name, start, end,
                     attrs=attrs or None, status=status, error=error)

    def current(self) -> Optional[TraceContext]:
        top = self._stack_top()
        return top.ctx if top is not None else None

    def _stack(self) -> List[_SpanHandle]:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def _stack_top(self) -> Optional[_SpanHandle]:
        stack = getattr(self._tls, "stack", None)
        return stack[-1] if stack else None

    # -- export -------------------------------------------------------------

    def export(self, trace_id: Optional[str] = None) -> List[Dict[str, Any]]:
        return self.store.export(trace_id)

    def tree(self, trace_id: Optional[str] = None) -> List[Dict[str, Any]]:
        return span_tree(self.export(trace_id))
