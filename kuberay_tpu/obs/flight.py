"""Per-CR flight recorder: a fixed-size ring buffer of what happened.

The black-box counterpart to the tracer: where spans answer "where did
the time go", the flight recorder answers "what sequence of events
produced this state" for one object — watch deliveries, state
transitions, recorded K8s Events, optimistic-concurrency conflicts,
reconcile errors and requeues — keyed by (kind, namespace, name) and
queryable as a timeline (``/debug/flight/<kind>/<ns>/<name>``).

Bounded twice: ``capacity`` records per object (deque ring), and
``max_objects`` tracked objects (LRU eviction), so a churning cluster
can never grow it past a fixed footprint.  Purely observational — it
reads the clock and nothing else, so recording under simulation leaves
the replay hash untouched.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional, Tuple

Key = Tuple[str, str, str]          # (kind, namespace, name)


class FlightRecorder:
    def __init__(self, capacity: int = 256, max_objects: int = 2048,
                 clock=None, tracer=None):
        self.capacity = capacity
        self.max_objects = max_objects
        self._now = clock.now if clock is not None else time.time
        # With a tracer, records made inside an active span are stamped
        # with its trace_id — a flight timeline row joins straight to
        # its spans during forensics.  Still purely observational.
        self._tracer = tracer
        self._lock = threading.Lock()
        self._buffers: "OrderedDict[Key, deque]" = OrderedDict()
        self._last_state: Dict[Key, str] = {}

    # -- recording ----------------------------------------------------------

    def record(self, kind: str, namespace: str, name: str, rtype: str,
               detail: str = "", **attrs) -> None:
        """Append one record to the object's ring.  ``rtype`` is the
        record class ("watch" | "state" | "event" | "conflict" |
        "error" | "requeue" | free-form)."""
        rec: Dict[str, Any] = {"ts": self._now(), "type": rtype,
                               "detail": detail}
        rec.update(attrs)
        if self._tracer is not None:
            ctx = self._tracer.current()
            if ctx is not None:
                rec["trace_id"] = ctx.trace_id
        key = (kind, namespace, name)
        with self._lock:
            buf = self._buffers.get(key)
            if buf is None:
                buf = deque(maxlen=self.capacity)
                self._buffers[key] = buf
                if len(self._buffers) > self.max_objects:
                    old_key, _ = self._buffers.popitem(last=False)
                    self._last_state.pop(old_key, None)
            else:
                self._buffers.move_to_end(key)
            buf.append(rec)

    def observe_event(self, ev) -> None:
        """Fold a store watch Event into the recorder: K8s Event objects
        land on their involvedObject's timeline; everything else records
        the delivery itself plus a synthesized state-transition record
        when status.state/phase changed since the last delivery."""
        obj = ev.obj
        md = obj.get("metadata", {})
        if ev.type == "BOOKMARK":
            return   # progress marker: no object, nothing to record
        if ev.kind == "Event":
            io = obj.get("involvedObject", {}) or {}
            self.record(io.get("kind", "") or "", io.get("namespace",
                        md.get("namespace", "default")),
                        io.get("name", "") or "", "event",
                        f"{obj.get('type', '')}/{obj.get('reason', '')}: "
                        f"{obj.get('message', '')}"[:300])
            return
        ns = md.get("namespace", "default")
        name = md.get("name", "")
        status = obj.get("status") or {}
        state = str(status.get("state") or
                    status.get("jobDeploymentStatus") or
                    status.get("serviceStatus") or
                    status.get("phase") or "")
        self.record(ev.kind, ns, name, "watch", ev.type,
                    rv=md.get("resourceVersion"))
        key = (ev.kind, ns, name)
        with self._lock:
            prev = self._last_state.get(key, "")
            changed = state != prev
            if changed:
                self._last_state[key] = state
        if changed:
            self.record(ev.kind, ns, name, "state",
                        f"{prev or '<none>'} -> {state or '<none>'}")

    # -- querying -----------------------------------------------------------

    def timeline(self, kind: str, namespace: str, name: str
                 ) -> List[Dict[str, Any]]:
        """Snapshot of one object's ring.  Record dicts are COPIED, not
        aliased: the debug/incident paths serialize these outside the
        lock, and a concurrent ``record()`` (ring rotation mutates the
        deque; attrs land on the dict at append time) must not race or
        mutate an in-flight JSON response."""
        with self._lock:
            buf = self._buffers.get((kind, namespace, name))
            return [dict(r) for r in buf] if buf is not None else []

    def keys(self) -> List[Key]:
        with self._lock:
            return list(self._buffers)

    def to_dict(self) -> Dict[str, Any]:
        """Whole-recorder snapshot (sim failure reports).  Same copy
        contract as :meth:`timeline`."""
        with self._lock:
            items = [("%s/%s/%s" % k, [dict(r) for r in buf])
                     for k, buf in self._buffers.items()]
        return {key: records for key, records in items}
