"""Critical-path profiles and trace diffs over recorded spans.

The tracer (obs/trace.py) answers "where did THIS request's time go";
this module answers the aggregate and comparative forms — "where does
the fleet's time go, per span kind" and "what changed between two
builds" — the Canopy pattern (Kaldor et al., SOSP'17): turn raw spans
into per-component profiles that machines, not humans, compare.

Three layers:

- **Extractor** (:func:`trace_records`): walks one trace's spans and
  attributes every second of a root window (a ``serve-request`` root,
  a ``slice-ready`` chain segment, a bench ``train-step``) to exactly
  one span kind's *exclusive self time*.  The attribution is an
  interval sweep: the window is partitioned at every candidate span
  boundary and each elementary interval charges the **deepest**
  covering span (ties: latest start, then span id); intervals no
  descendant covers charge the root's own kind.  By construction the
  per-kind self times sum to the root duration exactly — the
  decomposition invariant tests/test_profile.py holds the line on —
  even when siblings overlap (a naive duration-minus-children
  subtraction double-counts there).
- **Aggregator** (:func:`aggregate` / :func:`profile_spans`): folds
  many per-trace records into per-span-kind percentile profiles
  (interpolated quantiles from utils/quantiles.py) grouped by trace
  shape (``serve`` vs ``control-plane``), with self-time fractions
  that sum to 1.0 per shape.  Served live at ``/debug/profile`` and
  exported as a versioned JSON artifact (``tpu-profile/v1``) —
  byte-identical across re-runs of a seeded sim, because the virtual
  clock and counter span ids leave no wall-clock residue.
- **Diff engine** (:func:`diff_profiles`): compares baseline vs
  candidate profiles per (shape, kind) behind a noise gate — both
  sides need ``min_count`` samples and the relative change must clear
  ``rel_threshold`` (plus an optional absolute ``min_delta_s``) — so
  a regression verdict names the guilty span kind instead of "p99
  went up".  The upgrade ramp attaches this diff to every
  promote/rollback audit record; tools/bench_serve.sh runs it against
  the committed baseline artifact.

Like everything else in obs/, all of it is observational: pure
functions over exported span dicts, never touching the store, the rng
or the clock — mounting the profiler in the sim leaves replay hashes
untouched.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from kuberay_tpu.utils.quantiles import quantile

PROFILE_SCHEMA = "tpu-profile/v1"
DIFF_SCHEMA = "tpu-profile-diff/v1"

#: Root span name -> trace shape.  ``serve-request`` roots are the
#: per-request serve shape; ``slice-ready`` spans anchor the
#: control-plane shape (each one is a window over its reconcile
#: chain).  Callers profile other shapes by passing their own map
#: (bench.py uses {"train-step": "train"}).
DEFAULT_ROOTS: Dict[str, str] = {
    "serve-request": "serve",
    "slice-ready": "control-plane",
}

#: Comparison metric the diff engine reads from each kind's profile.
DIFF_METRIC = "p90_s"


def span_kind(name: str) -> str:
    """Normalize a span name to its kind: chain roots collapse to
    ``chain``, ad-hoc error spans to ``error``, everything else (the
    fixed serve/control-plane vocabulary) is already the kind."""
    if name.startswith("chain:"):
        return "chain"
    if name.startswith("error:"):
        return "error"
    return name


def _round(x: float) -> float:
    # Tidy artifact values; 9 decimals keeps ns resolution while
    # avoiding 0.30000000000000004-style float noise in diffs read by
    # humans.  Determinism does not depend on this — identical inputs
    # produce identical floats either way.
    return round(x, 9)


def _depths(spans: List[Dict[str, Any]]) -> Dict[str, int]:
    """Tree depth per span_id from parent links (orphans and roots are
    depth 0); cycle-safe because the store can hold orphaned links
    after eviction."""
    by_id = {s["span_id"]: s for s in spans}
    depths: Dict[str, int] = {}

    def depth(sid: str) -> int:
        d = depths.get(sid)
        if d is not None:
            return d
        depths[sid] = 0          # breaks cycles / missing parents
        parent = by_id.get(sid, {}).get("parent_id", "")
        if parent and parent in by_id and parent != sid:
            depths[sid] = depth(parent) + 1
        return depths[sid]

    for s in spans:
        depth(s["span_id"])
    return depths


def _window_self_times(root: Dict[str, Any],
                       candidates: List[Dict[str, Any]],
                       depths: Dict[str, int]) -> Dict[str, float]:
    """Exclusive self time per span kind over the root's window.

    Interval sweep: cut [root.start, root.end] at every candidate
    boundary; each elementary interval charges the deepest covering
    candidate (ties: latest start, then span id), or the root's own
    kind when nothing covers it.  The returned values partition the
    window — sum(values) == root duration up to float addition."""
    w0, w1 = root["start"], root["end"]
    root_kind = span_kind(root["name"])
    if w1 is None or w1 <= w0:
        return {root_kind: 0.0}
    live = [s for s in candidates
            if s["span_id"] != root["span_id"] and s["end"] is not None
            and s["end"] > w0 and s["start"] < w1]
    cuts = {w0, w1}
    for s in live:
        cuts.add(max(w0, s["start"]))
        cuts.add(min(w1, s["end"]))
    edges = sorted(cuts)
    self_s: Dict[str, float] = {}
    for a, b in zip(edges, edges[1:]):
        if b <= a:
            continue
        best = None
        best_key: Tuple[int, float, str] = (-1, 0.0, "")
        for s in live:
            if s["start"] <= a and s["end"] >= b:
                key = (depths.get(s["span_id"], 0), s["start"],
                       s["span_id"])
                if key > best_key:
                    best, best_key = s, key
        kind = span_kind(best["name"]) if best is not None else root_kind
        self_s[kind] = self_s.get(kind, 0.0) + (b - a)
    return self_s or {root_kind: 0.0}


def trace_records(spans: List[Dict[str, Any]],
                  roots: Optional[Dict[str, str]] = None
                  ) -> List[Dict[str, Any]]:
    """Per-window critical-path records from exported span dicts.

    One record per closed span whose name is in ``roots``; candidates
    for its window are the other spans of the same trace.  For the
    serve shape that is the whole request tree; for a ``slice-ready``
    window it includes chain siblings (pod-start, queue-wait,
    reconcile) that overlap the window — depth decides attribution,
    uncovered time stays with ``slice-ready`` itself."""
    roots = DEFAULT_ROOTS if roots is None else roots
    by_trace: Dict[str, List[Dict[str, Any]]] = {}
    for s in spans:
        by_trace.setdefault(s["trace_id"], []).append(s)
    records: List[Dict[str, Any]] = []
    for trace_id in sorted(by_trace):
        tspans = by_trace[trace_id]
        depths = _depths(tspans)
        for s in sorted(tspans, key=lambda s: (s["start"], s["span_id"])):
            if s["name"] not in roots or s["end"] is None:
                continue
            self_s = _window_self_times(s, tspans, depths)
            records.append({
                "trace_id": trace_id,
                "root_span_id": s["span_id"],
                "shape": roots[s["name"]],
                "duration_s": max(0.0, s["end"] - s["start"]),
                "self_s": self_s,
            })
    return records


def aggregate(records: List[Dict[str, Any]],
              meta: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """Fold per-window records into the ``tpu-profile/v1`` document:
    per shape, per span kind — sample count, total/mean self seconds,
    interpolated p50/p90/p99 self time, and the fraction of the
    shape's total wall time (fractions sum to 1.0 per shape, because
    each record's self times partition its window)."""
    by_shape: Dict[str, List[Dict[str, Any]]] = {}
    for rec in records:
        by_shape.setdefault(rec["shape"], []).append(rec)
    shapes: Dict[str, Any] = {}
    for shape in sorted(by_shape):
        recs = by_shape[shape]
        total = sum(r["duration_s"] for r in recs)
        durs = [r["duration_s"] for r in recs]
        kinds: Dict[str, Any] = {}
        for kind in sorted({k for r in recs for k in r["self_s"]}):
            samples = [r["self_s"][kind] for r in recs
                       if kind in r["self_s"]]
            kinds[kind] = {
                "count": len(samples),
                "total_s": _round(sum(samples)),
                "fraction": _round(sum(samples) / total) if total > 0
                else 0.0,
                "mean_s": _round(sum(samples) / len(samples)),
                "p50_s": _round(quantile(samples, 0.50)),
                "p90_s": _round(quantile(samples, 0.90)),
                "p99_s": _round(quantile(samples, 0.99)),
            }
        shapes[shape] = {
            "traces": len(recs),
            "total_s": _round(total),
            "duration_p50_s": _round(quantile(durs, 0.50)),
            "duration_p90_s": _round(quantile(durs, 0.90)),
            "duration_p99_s": _round(quantile(durs, 0.99)),
            "kinds": kinds,
        }
    doc: Dict[str, Any] = {"schema": PROFILE_SCHEMA, "shapes": shapes}
    if meta:
        doc["meta"] = dict(meta)
    return doc


def profile_spans(spans: List[Dict[str, Any]],
                  roots: Optional[Dict[str, str]] = None,
                  meta: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """Extractor + aggregator in one call: exported span dicts in,
    ``tpu-profile/v1`` document out."""
    return aggregate(trace_records(spans, roots), meta=meta)


# -- trace-diff engine ------------------------------------------------------


def diff_profiles(baseline: Dict[str, Any], candidate: Dict[str, Any],
                  *, min_count: int = 5, rel_threshold: float = 0.25,
                  min_delta_s: float = 0.0,
                  metric: str = DIFF_METRIC) -> Dict[str, Any]:
    """Compare two profiles per (shape, kind) behind a noise gate.

    A (shape, kind) pair is judged only when both sides carry at least
    ``min_count`` samples — otherwise it lands in ``skipped`` with the
    reason.  A judged pair regresses when the candidate's ``metric``
    grew by more than ``rel_threshold`` relatively AND ``min_delta_s``
    absolutely (improvements mirror that).  Regressions are sorted
    worst-absolute-delta first, so ``regressions[0]["kind"]`` names
    the guilty component."""
    regressions: List[Dict[str, Any]] = []
    improvements: List[Dict[str, Any]] = []
    skipped: List[Dict[str, Any]] = []
    b_shapes = baseline.get("shapes", {})
    c_shapes = candidate.get("shapes", {})
    for shape in sorted(set(b_shapes) | set(c_shapes)):
        bk = b_shapes.get(shape, {}).get("kinds", {})
        ck = c_shapes.get(shape, {}).get("kinds", {})
        for kind in sorted(set(bk) | set(ck)):
            b, c = bk.get(kind), ck.get(kind)
            if b is None or c is None:
                skipped.append({"shape": shape, "kind": kind,
                                "reason": "missing-side"})
                continue
            n = min(b["count"], c["count"])
            if n < min_count:
                skipped.append({"shape": shape, "kind": kind,
                                "reason": f"samples {n} < {min_count}"})
                continue
            base, cand = b[metric], c[metric]
            delta = cand - base
            # Zero-baseline guard: a kind that cost nothing before and
            # something now is an arbitrarily large relative change —
            # clamp the denominator instead of dividing by zero.
            rel = delta / max(base, 1e-9)
            entry = {"shape": shape, "kind": kind, "metric": metric,
                     "baseline_s": base, "candidate_s": cand,
                     "delta_s": _round(delta), "rel_change": _round(rel),
                     "samples": n}
            if rel >= rel_threshold and delta >= max(min_delta_s, 0.0):
                regressions.append(entry)
            elif rel <= -rel_threshold and -delta >= max(min_delta_s, 0.0):
                improvements.append(entry)
    regressions.sort(key=lambda e: (-e["delta_s"], e["shape"], e["kind"]))
    improvements.sort(key=lambda e: (e["delta_s"], e["shape"], e["kind"]))
    return {
        "schema": DIFF_SCHEMA,
        "metric": metric,
        "gate": {"min_count": min_count, "rel_threshold": rel_threshold,
                 "min_delta_s": min_delta_s},
        "regressions": regressions,
        "improvements": improvements,
        "skipped": skipped,
    }


def worst_regression(diff: Optional[Dict[str, Any]]
                     ) -> Optional[Dict[str, Any]]:
    """The largest-absolute-delta regression of a diff, or None."""
    if not diff:
        return None
    regs = diff.get("regressions") or []
    return regs[0] if regs else None


def describe_regression(entry: Dict[str, Any]) -> str:
    """One human line naming the guilty span kind — rollback events
    and CLI verdicts both use it."""
    pct = entry["rel_change"] * 100.0
    return (f"{entry['kind']} {entry['metric']} self "
            f"{entry['baseline_s']:.4f}s -> {entry['candidate_s']:.4f}s "
            f"(+{pct:.0f}%)")


# -- live profiling (gateway hook + /debug/profile) -------------------------


class RequestProfiler:
    """The gateway's request-completion hook and the live profile
    source behind ``/debug/profile`` and the upgrade ramp's
    build-vs-build diff.

    The gateway calls :meth:`note` with each completed request's trace
    id and the backend that FINALLY served it (retries/failover can
    touch several backends' spans in one trace; the hook records the
    one that answered, so a per-backend profile never charges blue
    with green's retry debris).  The ring is bounded; snapshots read
    spans lazily from the tracer's store, so noting a request costs
    one deque append."""

    def __init__(self, tracer, capacity: int = 1024):
        self._tracer = tracer
        self._ring: "deque[Tuple[str, str]]" = deque(maxlen=capacity)
        self._lock = threading.Lock()

    def note(self, trace_id: str, backend: str = "none") -> None:
        if not trace_id:
            return
        with self._lock:
            self._ring.append((trace_id, backend))

    def completed(self, backend: Optional[str] = None) -> List[str]:
        """Noted trace ids, oldest first, optionally scoped to the
        backend that served them (deduplicated, order-preserving)."""
        with self._lock:
            pairs = list(self._ring)
        seen: Dict[str, None] = {}
        for tid, b in pairs:
            if backend is None or b == backend:
                seen.setdefault(tid, None)
        return list(seen)

    def snapshot(self, backend: Optional[str] = None,
                 meta: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        """Live profile document.  Unscoped snapshots cover everything
        in the span store (serve requests AND control-plane chains);
        ``backend=`` narrows to the serve traces that backend
        answered."""
        spans = self._tracer.export()
        if backend is None:
            return profile_spans(spans, meta=meta)
        ids = set(self.completed(backend))
        spans = [s for s in spans if s["trace_id"] in ids]
        return profile_spans(spans, roots={"serve-request": "serve"},
                             meta=meta)
