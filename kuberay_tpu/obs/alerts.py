"""Multi-window multi-burn-rate SLO alerting (Google SRE Workbook ch. 5).

The SLO machinery in ``controlplane/slo.py`` drives autoscaling verdicts
but never tells a human anything is burning.  This module closes that
gap: declarative :class:`SloSpec` objects (TTFT p99 target, availability
from shed/error counters, goodput-ratio floor per CR) are evaluated as
fast/slow burn rates over deltas of ``MetricsRegistry`` snapshots under
an injectable clock, firing into a bounded alert ring served at
``/debug/alerts``.

Burn rate is the unit-free core: with an objective of 99%, the error
budget is 1% of events; a burn rate of 14 means the window consumed
budget 14x faster than allowed.  Each spec is watched over two windows —
a short one that pages fast on sharp breaches and a long one that
catches slow leaks a short window dilutes away.  Alerts clear when the
breaching events age out of their window.

Everything is observational: the engine reads cumulative snapshots and
the clock, never the store or the rng, so evaluating under simulation
leaves the replay hash byte-identical (the same contract the tracer and
the goodput ledger obey).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

#: Record classes an alert ring entry carries in ``state``.
FIRING, RESOLVED = "firing", "resolved"


@dataclasses.dataclass(frozen=True)
class SloSpec:
    """One service-level objective, evaluated as burn rates.

    ``kind`` selects how cumulative (total, bad) event counts are read:

    - ``latency``: a histogram family/labels; bad = observations above
      ``threshold_s`` (align the threshold to a bucket boundary — the
      exposition only knows bucket-resolution truth);
    - ``availability``: bad = every series of ``bad_families`` plus the
      5xx-coded series of ``total_family``; total = ``total_family``;
    - ``gauge-floor``: each series of ``gauge_family`` contributes one
      synthetic event per evaluation tick, bad when the gauge sits below
      ``floor`` — "spent too much of the window unproductive".  With
      ``above=True`` the comparison inverts (bad when the gauge sits
      ABOVE ``floor``): the floor doubles as a ceiling for
      higher-is-worse gauges like the step skew ratio.
    """

    name: str
    kind: str                                  # latency|availability|gauge-floor
    objective: float = 0.99                    # good-event target (0..1)
    # latency
    metric: str = ""
    labels: Tuple[Tuple[str, str], ...] = ()
    threshold_s: float = 0.5
    # availability
    total_family: str = ""
    bad_families: Tuple[str, ...] = ()
    # Optional series scoping: only count total/bad series whose labels
    # are a superset of these pairs.  Lets one spec watch a single
    # backend of a labeled family (the upgrade gate scopes availability
    # to the green fleet this way) without a dedicated metric.
    series_labels: Tuple[Tuple[str, str], ...] = ()
    # gauge-floor
    gauge_family: str = ""
    floor: float = 0.5
    above: bool = False         # invert: bad when gauge > floor
    # windows (seconds) and their burn-rate thresholds
    fast_window_s: float = 300.0
    fast_burn: float = 14.0
    slow_window_s: float = 3600.0
    slow_burn: float = 6.0
    min_samples: int = 5

    @property
    def budget(self) -> float:
        return max(1e-9, 1.0 - self.objective)


def default_slos(ttft_target_s: float = 0.5,
                 availability: float = 0.99,
                 goodput_floor: float = 0.5,
                 straggler_skew: float = 1.5) -> List[SloSpec]:
    """The stock catalog the operator mounts (docs/observability.md):
    serve TTFT p99, serve availability, per-CR goodput-ratio floor, and
    per-(job, host) step-skew ceiling (the straggler microscope's alert
    face — its gauge labels carry the job's goodput key, so a firing
    series deep-links to the flight ring and the goodput ledger)."""
    return [
        SloSpec(name="serve-ttft", kind="latency",
                metric="tpu_serve_request_duration_seconds",
                labels=(("phase", "ttft"),), threshold_s=ttft_target_s,
                objective=0.99),
        SloSpec(name="serve-availability", kind="availability",
                total_family="tpu_gateway_requests_total",
                bad_families=("tpu_gateway_shed_total",),
                objective=availability),
        SloSpec(name="goodput-ratio", kind="gauge-floor",
                gauge_family="tpu_goodput_ratio", floor=goodput_floor,
                objective=0.9),
        SloSpec(name="train-straggler", kind="gauge-floor",
                gauge_family="tpu_train_step_skew_ratio",
                floor=straggler_skew, above=True, objective=0.9),
    ]


class AlertEngine:
    """Evaluates SLO specs against a :class:`MetricsRegistry` and keeps a
    bounded ring of fired/resolved alerts.

    ``evaluate()`` is the single entry point — the operator calls it from
    its background tick, the sim harness from its settle loop.  Each call
    appends one cumulative sample per watched series and re-derives the
    burn rate of every (spec, series, window); transitions are recorded
    into the ring.  Alert identity is (spec, series, window): a breach
    that keeps burning stays one firing alert, it does not re-fire.
    """

    def __init__(self, registry, specs: Optional[List[SloSpec]] = None,
                 clock=None, capacity: int = 256,
                 audit=None, flight=None,
                 state: Optional[Dict[str, Any]] = None):
        self.registry = registry
        self.specs = list(specs) if specs is not None else default_slos()
        self._now: Callable[[], float] = (clock.now if clock is not None
                                          else time.time)
        self._audit = audit
        self._flight = flight
        self._lock = threading.Lock()
        # (spec.name, series_key) -> deque[(ts, total, bad)]
        self._samples: Dict[Tuple[str, Tuple], deque] = {}
        # (spec.name, series_key, window) -> active alert dict
        self._active: Dict[Tuple[str, Tuple, str], Dict[str, Any]] = {}
        self._ring: deque = deque(maxlen=capacity)
        self.evaluations = 0
        if state:
            self._restore(state)

    # -- restart survival ---------------------------------------------------

    def export_state(self) -> Dict[str, Any]:
        """JSON-ready snapshot of the engine's evaluation state: the
        cumulative sample windows, the active alerts (with their
        original ``since``), and the fired/resolved ring.  A restarted
        operator reconstructs the engine with ``state=`` so a
        still-burning breach stays ONE firing alert — it must not
        re-fire with a fresh identity just because the process moved."""
        with self._lock:
            return {
                "samples": [
                    {"spec": sn, "series": [list(p) for p in sk],
                     "points": [list(pt) for pt in dq]}
                    for (sn, sk), dq in self._samples.items()],
                "active": [
                    {"spec": sn, "series": [list(p) for p in sk],
                     "window": w, "alert": dict(a)}
                    for (sn, sk, w), a in self._active.items()],
                "ring": [dict(a) for a in self._ring],
                "evaluations": self.evaluations,
            }

    def _restore(self, state: Dict[str, Any]) -> None:
        for s in state.get("samples", []):
            key = (s["spec"], tuple(tuple(p) for p in s["series"]))
            self._samples[key] = deque(
                (tuple(pt) for pt in s["points"]), maxlen=2048)
        for a in state.get("active", []):
            key = (a["spec"], tuple(tuple(p) for p in a["series"]),
                   a["window"])
            self._active[key] = dict(a["alert"])
        for a in state.get("ring", []):
            self._ring.append(dict(a))
        self.evaluations = int(state.get("evaluations", 0))

    # -- cumulative event counts per spec -----------------------------------

    def _latency_counts(self, spec: SloSpec
                        ) -> List[Tuple[Tuple, float, float]]:
        snap = self.registry.histogram_snapshot(spec.metric,
                                                dict(spec.labels))
        if snap is None:
            return []
        good = sum(c for b, c in zip(snap["buckets"], snap["counts"])
                   if b <= spec.threshold_s)
        return [(spec.labels, float(snap["n"]), float(snap["n"] - good))]

    def _availability_counts(self, spec: SloSpec
                             ) -> List[Tuple[Tuple, float, float]]:
        scope = dict(spec.series_labels)

        def in_scope(labels: Dict[str, str]) -> bool:
            return all(labels.get(k) == v for k, v in scope.items())

        series = [(labels, v) for labels, v
                  in self.registry.family_snapshot(spec.total_family)
                  if in_scope(labels)]
        if not series:
            return []
        total = sum(v for _, v in series)
        bad = sum(v for labels, v in series
                  if str(labels.get("code", "")).startswith("5"))
        for fam in spec.bad_families:
            bad += sum(v for labels, v
                       in self.registry.family_snapshot(fam)
                       if in_scope(labels))
        return [(spec.series_labels, total, bad)]

    def _gauge_counts(self, spec: SloSpec
                      ) -> List[Tuple[Tuple, float, float]]:
        out = []
        for labels, value in self.registry.family_snapshot(
                spec.gauge_family):
            key = tuple(sorted(labels.items()))
            prev = self._samples.get((spec.name, key))
            breach = (value > spec.floor) if spec.above \
                else (value < spec.floor)
            total = (prev[-1][1] if prev else 0.0) + 1.0
            bad = (prev[-1][2] if prev else 0.0) + (1.0 if breach else 0.0)
            out.append((key, total, bad))
        return out

    def _counts(self, spec: SloSpec) -> List[Tuple[Tuple, float, float]]:
        if spec.kind == "latency":
            return self._latency_counts(spec)
        if spec.kind == "availability":
            return self._availability_counts(spec)
        if spec.kind == "gauge-floor":
            return self._gauge_counts(spec)
        raise ValueError(f"unknown SLO kind {spec.kind!r}")

    # -- windowed burn rates ------------------------------------------------

    @staticmethod
    def _anchor(samples: deque, horizon: float
                ) -> Optional[Tuple[float, float, float]]:
        """The newest sample at or before the window start — cumulative
        deltas against it cover exactly the window (plus at most one
        evaluation interval of slack at the old edge)."""
        anchor = None
        for s in samples:
            if s[0] <= horizon:
                anchor = s
            else:
                break
        return anchor if anchor is not None else (samples[0]
                                                  if samples else None)

    def _burn(self, spec: SloSpec, samples: deque, now: float,
              window: float) -> Tuple[float, float, float]:
        """(burn_rate, bad_delta, total_delta) over the trailing window."""
        cur = samples[-1]
        anchor = self._anchor(samples, now - window)
        total = cur[1] - anchor[1]
        bad = cur[2] - anchor[2]
        if total < spec.min_samples:
            return 0.0, bad, total
        return (bad / total) / spec.budget, bad, total

    # -- cross-links --------------------------------------------------------

    def _exemplar(self, spec: SloSpec) -> Optional[Tuple[str, float]]:
        """The latest above-threshold exemplar of a latency spec:
        ``(trace_id, observed_value)`` from the highest breaching
        bucket that carries one, or None."""
        if spec.kind != "latency":
            return None
        snap = self.registry.histogram_snapshot(spec.metric,
                                                dict(spec.labels)) or {}
        for bucket, ex in zip(reversed(snap.get("buckets", [])),
                              reversed(snap.get("exemplars", []))):
            if ex is not None and bucket > spec.threshold_s:
                return str(ex[0]), float(ex[1])
        return None

    def _links(self, spec: SloSpec, series_key: Tuple) -> Dict[str, str]:
        """Where to look next: the exemplar trace behind a latency
        breach, the autoscaler decision audit, the flight-recorder ring
        for the breaching CR."""
        links: Dict[str, str] = {}
        ex = self._exemplar(spec)
        if ex is not None:
            links["trace"] = f"/debug/traces?trace_id={ex[0]}&tree=1"
        if self._audit is not None:
            links["autoscaler"] = "/debug/autoscaler"
        if spec.kind == "gauge-floor" and series_key:
            labels = dict(series_key)
            if {"kind", "namespace", "name"} <= set(labels):
                triple = (labels["kind"], labels["namespace"],
                          labels["name"])
                links["flight"] = "/debug/flight/%s/%s/%s" % triple
                links["goodput"] = "/debug/goodput/%s/%s/%s" % triple
        return links

    # -- the tick -----------------------------------------------------------

    def evaluate(self) -> List[Dict[str, Any]]:
        """One evaluation pass; returns alerts that fired this tick."""
        now = self._now()
        fired: List[Dict[str, Any]] = []
        with self._lock:
            self.evaluations += 1
            for spec in self.specs:
                for series_key, total, bad in self._counts(spec):
                    skey = (spec.name, series_key)
                    samples = self._samples.setdefault(
                        skey, deque(maxlen=2048))
                    samples.append((now, total, bad))
                    for window_name, window_s, burn_thresh in (
                            ("fast", spec.fast_window_s, spec.fast_burn),
                            ("slow", spec.slow_window_s, spec.slow_burn)):
                        burn, bad_d, total_d = self._burn(
                            spec, samples, now, window_s)
                        akey = (spec.name, series_key, window_name)
                        active = self._active.get(akey)
                        if burn >= burn_thresh and active is None:
                            alert = {
                                "name": spec.name, "window": window_name,
                                "series": dict(series_key),
                                "state": FIRING, "since": now,
                                "burn_rate": round(burn, 3),
                                "burn_threshold": burn_thresh,
                                "budget": spec.budget,
                                "bad": bad_d, "total": total_d,
                                "links": self._links(spec, series_key),
                            }
                            ex = self._exemplar(spec)
                            if ex is not None:
                                # The page's "show me one bad request"
                                # answer: the latest above-threshold
                                # exemplar, resolvable at the trace
                                # link above.
                                alert["exemplar"] = {"trace_id": ex[0],
                                                     "value": ex[1]}
                            self._active[akey] = alert
                            self._ring.append(dict(alert))
                            fired.append(alert)
                        elif burn >= burn_thresh and active is not None:
                            active["burn_rate"] = round(burn, 3)
                            active["bad"], active["total"] = bad_d, total_d
                        elif burn < burn_thresh and active is not None:
                            resolved = self._active.pop(akey)
                            resolved = dict(resolved, state=RESOLVED,
                                            resolved_at=now,
                                            burn_rate=round(burn, 3))
                            self._ring.append(resolved)
        return fired

    # -- querying -----------------------------------------------------------

    def active(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(a) for a in self._active.values()]

    def to_dict(self) -> Dict[str, Any]:
        """The /debug/alerts document: active alerts, the bounded
        fired/resolved history ring, and the spec catalog."""
        with self._lock:
            return {
                "active": [dict(a) for a in self._active.values()],
                "ring": [dict(a) for a in self._ring],
                "evaluations": self.evaluations,
                "specs": [{
                    "name": s.name, "kind": s.kind,
                    "objective": s.objective,
                    "fast": {"window_s": s.fast_window_s,
                             "burn": s.fast_burn},
                    "slow": {"window_s": s.slow_window_s,
                             "burn": s.slow_burn},
                } for s in self.specs],
            }
