"""Head-pod autoscaler sidecar package.

The decision logic lives in ``kuberay_tpu.controlplane.autoscaler``
(shared with the operator's in-process mode); this package is the
``python -m kuberay_tpu.autoscaler.sidecar`` process the pod builder
injects (builders/pod.py build_autoscaler_container — the analogue of
reference BuildAutoscalerContainer, common/pod.go:736).
"""
