"""Autoscaler sidecar: the process the head pod's autoscaler container
runs (builders/pod.py build_autoscaler_container injects exactly this
command).

Reference parity: the Ray autoscaler sidecar the reference builds into
the head pod (``common/pod.go:736`` BuildAutoscalerContainer) patches
``WorkerGroupSpec.Replicas`` / ``ScaleStrategy.WorkersToDelete`` through
the K8s API.  Here the loop is ``controlplane/autoscaler.SliceAutoscaler``
(slice-granular decisions from queued-TpuJob demand) driven over the REST
store, so the same binary works against the framework's apiserver in
tests and a real kube-apiserver in-cluster (service-account token + CA
picked up from the pod filesystem).
"""

from __future__ import annotations

import argparse
import os
import sys
import time

SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


def _default_apiserver(env=os.environ) -> str:
    url = env.get("TPU_APISERVER_URL", "")
    if url:
        return url
    host = env.get("KUBERNETES_SERVICE_HOST", "")
    if host:
        return f"https://{host}:{env.get('KUBERNETES_SERVICE_PORT', '443')}"
    return "http://127.0.0.1:8765"


def _sa_token() -> str:
    try:
        with open(os.path.join(SA_DIR, "token")) as f:
            return f.read().strip()
    except OSError:
        return ""


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="tpu-autoscaler")
    ap.add_argument("--cluster", required=True)
    ap.add_argument("--namespace", default="default")
    ap.add_argument("--apiserver", default="",
                    help="API server base URL (default: TPU_APISERVER_URL "
                         "env, then the in-cluster kubernetes service)")
    ap.add_argument("--token", default="",
                    help="Bearer token (default: TPU_APISERVER_TOKEN env, "
                         "then the mounted service-account token)")
    ap.add_argument("--interval", type=float, default=5.0)
    ap.add_argument("--once", action="store_true",
                    help="single reconcile pass (tests / cron)")
    args = ap.parse_args(argv)

    import json

    from kuberay_tpu.controlplane.autoscaler import (DecisionAudit,
                                                     SliceAutoscaler)
    from kuberay_tpu.controlplane.rest_store import RestObjectStore

    url = args.apiserver or _default_apiserver()
    token = (args.token or os.environ.get("TPU_APISERVER_TOKEN", "")
             or _sa_token())
    store = RestObjectStore(url, token=token or None)
    idle_timeout = float(os.environ.get("TPU_AUTOSCALER_IDLE_TIMEOUT", "60"))
    # Decision audit (same ring the operator mounts at /debug/autoscaler):
    # the sidecar has no HTTP surface, so each decision — input signals
    # and verdict — is emitted to the container log as one JSON line.
    audit = DecisionAudit()
    scaler = SliceAutoscaler(store, idle_timeout=idle_timeout, audit=audit)
    print(f"autoscaler sidecar: cluster={args.cluster} ns={args.namespace} "
          f"apiserver={url} idle_timeout={idle_timeout}s", flush=True)

    printed = 0
    while True:
        try:
            changed = scaler.reconcile(args.cluster, args.namespace)
            fresh = min(audit.total - printed, len(audit))
            if fresh > 0:
                for entry in reversed(audit.to_list()[:fresh]):
                    print(f"autoscaler decision: {json.dumps(entry)}",
                          flush=True)
            printed = audit.total
            if changed:
                print(f"autoscaler: patched {args.cluster}", flush=True)
        except Exception as e:  # keep the sidecar alive through API blips
            print(f"autoscaler: reconcile error: {e}", file=sys.stderr,
                  flush=True)
        if args.once:
            return 0
        time.sleep(args.interval)


if __name__ == "__main__":
    sys.exit(main())
