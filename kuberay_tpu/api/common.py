"""Common object model: metadata, pod templates, conditions.

K8s-shaped but dependency-free.  Dataclasses serialize to/from plain dicts
(``to_dict``/``from_dict``) so objects round-trip through the store, the REST
gateway, and YAML manifests exactly like CRs do through the K8s API.

Mirrors the role of k8s apimachinery for the reference's apis/ray/v1 types.
"""

from __future__ import annotations

import copy
import dataclasses
import time
from typing import Any, Dict, List, Optional


def _prune(d):
    """Drop None values and empty containers recursively (K8s-style JSON)."""
    if isinstance(d, dict):
        out = {k: _prune(v) for k, v in d.items()}
        return {k: v for k, v in out.items() if v not in (None, {}, [])}
    if isinstance(d, list):
        return [_prune(v) for v in d]
    return d


class Serializable:
    """dict round-tripping for nested dataclasses."""

    def to_dict(self) -> Dict[str, Any]:
        return _prune(dataclasses.asdict(self))

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]):
        if d is None:
            return None
        kwargs = {}
        for f in dataclasses.fields(cls):
            if f.name not in d:
                continue
            v = d[f.name]
            ftype = cls._nested_types().get(f.name)
            converted = False
            if ftype is not None and v is not None:
                if isinstance(v, list):
                    v = [ftype.from_dict(x) if isinstance(x, dict) else x for x in v]
                    converted = all(not isinstance(x, dict) for x in v)
                elif isinstance(v, dict):
                    v = ftype.from_dict(v)
                    converted = True
            # Freshly-built nested objects are already ours; only raw
            # dict/list values need the defensive copy.
            kwargs[f.name] = v if converted else copy.deepcopy(v)
        return cls(**kwargs)

    @classmethod
    def _nested_types(cls) -> Dict[str, type]:
        """Map field name -> nested Serializable type (overridden as needed)."""
        return {}


@dataclasses.dataclass
class OwnerReference(Serializable):
    apiVersion: str = ""
    kind: str = ""
    name: str = ""
    uid: str = ""
    controller: bool = True
    blockOwnerDeletion: bool = True


@dataclasses.dataclass
class ObjectMeta(Serializable):
    name: str = ""
    namespace: str = "default"
    uid: str = ""
    resourceVersion: int = 0
    generation: int = 0
    creationTimestamp: float = 0.0
    deletionTimestamp: Optional[float] = None
    labels: Dict[str, str] = dataclasses.field(default_factory=dict)
    annotations: Dict[str, str] = dataclasses.field(default_factory=dict)
    finalizers: List[str] = dataclasses.field(default_factory=list)
    ownerReferences: List[OwnerReference] = dataclasses.field(default_factory=list)

    @classmethod
    def _nested_types(cls):
        return {"ownerReferences": OwnerReference}


@dataclasses.dataclass
class EnvVar(Serializable):
    name: str = ""
    value: str = ""


@dataclasses.dataclass
class ContainerPort(Serializable):
    name: str = ""
    containerPort: int = 0


@dataclasses.dataclass
class ResourceRequirements(Serializable):
    # {"cpu": "4", "memory": "16Gi", "google.com/tpu": "4"}
    requests: Dict[str, str] = dataclasses.field(default_factory=dict)
    limits: Dict[str, str] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class Container(Serializable):
    name: str = ""
    image: str = ""
    command: List[str] = dataclasses.field(default_factory=list)
    args: List[str] = dataclasses.field(default_factory=list)
    env: List[EnvVar] = dataclasses.field(default_factory=list)
    ports: List[ContainerPort] = dataclasses.field(default_factory=list)
    resources: ResourceRequirements = dataclasses.field(default_factory=ResourceRequirements)
    workingDir: str = ""
    # Container-level restart policy: K8s native-sidecar field (valid on
    # initContainers, value "Always").  Preserved on round-trip so user
    # templates with native sidecars don't silently lose it; nothing in
    # this framework sets it (the SidecarMode submitter relies on the
    # POD-level "Never" instead — see builders/job.py).
    restartPolicy: str = ""

    @classmethod
    def _nested_types(cls):
        return {"env": EnvVar, "ports": ContainerPort,
                "resources": ResourceRequirements}


@dataclasses.dataclass
class PodSpec(Serializable):
    containers: List[Container] = dataclasses.field(default_factory=list)
    initContainers: List[Container] = dataclasses.field(default_factory=list)
    nodeSelector: Dict[str, str] = dataclasses.field(default_factory=dict)
    tolerations: List[Dict[str, Any]] = dataclasses.field(default_factory=list)
    restartPolicy: str = ""
    serviceAccountName: str = ""
    subdomain: str = ""
    hostname: str = ""
    schedulerName: str = ""

    @classmethod
    def _nested_types(cls):
        return {"containers": Container, "initContainers": Container}


@dataclasses.dataclass
class PodTemplateSpec(Serializable):
    metadata: ObjectMeta = dataclasses.field(default_factory=ObjectMeta)
    spec: PodSpec = dataclasses.field(default_factory=PodSpec)

    @classmethod
    def _nested_types(cls):
        return {"metadata": ObjectMeta, "spec": PodSpec}


@dataclasses.dataclass
class Condition(Serializable):
    """K8s-style status condition (metav1.Condition shape)."""

    type: str = ""
    status: str = "Unknown"     # "True" | "False" | "Unknown"
    reason: str = ""
    message: str = ""
    lastTransitionTime: float = 0.0
    observedGeneration: int = 0


def set_condition(conditions: List[Condition], cond: Condition) -> bool:
    """Upsert by type; preserves lastTransitionTime when status unchanged.

    Returns True when the condition *meaningfully* changed
    (status/reason/message — drives status-update throttling, the
    reference's consistency.go:16 pattern).  ``observedGeneration`` is
    always refreshed on the stored condition (k8s meta.SetStatusCondition
    behavior) but does not by itself count as a change.  The input is
    copied, never aliased.
    """
    cond = copy.deepcopy(cond)
    for i, existing in enumerate(conditions):
        if existing.type == cond.type:
            if (existing.status == cond.status and existing.reason == cond.reason
                    and existing.message == cond.message):
                existing.observedGeneration = cond.observedGeneration
                return False
            if existing.status == cond.status:
                cond.lastTransitionTime = existing.lastTransitionTime
            elif not cond.lastTransitionTime:
                cond.lastTransitionTime = time.time()
            conditions[i] = cond
            return True
    if not cond.lastTransitionTime:
        cond.lastTransitionTime = time.time()
    conditions.append(cond)
    return True


def get_condition(conditions: List[Condition], ctype: str) -> Optional[Condition]:
    for c in conditions:
        if c.type == ctype:
            return c
    return None


def is_condition_true(conditions: List[Condition], ctype: str) -> bool:
    c = get_condition(conditions, ctype)
    return c is not None and c.status == "True"
