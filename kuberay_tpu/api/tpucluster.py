"""TpuCluster CRD-equivalent types.

TPU-first re-design of the reference's RayClusterSpec
(apis/ray/v1/raycluster_types.go:14-62) and WorkerGroupSpec (:374-418):

- A worker group declares ``accelerator`` + ``topology`` and ``replicas``
  counts *slices*, not pods.  ``numHosts`` is derived from the topology
  (never free-form like the reference's ``NumOfHosts``), so a spec cannot
  describe a slice the hardware can't form.
- The autoscaler contract (:421-424 Replicas/ScaleStrategy.WorkersToDelete)
  becomes slice-granular: ``scaleStrategy.slicesToDelete`` names whole
  slices.
- Head fault tolerance (GcsFaultToleranceOptions :131) maps to coordinator
  state persistence options.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

from kuberay_tpu.api.common import (
    Condition,
    ObjectMeta,
    PodTemplateSpec,
    Serializable,
)
from kuberay_tpu.topology import SliceTopology
from kuberay_tpu.utils import constants as C


# --- enums -------------------------------------------------------------------

class ClusterState:
    """Status.state values (ref raycluster_types.go state enum)."""

    READY = "ready"
    SUSPENDED = "suspended"
    FAILED = "failed"


class ClusterConditionType:
    """Status condition types (ref raycluster_types.go:500-610)."""

    HEAD_POD_READY = "HeadPodReady"
    PROVISIONED = "TpuClusterProvisioned"      # all slices ready at least once
    REPLICA_FAILURE = "ReplicaFailure"
    SUSPENDING = "TpuClusterSuspending"
    SUSPENDED = "TpuClusterSuspended"
    GANG_ADMITTED = "GangAdmitted"             # quota/capacity verdict


class UpgradeStrategyType:
    """In-place upgrade behavior when the pod spec hash changes."""

    RECREATE = "Recreate"         # delete all pods, rebuild (ref Recreate path)
    NONE = "None"                 # ignore spec changes for existing pods


# --- spec --------------------------------------------------------------------

@dataclasses.dataclass
class AutoscalerOptions(Serializable):
    """Ref AutoscalerOptions (raycluster_types.go:427-476), slice-granular."""

    idleTimeoutSeconds: int = 60
    upscalingMode: str = "Default"      # Default | Aggressive | Conservative
    imagePullPolicy: str = ""
    image: str = ""
    # v2-style per-group overrides land on the group spec, not here.


@dataclasses.dataclass
class HeadStateOptions(Serializable):
    """Coordinator fault tolerance (ref GcsFaultToleranceOptions
    raycluster_types.go:131 + gcs_ft.go:17 embedded variant).

    ``backend`` 'memory' keeps cluster metadata in-process (workers die with
    the head); 'external' points at a Redis-compatible store; 'persistent'
    provisions a PVC the coordinator journals to (embedded-RocksDB analogue).
    """

    backend: str = "memory"             # memory | external | persistent
    externalStorageAddress: str = ""
    externalStorageNamespace: str = ""
    storageSize: str = "10Gi"
    storageClassName: str = ""


@dataclasses.dataclass
class HeadGroupSpec(Serializable):
    template: PodTemplateSpec = dataclasses.field(default_factory=PodTemplateSpec)
    serviceType: str = "ClusterIP"
    enableIngress: bool = False
    startParams: Dict[str, str] = dataclasses.field(default_factory=dict)

    @classmethod
    def _nested_types(cls):
        return {"template": PodTemplateSpec}


@dataclasses.dataclass
class ScaleStrategy(Serializable):
    """Autoscaler downscale contract: names whole slices (ref
    ScaleStrategy.WorkersToDelete raycluster_types.go:421-424, expanded to
    groups at raycluster_controller.go:1293-1322 — here it is group-native)."""

    slicesToDelete: List[str] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class WorkerGroupSpec(Serializable):
    groupName: str = ""
    accelerator: str = "v5e"            # TPU generation
    topology: str = "2x2"               # ICI topology, e.g. "4x4" / "4x4x4"
    computeTemplate: str = ""           # named slice preset (api/computetemplate)
    replicas: int = 1                   # number of slices
    minReplicas: int = 0
    maxReplicas: int = 1
    suspend: bool = False
    # Per-group idle scale-down override (ref WorkerGroupSpec.
    # IdleTimeoutSeconds, autoscaler v2): 0 = inherit
    # autoscalerOptions.idleTimeoutSeconds.
    idleTimeoutSeconds: int = 0
    scaleStrategy: ScaleStrategy = dataclasses.field(default_factory=ScaleStrategy)
    template: PodTemplateSpec = dataclasses.field(default_factory=PodTemplateSpec)
    startParams: Dict[str, str] = dataclasses.field(default_factory=dict)

    @classmethod
    def _nested_types(cls):
        return {"scaleStrategy": ScaleStrategy, "template": PodTemplateSpec}

    # Friendly wire aliases accepted from clients (the SDK/dashboard speak
    # in slices): canonical keys win when both are present.
    _ALIASES = (("numSlices", "replicas"), ("tpuVersion", "accelerator"))

    @classmethod
    def from_dict(cls, d):
        if d:
            d = dict(d)
            for alias, canon in cls._ALIASES:
                if alias in d:
                    if canon not in d:
                        d[canon] = d[alias]
                    del d[alias]
        return super().from_dict(d)

    def slice_topology(self) -> SliceTopology:
        return SliceTopology.create(self.accelerator, self.topology)

    @property
    def num_hosts(self) -> int:
        """Pods per slice — derived, never declared."""
        return self.slice_topology().num_hosts


@dataclasses.dataclass
class NetworkPolicySpec(Serializable):
    """Ref raycluster_types.go:254-311 (NetworkPolicy modes)."""

    enabled: bool = False
    mode: str = "DenyAll"               # DenyAll | DenyAllEgress
    allowNamespaces: List[str] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class TpuClusterSpec(Serializable):
    headGroupSpec: HeadGroupSpec = dataclasses.field(default_factory=HeadGroupSpec)
    workerGroupSpecs: List[WorkerGroupSpec] = dataclasses.field(default_factory=list)
    suspend: bool = False
    enableInTreeAutoscaling: bool = False
    autoscalerOptions: Optional[AutoscalerOptions] = None
    headStateOptions: Optional[HeadStateOptions] = None
    networkPolicy: Optional[NetworkPolicySpec] = None
    upgradeStrategy: str = UpgradeStrategyType.NONE
    # Token auth for the coordinator API (ref auth secret builder +
    # e2e raycluster_auth_test.go): the operator mints a Secret and wires
    # it into every container; the coordinator requires Bearer auth.
    # Defaults to True: the coordinator runs job entrypoints, so an
    # unauthenticated coordinator port is remote code execution.  Set
    # enableTokenAuth=false explicitly to opt out (trusted networks only).
    enableTokenAuth: bool = True
    # Kueue-style handoff (ref ManagedBy raycluster_types.go:25-34):
    managedBy: str = ""
    # Gang scheduler selection (ref batchscheduler labels):
    schedulerName: str = ""
    gangSchedulingQueue: str = ""
    # Multi-tenant quota identity (controlplane/quota.py): empty tenant
    # bypasses the QuotaPool ledger; higher priority wins reclaim ties.
    tenant: str = ""
    priority: int = 0

    @classmethod
    def _nested_types(cls):
        return {
            "headGroupSpec": HeadGroupSpec,
            "workerGroupSpecs": WorkerGroupSpec,
            "autoscalerOptions": AutoscalerOptions,
            "headStateOptions": HeadStateOptions,
            "networkPolicy": NetworkPolicySpec,
        }


# --- status ------------------------------------------------------------------

@dataclasses.dataclass
class WorkerGroupStatus(Serializable):
    groupName: str = ""
    desiredSlices: int = 0
    readySlices: int = 0
    desiredHosts: int = 0
    readyHosts: int = 0
    desiredTpuChips: int = 0


@dataclasses.dataclass
class TpuClusterStatus(Serializable):
    state: str = ""
    reason: str = ""
    observedGeneration: int = 0
    conditions: List[Condition] = dataclasses.field(default_factory=list)
    readyWorkerHosts: int = 0
    desiredWorkerHosts: int = 0
    readySlices: int = 0
    desiredSlices: int = 0
    desiredTpuChips: int = 0
    groups: List[WorkerGroupStatus] = dataclasses.field(default_factory=list)
    headServiceName: str = ""
    headPodName: str = ""
    headPodIP: str = ""
    coordinatorAddress: str = ""
    lastResumeTime: float = 0.0
    stateTransitionTimes: Dict[str, float] = dataclasses.field(default_factory=dict)

    @classmethod
    def _nested_types(cls):
        return {"conditions": Condition, "groups": WorkerGroupStatus}


@dataclasses.dataclass
class TpuCluster(Serializable):
    apiVersion: str = C.API_VERSION
    kind: str = C.KIND_CLUSTER
    metadata: ObjectMeta = dataclasses.field(default_factory=ObjectMeta)
    spec: TpuClusterSpec = dataclasses.field(default_factory=TpuClusterSpec)
    status: TpuClusterStatus = dataclasses.field(default_factory=TpuClusterStatus)

    @classmethod
    def _nested_types(cls):
        return {"metadata": ObjectMeta, "spec": TpuClusterSpec,
                "status": TpuClusterStatus}
