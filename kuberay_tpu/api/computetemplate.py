"""ComputeTemplate: named, reusable slice-shape presets.

Reference capability: the apiserver v1 ComputeTemplate service
(``proto/config.proto`` ComputeTemplate; stored as labeled ConfigMaps,
resolved into container resources when the resource manager materializes
a cluster).  TPU-native re-design: a template names a **slice shape** —
TPU generation + ICI topology + per-host cpu/memory — because on TPU the
accelerator count is a property of the (accelerator, topology) pair, not
a free-form `gpu: N` field.  Worker groups opt in with
``computeTemplate: <name>``; the operator resolves the template at
reconcile time (kept resolution server-side like the reference, so every
client — CLI, SDK, raw YAML — benefits).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

from kuberay_tpu.api.common import ObjectMeta, Serializable
from kuberay_tpu.topology import SliceTopology

KIND_COMPUTE_TEMPLATE = "ComputeTemplate"


@dataclasses.dataclass
class ComputeTemplateSpec(Serializable):
    accelerator: str = "v5e"          # TPU generation (v4/v5e/v5p/v6e)
    topology: str = "2x2"             # ICI topology of one slice
    cpu: str = ""                     # per-host requests (optional)
    memory: str = ""
    nodeSelectors: Dict[str, str] = dataclasses.field(default_factory=dict)
    tolerations: List[Dict[str, Any]] = dataclasses.field(default_factory=list)
    description: str = ""


@dataclasses.dataclass
class ComputeTemplate(Serializable):
    apiVersion: str = "tpu.dev/v1"
    kind: str = KIND_COMPUTE_TEMPLATE
    metadata: ObjectMeta = dataclasses.field(default_factory=ObjectMeta)
    spec: ComputeTemplateSpec = dataclasses.field(
        default_factory=ComputeTemplateSpec)

    @classmethod
    def _nested_types(cls):
        return {"metadata": ObjectMeta, "spec": ComputeTemplateSpec}


def validate_compute_template(t: ComputeTemplate) -> List[str]:
    errs: List[str] = []
    if not t.metadata.name:
        errs.append("metadata.name is required")
    try:
        SliceTopology.create(t.spec.accelerator, t.spec.topology)
    except Exception as e:  # noqa: BLE001 — surface as validation error
        errs.append(f"spec: {e}")
    return errs


# --- builtin presets (ref python-client Director small/medium/large) ---------
# Real slice shapes, stepping through TPU sizes rather than cpu tiers.

BUILTIN_TEMPLATES: Dict[str, ComputeTemplateSpec] = {
    "tpu-small": ComputeTemplateSpec(
        accelerator="v5e", topology="2x2", cpu="8", memory="16Gi",
        description="1 host, 4 chips (v5e 2x2)"),
    "tpu-medium": ComputeTemplateSpec(
        accelerator="v5e", topology="4x4", cpu="24", memory="48Gi",
        description="4 hosts, 16 chips (v5e 4x4)"),
    "tpu-large": ComputeTemplateSpec(
        accelerator="v5p", topology="4x4x4", cpu="48", memory="96Gi",
        description="16 hosts, 64 chips (v5p 4x4x4)"),
}


def builtin_template(name: str,
                     namespace: str = "default") -> Optional[ComputeTemplate]:
    spec = BUILTIN_TEMPLATES.get(name)
    if spec is None:
        return None
    return ComputeTemplate(
        metadata=ObjectMeta(name=name, namespace=namespace),
        spec=dataclasses.replace(spec))


def resolve_group_template(group, template: ComputeTemplate) -> None:
    """Fill a WorkerGroupSpec in place from a template.

    The template is authoritative for the slice shape (accelerator,
    topology); cpu/memory/nodeSelectors/tolerations merge into the pod
    template without overwriting anything the group set explicitly.
    """
    from kuberay_tpu.api.common import Container

    group.accelerator = template.spec.accelerator
    group.topology = template.spec.topology
    pod_spec = group.template.spec               # typed PodSpec
    if not pod_spec.containers:
        pod_spec.containers = [Container(name="worker")]
    c0 = pod_spec.containers[0]
    if template.spec.cpu or template.spec.memory:
        for slot in (c0.resources.requests, c0.resources.limits):
            if template.spec.cpu:
                slot.setdefault("cpu", template.spec.cpu)
            if template.spec.memory:
                slot.setdefault("memory", template.spec.memory)
    for k, v in template.spec.nodeSelectors.items():
        pod_spec.nodeSelector.setdefault(k, v)
    for t in template.spec.tolerations:
        if t not in pod_spec.tolerations:
            pod_spec.tolerations.append(t)


def resolve_compute_templates(cluster, store) -> List[str]:
    """Resolve every ``computeTemplate`` reference in a TpuCluster spec,
    mutating the in-memory spec only (the stored CR keeps the reference,
    like the reference's ConfigMap indirection).  Lookup order: CR in the
    cluster's namespace, then builtin presets.  Returns errors for
    unknown template names."""
    errs: List[str] = []
    ns = cluster.metadata.namespace or "default"
    for group in cluster.spec.workerGroupSpecs:
        name = getattr(group, "computeTemplate", "")
        if not name:
            continue
        raw = store.try_get(KIND_COMPUTE_TEMPLATE, name, ns)
        template = (ComputeTemplate.from_dict(raw) if raw is not None
                    else builtin_template(name, ns))
        if template is None:
            errs.append(f"workerGroup '{group.groupName}': unknown "
                        f"computeTemplate '{name}'")
            continue
        terrs = validate_compute_template(template)
        if terrs:
            errs.extend(f"computeTemplate '{name}': {e}" for e in terrs)
            continue
        resolve_group_template(group, template)
    return errs
