"""Operator configuration (ref apis/config/v1alpha1/configuration_types.go:18-78).

Three config layers like the reference (§5.6): CLI flags ⊕ this structured
config ⊕ feature gates.  Env-var escape hatches are read at use sites.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List

from kuberay_tpu.api.common import Serializable


@dataclasses.dataclass
class OperatorConfiguration(Serializable):
    metricsAddr: str = ":8080"
    probeAddr: str = ":8082"
    enableLeaderElection: bool = True
    leaderElectionNamespace: str = "default"
    # Workers PER SHARD (each shard pool gets its own reconcile threads):
    reconcileConcurrency: int = 1
    # Hash-sharded reconcile pools (controlplane/sharding.py): keys
    # partition across this many worker pools; 1 = the classic single
    # queue.  Multi-process deployments split ownership via per-shard
    # leases (--shard-leases), capped at maxOwnedShards per replica
    # (0 = own every shard you can grab).
    shardCount: int = 1
    maxOwnedShards: int = 0
    # Watch backlog window (events resumable by rv before ExpiredError
    # forces a relist) and bookmark cadence (BOOKMARK progress event to
    # subscribers every N committed rvs; 0 = off):
    watchBacklogMax: int = 10000
    watchBookmarkInterval: int = 0
    watchNamespaces: List[str] = dataclasses.field(default_factory=list)
    logLevel: str = "info"
    logFile: str = ""
    logStdoutEncoder: str = "json"      # json | console
    # Gang scheduler plugin name ("" = builtin, or volcano|yunikorn|kai|
    # scheduler-plugins — ref batch-scheduler name in config):
    batchScheduler: str = ""
    enableBatchScheduler: bool = False
    # OpenShift: expose the head via a Route instead of an Ingress (ref
    # common/openshift.go BuildRouteForHeadService; the reference flips
    # on detected cluster type, we take an explicit knob).
    useOpenShiftRoute: bool = False
    # Injected into every built pod (ref default envs/labels/annotations):
    defaultPodEnv: Dict[str, str] = dataclasses.field(default_factory=dict)
    defaultPodLabels: Dict[str, str] = dataclasses.field(default_factory=dict)
    defaultPodAnnotations: Dict[str, str] = dataclasses.field(default_factory=dict)
    # Client-side rate limits (ref QPS/burst):
    clientQps: float = 50.0
    clientBurst: int = 100
    # Requeue cadences:
    requeueSeconds: float = 2.0
    unconditionalRequeueSeconds: float = 300.0
    # Feature gates, e.g. {"TpuMultiHostIndexing": True}:
    featureGates: Dict[str, bool] = dataclasses.field(default_factory=dict)
    # History archive destination ("" = off): file:///path, s3://bucket
    # ?endpoint=..., or gs://bucket?endpoint=... — the operator archives
    # CR lifecycles there (ref historyserver collector deployment).
    historyArchiveURL: str = ""
    # Head sidecars to inject (ref sidecar containers config):
    headSidecarContainers: List[dict] = dataclasses.field(default_factory=list)
    workerSidecarContainers: List[dict] = dataclasses.field(default_factory=list)
