"""TpuService CRD-equivalent types: zero-downtime serving.

Mirrors the reference's RayService (apis/ray/v1/rayservice_types.go):
upgrade strategies (:22-33), ClusterUpgradeOptions (:64-77), active/pending
two-cluster status.  The serve payload is a continuous-batching JAX
inference engine (kuberay_tpu.serve) instead of Ray Serve; "roll TPU slices
without breaking ICI rings" means upgrades replace whole slices behind
weighted routes, never individual hosts.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

from kuberay_tpu.api.common import Condition, ObjectMeta, Serializable
from kuberay_tpu.api.tpucluster import TpuClusterSpec
from kuberay_tpu.utils import constants as C


class ServiceUpgradeType:
    """Ref RayServiceUpgradeType (rayservice_types.go:22-33)."""

    NEW_CLUSTER = "NewCluster"                  # blue/green: full pending cluster
    INCREMENTAL = "NewClusterWithIncrementalUpgrade"  # weighted traffic stepping
    NONE = "None"                               # never upgrade automatically


class ServiceStatusName:
    """Per-cluster serve application health."""

    RUNNING = "RUNNING"
    DEPLOYING = "DEPLOYING"
    UNHEALTHY = "UNHEALTHY"
    NOT_STARTED = "NOT_STARTED"
    # A multi-host serve group lost a follower (or hung in a collective):
    # unrecoverable in place — the slice must be replaced whole.
    DEGRADED = "DEGRADED"


class ServiceConditionType:
    """Ref rayservice conditions (:776)."""

    READY = "Ready"
    UPGRADE_IN_PROGRESS = "UpgradeInProgress"
    ROLLING_BACK = "RollingBack"
    # A serving slice's lockstep group failed (dead follower / stuck
    # collective); replacement is in flight.  Serve-layer counterpart of
    # the cluster controller's whole-slice repair invariant.
    SERVE_GROUP_DEGRADED = "ServeGroupDegraded"


@dataclasses.dataclass
class ClusterUpgradeOptions(Serializable):
    """Ref ClusterUpgradeOptions (rayservice_types.go:64-77).

    Slice-quantized: ``stepSizePercent`` of traffic is shifted every
    ``intervalSeconds`` once the pending cluster's target capacity covers it;
    capacity moves in whole-slice units (SURVEY.md §7 hard part 3).
    """

    stepSizePercent: int = 10
    intervalSeconds: int = 30
    maxSurgePercent: int = 100          # extra capacity allowed during roll


@dataclasses.dataclass
class TpuServiceSpec(Serializable):
    # Serve config: model/apps description consumed by the inference engine
    # (analogue of the ref's ServeConfigV2 multi-app YAML blob).
    serveConfig: Dict[str, Any] = dataclasses.field(default_factory=dict)
    # Disaggregated serving role (SERVE_TIERS): "mixed" replicas run
    # prefill+decode colocated (the default, single-hop gateway path);
    # "prefill"/"decode" services form a two-tier fleet — the controller
    # stamps the tier into TrafficRoute backends and the gateway
    # two-hop-schedules across them (serve/gateway.py).
    serveTier: str = C.SERVE_TIER_MIXED
    clusterSpec: TpuClusterSpec = dataclasses.field(default_factory=TpuClusterSpec)
    upgradeStrategy: str = ServiceUpgradeType.NEW_CLUSTER
    upgradeOptions: Optional[ClusterUpgradeOptions] = None
    suspend: bool = False
    # Seconds to keep the retired active cluster after promotion
    # (ref RayClusterDeletionDelaySeconds, cleanUpRayClusterInstance :1247):
    clusterDeletionDelaySeconds: int = 60
    serviceUnhealthySecondThreshold: int = 900
    deploymentUnhealthySecondThreshold: int = 300
    excludeHeadPodFromServe: bool = False

    @classmethod
    def _nested_types(cls):
        return {"clusterSpec": TpuClusterSpec,
                "upgradeOptions": ClusterUpgradeOptions}


@dataclasses.dataclass
class ServeApplicationStatus(Serializable):
    name: str = ""
    status: str = ServiceStatusName.NOT_STARTED
    message: str = ""
    lastUpdateTime: float = 0.0


@dataclasses.dataclass
class ServiceClusterStatus(Serializable):
    """Status of one (active or pending) cluster in the pair."""

    clusterName: str = ""
    specHash: str = ""
    applications: List[ServeApplicationStatus] = dataclasses.field(default_factory=list)
    trafficWeightPercent: int = 0
    targetCapacityPercent: int = 100

    @classmethod
    def _nested_types(cls):
        return {"applications": ServeApplicationStatus}


@dataclasses.dataclass
class TpuServiceStatus(Serializable):
    serviceStatus: str = ""
    observedGeneration: int = 0
    conditions: List[Condition] = dataclasses.field(default_factory=list)
    activeServiceStatus: Optional[ServiceClusterStatus] = None
    pendingServiceStatus: Optional[ServiceClusterStatus] = None
    numServeEndpoints: int = 0
    lastUpgradeStepTime: float = 0.0

    @classmethod
    def _nested_types(cls):
        return {"conditions": Condition,
                "activeServiceStatus": ServiceClusterStatus,
                "pendingServiceStatus": ServiceClusterStatus}


@dataclasses.dataclass
class TpuService(Serializable):
    apiVersion: str = C.API_VERSION
    kind: str = C.KIND_SERVICE
    metadata: ObjectMeta = dataclasses.field(default_factory=ObjectMeta)
    spec: TpuServiceSpec = dataclasses.field(default_factory=TpuServiceSpec)
    status: TpuServiceStatus = dataclasses.field(default_factory=TpuServiceStatus)

    @classmethod
    def _nested_types(cls):
        return {"metadata": ObjectMeta, "spec": TpuServiceSpec,
                "status": TpuServiceStatus}
