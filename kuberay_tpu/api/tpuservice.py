"""TpuService CRD-equivalent types: zero-downtime serving.

Mirrors the reference's RayService (apis/ray/v1/rayservice_types.go):
upgrade strategies (:22-33), ClusterUpgradeOptions (:64-77), active/pending
two-cluster status.  The serve payload is a continuous-batching JAX
inference engine (kuberay_tpu.serve) instead of Ray Serve; "roll TPU slices
without breaking ICI rings" means upgrades replace whole slices behind
weighted routes, never individual hosts.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

from kuberay_tpu.api.common import Condition, ObjectMeta, Serializable
from kuberay_tpu.api.tpucluster import TpuClusterSpec
from kuberay_tpu.utils import constants as C


class ServiceUpgradeType:
    """Ref RayServiceUpgradeType (rayservice_types.go:22-33)."""

    NEW_CLUSTER = "NewCluster"                  # blue/green: full pending cluster
    INCREMENTAL = "NewClusterWithIncrementalUpgrade"  # weighted traffic stepping
    NONE = "None"                               # never upgrade automatically


class ServiceStatusName:
    """Per-cluster serve application health."""

    RUNNING = "RUNNING"
    DEPLOYING = "DEPLOYING"
    UNHEALTHY = "UNHEALTHY"
    NOT_STARTED = "NOT_STARTED"
    # A multi-host serve group lost a follower (or hung in a collective):
    # unrecoverable in place — the slice must be replaced whole.
    DEGRADED = "DEGRADED"


class ServiceConditionType:
    """Ref rayservice conditions (:776)."""

    READY = "Ready"
    UPGRADE_IN_PROGRESS = "UpgradeInProgress"
    ROLLING_BACK = "RollingBack"
    # A serving slice's lockstep group failed (dead follower / stuck
    # collective); replacement is in flight.  Serve-layer counterpart of
    # the cluster controller's whole-slice repair invariant.
    SERVE_GROUP_DEGRADED = "ServeGroupDegraded"


@dataclasses.dataclass
class ClusterUpgradeOptions(Serializable):
    """Ref ClusterUpgradeOptions (rayservice_types.go:64-77).

    Slice-quantized: ``stepSizePercent`` of traffic is shifted every
    ``intervalSeconds`` once the pending cluster's target capacity covers it;
    capacity moves in whole-slice units (SURVEY.md §7 hard part 3).
    """

    stepSizePercent: int = 10
    intervalSeconds: int = 30
    maxSurgePercent: int = 100          # extra capacity allowed during roll
    # Closed-loop (burn-rate-gated) ramp budgets.  A rollback snaps the
    # pending fleet's weight to 0; after ``holdSeconds`` of clean burn the
    # ramp retries from 0, at most ``maxRollbacks`` times before the
    # pending cluster is abandoned whole (state Aborted).
    maxRollbacks: int = 2
    holdSeconds: int = 60
    # ICI-atomic wave size: green capacity is provisioned this many
    # slices at a time and weight never outruns the fully-Ready ring
    # fraction.  0 = all slices at once (the pre-wave behavior).
    waveSlices: int = 0
    # Prefix-cache pre-warm: before the first weight step the gateway
    # replays up to this many of the active fleet's hottest prompt
    # prefixes against the green backend.  0 = off.
    prewarmPrompts: int = 0
    # Session drain: after the ramp reaches 100 the blue backend is held
    # at weight 0 until the gateway acks zero in-flight requests, or
    # this many seconds pass.  0 = promote immediately (no drain).
    drainTimeoutSeconds: int = 0


@dataclasses.dataclass
class KvTierOptions(Serializable):
    """Tiered KV-cache hierarchy knobs (docs/kv-tiers.md).

    ``hostBlocks``/``spillBlocks`` size the per-replica host-DRAM and
    spill tiers behind the device pool (serve/kv_tiers.py); the
    controller folds them into every serveConfig application block so
    replicas mount the hierarchy at boot.  Session fields bound the
    gateway's session table — resume state is gateway-side metadata
    (block-hash chain + last backend), never engine state, so these
    do not reach the engine CLI.
    """

    hostBlocks: int = 0                 # 0 = tiering off (device only)
    spillBlocks: int = 0                # bounded third tier behind host
    sessionCapacity: int = 1024         # max live sessions at the gateway
    sessionTtlSeconds: int = 600        # idle session expiry


@dataclasses.dataclass
class TpuServiceSpec(Serializable):
    # Serve config: model/apps description consumed by the inference engine
    # (analogue of the ref's ServeConfigV2 multi-app YAML blob).
    serveConfig: Dict[str, Any] = dataclasses.field(default_factory=dict)
    # Disaggregated serving role (SERVE_TIERS): "mixed" replicas run
    # prefill+decode colocated (the default, single-hop gateway path);
    # "prefill"/"decode" services form a two-tier fleet — the controller
    # stamps the tier into TrafficRoute backends and the gateway
    # two-hop-schedules across them (serve/gateway.py).
    serveTier: str = C.SERVE_TIER_MIXED
    # Tiered KV-cache hierarchy (device → host → spill) + gateway
    # session bounds; None = flat device-only cache.
    kvTiers: Optional[KvTierOptions] = None
    clusterSpec: TpuClusterSpec = dataclasses.field(default_factory=TpuClusterSpec)
    upgradeStrategy: str = ServiceUpgradeType.NEW_CLUSTER
    upgradeOptions: Optional[ClusterUpgradeOptions] = None
    suspend: bool = False
    # Seconds to keep the retired active cluster after promotion
    # (ref RayClusterDeletionDelaySeconds, cleanUpRayClusterInstance :1247):
    clusterDeletionDelaySeconds: int = 60
    serviceUnhealthySecondThreshold: int = 900
    deploymentUnhealthySecondThreshold: int = 300
    excludeHeadPodFromServe: bool = False

    @classmethod
    def _nested_types(cls):
        return {"clusterSpec": TpuClusterSpec,
                "upgradeOptions": ClusterUpgradeOptions,
                "kvTiers": KvTierOptions}


@dataclasses.dataclass
class ServeApplicationStatus(Serializable):
    name: str = ""
    status: str = ServiceStatusName.NOT_STARTED
    message: str = ""
    lastUpdateTime: float = 0.0


@dataclasses.dataclass
class ServiceClusterStatus(Serializable):
    """Status of one (active or pending) cluster in the pair."""

    clusterName: str = ""
    specHash: str = ""
    applications: List[ServeApplicationStatus] = dataclasses.field(default_factory=list)
    trafficWeightPercent: int = 0
    targetCapacityPercent: int = 100

    @classmethod
    def _nested_types(cls):
        return {"applications": ServeApplicationStatus}


class UpgradeState:
    """Lifecycle of one burn-rate-gated incremental upgrade."""

    PREWARMING = "Prewarming"    # green at weight 0, cache replay pending
    RAMPING = "Ramping"          # weight stepping under the gate
    HOLDING = "Holding"          # post-rollback backoff, waiting to retry
    ROLLED_BACK = "RolledBack"   # fast-burn fired, weight snapped to 0
    DRAINING = "Draining"        # green at 100, blue finishing in-flight
    PROMOTED = "Promoted"
    ABORTED = "Aborted"          # rollback budget exhausted, pending gone


@dataclasses.dataclass
class UpgradeStatus(Serializable):
    """Observable state of the gated ramp (docs/upgrades.md)."""

    state: str = ""
    rollbacks: int = 0
    lastRollbackTime: float = 0.0
    # The burn-rate alert that forced the last rollback (obs/alerts.py
    # active() shape: name/window/series/burn_rate/...).
    lastAlert: Dict[str, Any] = dataclasses.field(default_factory=dict)
    # ICI-ring wave progress of the green cluster.
    readySlices: int = 0
    desiredSlices: int = 0
    # Spec hash whose upgrade exhausted the rollback budget; the
    # controller refuses to re-prepare a pending cluster for it until
    # the spec changes again.
    abortedSpecHash: str = ""


@dataclasses.dataclass
class TpuServiceStatus(Serializable):
    serviceStatus: str = ""
    observedGeneration: int = 0
    conditions: List[Condition] = dataclasses.field(default_factory=list)
    activeServiceStatus: Optional[ServiceClusterStatus] = None
    pendingServiceStatus: Optional[ServiceClusterStatus] = None
    numServeEndpoints: int = 0
    lastUpgradeStepTime: float = 0.0
    upgrade: Optional[UpgradeStatus] = None

    @classmethod
    def _nested_types(cls):
        return {"conditions": Condition,
                "activeServiceStatus": ServiceClusterStatus,
                "pendingServiceStatus": ServiceClusterStatus,
                "upgrade": UpgradeStatus}


@dataclasses.dataclass
class TpuService(Serializable):
    apiVersion: str = C.API_VERSION
    kind: str = C.KIND_SERVICE
    metadata: ObjectMeta = dataclasses.field(default_factory=ObjectMeta)
    spec: TpuServiceSpec = dataclasses.field(default_factory=TpuServiceSpec)
    status: TpuServiceStatus = dataclasses.field(default_factory=TpuServiceStatus)

    @classmethod
    def _nested_types(cls):
        return {"metadata": ObjectMeta, "spec": TpuServiceSpec,
                "status": TpuServiceStatus}
