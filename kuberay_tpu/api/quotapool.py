"""QuotaPool: hierarchical multi-tenant chip budgets for gang admission.

Reference capability: the L0 queueing systems the survey names (Volcano
queues, YuniKorn hierarchical queues, Kueue ClusterQueue/LocalQueue
borrowing) — rebuilt TPU-native.  One QuotaPool describes the cluster's
chip capacity and a tenant -> queue tree of guaranteed / borrowable /
ceiling budgets, all denominated in **chips** because on TPU the atomic
schedulable unit is a whole slice and a gang's chip demand is fully
determined by its (accelerator, topology, replicas) shape.

Semantics (enforced by ``controlplane/quota.py``, documented in
``docs/scheduling.md``):

- ``guaranteedChips``: capacity a queue can always claim; admission
  within guarantee may reclaim borrowed capacity from other queues.
- ``ceilingChips``: hard upper bound for the queue (0 = pool total).
- ``borrowable``: whether the queue may exceed its guarantee by
  borrowing idle capacity (borrowed capacity is reclaimable).
- ``starvationBoundSeconds``: any gang pending longer escalates to the
  front of its queue with a borrowed-capacity override.
- ``reclaimNoticeSeconds``: the advance warning an evicted borrower
  receives (the eviction fires the notice->drain->checkpoint path, so
  elastic jobs shrink before they die).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List

from kuberay_tpu.api.common import ObjectMeta, Serializable

KIND_QUOTA_POOL = "QuotaPool"


@dataclasses.dataclass
class QuotaQueue(Serializable):
    name: str = "default"
    guaranteedChips: int = 0       # always-claimable share
    ceilingChips: int = 0          # hard cap; 0 = pool total
    borrowable: bool = True        # may exceed guarantee on idle capacity


@dataclasses.dataclass
class QuotaTenant(Serializable):
    name: str = ""
    queues: List[QuotaQueue] = dataclasses.field(default_factory=list)

    @classmethod
    def _nested_types(cls):
        return {"queues": QuotaQueue}


@dataclasses.dataclass
class QuotaPoolSpec(Serializable):
    totalChips: int = 0                    # pool-wide physical capacity
    starvationBoundSeconds: float = 300.0  # pending-age escalation bound
    reclaimNoticeSeconds: float = 30.0     # eviction advance warning
    tenants: List[QuotaTenant] = dataclasses.field(default_factory=list)

    @classmethod
    def _nested_types(cls):
        return {"tenants": QuotaTenant}


@dataclasses.dataclass
class QuotaPoolStatus(Serializable):
    claimedChips: int = 0
    pendingGangs: int = 0
    conditions: List[Dict[str, str]] = dataclasses.field(
        default_factory=list)


@dataclasses.dataclass
class QuotaPool(Serializable):
    apiVersion: str = "tpu.dev/v1"
    kind: str = KIND_QUOTA_POOL
    metadata: ObjectMeta = dataclasses.field(default_factory=ObjectMeta)
    spec: QuotaPoolSpec = dataclasses.field(default_factory=QuotaPoolSpec)
    status: QuotaPoolStatus = dataclasses.field(
        default_factory=QuotaPoolStatus)

    @classmethod
    def _nested_types(cls):
        return {"metadata": ObjectMeta, "spec": QuotaPoolSpec,
                "status": QuotaPoolStatus}


def validate_quota_pool(pool: QuotaPool) -> List[str]:
    errs: List[str] = []
    if not pool.metadata.name:
        errs.append("metadata.name is required")
    if pool.spec.totalChips <= 0:
        errs.append("spec.totalChips must be > 0")
    if pool.spec.starvationBoundSeconds <= 0:
        errs.append("spec.starvationBoundSeconds must be > 0")
    if pool.spec.reclaimNoticeSeconds < 0:
        errs.append("spec.reclaimNoticeSeconds must be >= 0")
    seen = set()
    for t in pool.spec.tenants:
        if not t.name:
            errs.append("tenant name is required")
        for q in t.queues:
            key = (t.name, q.name)
            if key in seen:
                errs.append(f"duplicate queue {t.name}/{q.name}")
            seen.add(key)
            if q.guaranteedChips < 0:
                errs.append(f"{t.name}/{q.name}: guaranteedChips < 0")
            if q.ceilingChips < 0:
                errs.append(f"{t.name}/{q.name}: ceilingChips < 0")
            if q.ceilingChips and q.guaranteedChips > q.ceilingChips:
                errs.append(f"{t.name}/{q.name}: guaranteed > ceiling")
            if q.ceilingChips > pool.spec.totalChips:
                errs.append(f"{t.name}/{q.name}: ceiling > totalChips")
    total_guaranteed = sum(q.guaranteedChips for t in pool.spec.tenants
                           for q in t.queues)
    if total_guaranteed > pool.spec.totalChips:
        errs.append(f"sum of guaranteedChips ({total_guaranteed}) exceeds "
                    f"totalChips ({pool.spec.totalChips})")
    return errs
