"""TpuCronJob CRD-equivalent types (ref apis/ray/v1/raycronjob_types.go)."""

from __future__ import annotations

import dataclasses
from typing import List

from kuberay_tpu.api.common import Condition, ObjectMeta, Serializable
from kuberay_tpu.api.tpujob import TpuJobSpec
from kuberay_tpu.utils import constants as C


class ConcurrencyPolicy:
    ALLOW = "Allow"
    FORBID = "Forbid"
    REPLACE = "Replace"


@dataclasses.dataclass
class TpuCronJobSpec(Serializable):
    schedule: str = ""                  # standard 5-field cron
    concurrencyPolicy: str = ConcurrencyPolicy.ALLOW
    suspend: bool = False
    startingDeadlineSeconds: int = 0    # missed-run catch-up window
    successfulJobsHistoryLimit: int = 3
    failedJobsHistoryLimit: int = 1
    jobTemplate: TpuJobSpec = dataclasses.field(default_factory=TpuJobSpec)

    @classmethod
    def _nested_types(cls):
        return {"jobTemplate": TpuJobSpec}


@dataclasses.dataclass
class TpuCronJobStatus(Serializable):
    lastScheduleTime: float = 0.0
    lastSuccessfulTime: float = 0.0
    activeJobNames: List[str] = dataclasses.field(default_factory=list)
    conditions: List[Condition] = dataclasses.field(default_factory=list)

    @classmethod
    def _nested_types(cls):
        return {"conditions": Condition}


@dataclasses.dataclass
class TpuCronJob(Serializable):
    apiVersion: str = C.API_VERSION
    kind: str = C.KIND_CRONJOB
    metadata: ObjectMeta = dataclasses.field(default_factory=ObjectMeta)
    spec: TpuCronJobSpec = dataclasses.field(default_factory=TpuCronJobSpec)
    status: TpuCronJobStatus = dataclasses.field(default_factory=TpuCronJobStatus)

    @classmethod
    def _nested_types(cls):
        return {"metadata": ObjectMeta, "spec": TpuCronJobSpec,
                "status": TpuCronJobStatus}
