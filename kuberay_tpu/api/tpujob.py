"""TpuJob CRD-equivalent types.

Job lifecycle state machine mirroring the reference's RayJob
(apis/ray/v1/rayjob_types.go): submission modes (:80-87), deletion strategy
(:108), backoff/deadlines (:209-217,283).  The payload a submitter launches
is a JAX program against the cluster coordinator instead of ``ray job
submit`` against a dashboard.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

from kuberay_tpu.api.common import Condition, ObjectMeta, PodTemplateSpec, Serializable
from kuberay_tpu.api.tpucluster import TpuClusterSpec
from kuberay_tpu.utils import constants as C


class JobSubmissionMode:
    """Ref rayjob_types.go:80-87."""

    K8S_JOB = "K8sJobMode"            # operator creates a submitter Job
    HTTP = "HTTPMode"                 # operator submits via coordinator HTTP
    SIDECAR = "SidecarMode"           # submitter container in head pod
    INTERACTIVE = "InteractiveMode"   # user submits manually


class JobDeploymentStatus:
    """Ref rayjob_controller.go:165-462 state machine states."""

    NEW = "New"
    INITIALIZING = "Initializing"
    WAITING = "Waiting"               # interactive mode: cluster up, no job
    RUNNING = "Running"
    COMPLETE = "Complete"
    FAILED = "Failed"
    SUSPENDING = "Suspending"
    SUSPENDED = "Suspended"
    RETRYING = "Retrying"


class JobStatus:
    """Application-level job status (ref rayv1.JobStatus)."""

    PENDING = "PENDING"
    RUNNING = "RUNNING"
    STOPPED = "STOPPED"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"

    TERMINAL = (STOPPED, SUCCEEDED, FAILED)


class JobFailedReason:
    SUBMISSION_FAILED = "SubmissionFailed"
    DEADLINE_EXCEEDED = "DeadlineExceeded"
    APP_FAILED = "AppFailed"
    VALIDATION_FAILED = "ValidationFailed"


class DeletionPolicyType:
    """Ref DeletionStrategy (rayjob_types.go:108): what to delete when."""

    DELETE_CLUSTER = "DeleteCluster"    # delete the TpuCluster CR
    DELETE_WORKERS = "DeleteWorkers"    # keep head, delete worker slices
    DELETE_SELF = "DeleteSelf"          # delete the TpuJob CR itself
    DELETE_NONE = "DeleteNone"


@dataclasses.dataclass
class DeletionRule(Serializable):
    """Apply ``policy`` ``ttlSeconds`` after the job reaches ``condition``."""

    policy: str = DeletionPolicyType.DELETE_NONE
    condition: str = "Succeeded"        # Succeeded | Failed
    ttlSeconds: int = 0


@dataclasses.dataclass
class DeletionStrategy(Serializable):
    rules: List[DeletionRule] = dataclasses.field(default_factory=list)

    @classmethod
    def _nested_types(cls):
        return {"rules": DeletionRule}


@dataclasses.dataclass
class ElasticPolicy(Serializable):
    """Requeue-vs-shrink when preemption takes slice capacity away and
    no replacement exists (docs/preemption.md):

    - ``shrink``: step the job's cluster down to the surviving slice
      count (data-parallel world-size shrink, floored at
      ``minReplicas``), and restore the original replica count once
      replacement capacity (a ready warm slice) returns;
    - ``requeue``: leave replicas alone and ride the controller's
      replacement provisioning (the default posture without a policy).
    """

    mode: str = "shrink"              # "shrink" | "requeue"
    minReplicas: int = 1


@dataclasses.dataclass
class SubmitterConfig(Serializable):
    """Submitter pod knobs (ref SubmitterPodTemplate + backoff)."""

    template: Optional[PodTemplateSpec] = None
    backoffLimit: int = 2

    @classmethod
    def _nested_types(cls):
        return {"template": PodTemplateSpec}


@dataclasses.dataclass
class TpuJobSpec(Serializable):
    entrypoint: str = ""
    # runtime env: pip/env-vars/working-dir, serialized dict like the ref's
    # RuntimeEnvYAML (rayjob_types.go):
    runtimeEnv: Dict[str, str] = dataclasses.field(default_factory=dict)
    metadata: Dict[str, str] = dataclasses.field(default_factory=dict)
    entrypointNumTpuChips: int = 0      # chips the entrypoint step consumes
    clusterSpec: Optional[TpuClusterSpec] = None
    clusterSelector: Dict[str, str] = dataclasses.field(default_factory=dict)
    submissionMode: str = JobSubmissionMode.K8S_JOB
    submitterConfig: SubmitterConfig = dataclasses.field(default_factory=SubmitterConfig)
    suspend: bool = False
    # Default False like the reference's RayJob, so deletionStrategy works
    # without explicitly opting out of shutdown.
    shutdownAfterJobFinishes: bool = False
    ttlSecondsAfterFinished: int = 0
    activeDeadlineSeconds: int = 0      # whole-job deadline (:209)
    preRunningDeadlineSeconds: int = 0  # deadline to *reach* Running (:283)
    backoffLimit: int = 0               # retries with fresh clusters (:213-217)
    deletionStrategy: Optional[DeletionStrategy] = None
    elastic: Optional[ElasticPolicy] = None
    managedBy: str = ""
    schedulerName: str = ""
    gangSchedulingQueue: str = ""
    # Multi-tenant quota identity, forwarded onto the created cluster:
    tenant: str = ""
    priority: int = 0

    @classmethod
    def _nested_types(cls):
        return {
            "clusterSpec": TpuClusterSpec,
            "submitterConfig": SubmitterConfig,
            "deletionStrategy": DeletionStrategy,
            "elastic": ElasticPolicy,
        }


@dataclasses.dataclass
class TpuJobStatus(Serializable):
    jobId: str = ""
    clusterName: str = ""
    jobStatus: str = ""                  # application-level (JobStatus)
    jobDeploymentStatus: str = JobDeploymentStatus.NEW
    reason: str = ""
    message: str = ""
    startTime: float = 0.0
    endTime: float = 0.0
    succeeded: int = 0
    failed: int = 0                      # retry attempts that failed
    # Replica count before an elastic shrink (0 = not shrunk): the
    # restore target once replacement capacity returns.
    elasticOriginalReplicas: int = 0
    observedGeneration: int = 0
    conditions: List[Condition] = dataclasses.field(default_factory=list)
    clusterStatus: Dict[str, object] = dataclasses.field(default_factory=dict)

    @classmethod
    def _nested_types(cls):
        return {"conditions": Condition}


@dataclasses.dataclass
class TpuJob(Serializable):
    apiVersion: str = C.API_VERSION
    kind: str = C.KIND_JOB
    metadata: ObjectMeta = dataclasses.field(default_factory=ObjectMeta)
    spec: TpuJobSpec = dataclasses.field(default_factory=TpuJobSpec)
    status: TpuJobStatus = dataclasses.field(default_factory=TpuJobStatus)

    @classmethod
    def _nested_types(cls):
        return {"metadata": ObjectMeta, "spec": TpuJobSpec, "status": TpuJobStatus}
