"""JSON-Schema generation from the typed API dataclasses — packaged so
the apiserver can build its OpenAPI contract without a source checkout
(scripts/gen_schema.py and scripts/gen_openapi.py are thin wrappers)."""

from __future__ import annotations

import dataclasses
from typing import Any, Dict


def schema_for(cls, seen=None) -> Dict[str, Any]:
    seen = seen or set()
    if cls in seen:
        return {"type": "object"}   # cycle guard
    seen = seen | {cls}
    props = {}
    nested = cls._nested_types() if hasattr(cls, "_nested_types") else {}
    for f in dataclasses.fields(cls):
        t = f.type if isinstance(f.type, str) else getattr(
            f.type, "__name__", str(f.type))
        nt = nested.get(f.name)
        if nt is not None:
            inner = schema_for(nt, seen)
            if "List" in str(t) or "list" in str(t):
                props[f.name] = {"type": "array", "items": inner}
            else:
                props[f.name] = inner
        elif "int" in str(t):
            props[f.name] = {"type": "integer"}
        elif "float" in str(t):
            props[f.name] = {"type": "number"}
        elif "bool" in str(t):
            props[f.name] = {"type": "boolean"}
        elif "Dict" in str(t) or "dict" in str(t):
            props[f.name] = {"type": "object"}
        elif "List" in str(t) or "list" in str(t):
            props[f.name] = {"type": "array"}
        else:
            props[f.name] = {"type": "string"}
    return {"type": "object", "properties": props}


def crd_schema(cls) -> Dict[str, Any]:
    """Full document for one CRD kind (what docs/crds/*.schema.json hold)."""
    return {
        "$schema": "https://json-schema.org/draft/2020-12/schema",
        "title": cls.__name__,
        "description": (cls.__doc__ or "").strip().splitlines()[0]
        if cls.__doc__ else "",
        **schema_for(cls),
    }
