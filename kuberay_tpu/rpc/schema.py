"""Runtime schema for the tpu.v1 RPC contract.

Loads the serialized FileDescriptorSet that scripts/gen_proto.py emitted
from the checked-in ``proto/tpu/v1/api.proto`` and materializes message
classes from it via the descriptor pool — no generated ``*_pb2.py``
gencode, so the contract file is the single artifact and the protobuf
runtime can move independently (grpc_tools is not available in this
image; protoc + the runtime pool are).

Also provides the dict<->message bridge the server and client share.
json_format is deliberately NOT used: its proto3-JSON mapping renders
int64 as strings and drops/renames in ways that would diverge from the
K8s-style dicts the resource layer speaks.  The converters here follow
the same convention as ``Serializable.to_dict`` (kuberay_tpu/api/common):
scalars always included, empty containers and unset message/optional
fields pruned.
"""

from __future__ import annotations

import pathlib
from typing import Any, Dict

from google.protobuf import descriptor_pb2, descriptor_pool, message_factory
from google.protobuf.descriptor import FieldDescriptor as FD

_BINPB = pathlib.Path(__file__).resolve().parent / "schema.binpb"

_pool = descriptor_pool.DescriptorPool()
_fds = descriptor_pb2.FileDescriptorSet.FromString(_BINPB.read_bytes())
for _file in _fds.file:
    _pool.Add(_file)

_STRUCT = "google.protobuf.Struct"


def message_class(name: str):
    """Message class for a tpu.v1 (or well-known) type name."""
    full = name if "." in name else f"tpu.v1.{name}"
    return message_factory.GetMessageClass(_pool.FindMessageTypeByName(full))


def service_descriptor(name: str):
    return _pool.FindServiceByName(f"tpu.v1.{name}")


# ---------------------------------------------------------------------------
# dict <-> message
# ---------------------------------------------------------------------------

def _is_map(field) -> bool:
    return (field.type == FD.TYPE_MESSAGE
            and field.message_type.GetOptions().map_entry)


def _scalar_to_py(field, value):
    return value


def _py_to_scalar(field, value):
    if field.cpp_type in (FD.CPPTYPE_INT32, FD.CPPTYPE_INT64,
                          FD.CPPTYPE_UINT32, FD.CPPTYPE_UINT64):
        return int(value)          # SimKube/etcd-style string rvs coerce
    if field.cpp_type == FD.CPPTYPE_DOUBLE or \
            field.cpp_type == FD.CPPTYPE_FLOAT:
        return float(value)
    if field.cpp_type == FD.CPPTYPE_BOOL:
        return bool(value)
    if field.cpp_type == FD.CPPTYPE_STRING:
        return value if isinstance(value, str) else str(value)
    return value


def _struct_to_py(struct_msg) -> Any:
    """google.protobuf.Struct/Value/ListValue -> plain JSON value."""
    kind = struct_msg.DESCRIPTOR.full_name
    if kind == "google.protobuf.Struct":
        return {k: _struct_to_py(v) for k, v in struct_msg.fields.items()}
    if kind == "google.protobuf.ListValue":
        return [_struct_to_py(v) for v in struct_msg.values]
    # Value
    which = struct_msg.WhichOneof("kind")
    if which == "null_value" or which is None:
        return None
    if which in ("number_value", "string_value", "bool_value"):
        v = getattr(struct_msg, which)
        if which == "number_value" and float(v).is_integer():
            return int(v)
        return v
    return _struct_to_py(getattr(struct_msg, which))


def _py_to_struct(struct_msg, value):
    """Fill a Struct message from a plain dict."""
    struct_msg.Clear()
    for k, v in (value or {}).items():
        _fill_value(struct_msg.fields[k], v)


def _fill_value(value_msg, v):
    if v is None:
        value_msg.null_value = 0
    elif isinstance(v, bool):
        value_msg.bool_value = v
    elif isinstance(v, (int, float)):
        value_msg.number_value = float(v)
    elif isinstance(v, str):
        value_msg.string_value = v
    elif isinstance(v, dict):
        for k, inner in v.items():
            _fill_value(value_msg.struct_value.fields[k], inner)
        if not v:
            value_msg.struct_value.SetInParent()
    elif isinstance(v, (list, tuple)):
        value_msg.list_value.SetInParent()
        for inner in v:
            _fill_value(value_msg.list_value.values.add(), inner)
    else:
        value_msg.string_value = str(v)


def message_to_dict(msg) -> Dict[str, Any]:
    """K8s-dict convention: scalars always present, empty containers and
    unset message/optional fields pruned (mirrors Serializable.to_dict)."""
    out: Dict[str, Any] = {}
    for field in msg.DESCRIPTOR.fields:
        if _is_map(field):
            m = getattr(msg, field.name)
            if m:
                vf = field.message_type.fields_by_name["value"]
                if vf.type == FD.TYPE_MESSAGE:
                    out[field.name] = {k: message_to_dict(v)
                                       for k, v in m.items()}
                else:
                    out[field.name] = dict(m)
            continue
        if field.is_repeated:
            seq = getattr(msg, field.name)
            if not seq:
                continue
            if field.type == FD.TYPE_MESSAGE:
                if field.message_type.full_name == _STRUCT:
                    out[field.name] = [_struct_to_py(v) for v in seq]
                else:
                    out[field.name] = [message_to_dict(v) for v in seq]
            else:
                out[field.name] = list(seq)
            continue
        if field.type == FD.TYPE_MESSAGE:
            if not msg.HasField(field.name):
                continue
            sub = getattr(msg, field.name)
            if field.message_type.full_name == _STRUCT:
                out[field.name] = _struct_to_py(sub)
            else:
                out[field.name] = message_to_dict(sub)
            continue
        if field.has_presence and not msg.HasField(field.name):
            continue
        out[field.name] = _scalar_to_py(field, getattr(msg, field.name))
    return out


def dict_to_message(d: Dict[str, Any], msg, *,
                    ignore_unknown: bool = False) -> Any:
    """Fill ``msg`` (instance or tpu.v1 type name) from a K8s-style
    dict.  Unknown keys raise ValueError by default — the typed contract
    is the point; a silently-dropped field is a wire bug waiting to be
    found the hard way (this is what caught the reference-SDK numSlices
    drop in round 2).  ``ignore_unknown=True`` is for the server's
    RESPONSE direction only: store objects can carry metadata the
    contract does not model (e.g. SSA managedFields), and a read must
    not 500 on them."""
    if isinstance(msg, str):
        msg = message_class(msg)()
    fields = msg.DESCRIPTOR.fields_by_name
    for key, value in (d or {}).items():
        field = fields.get(key)
        if field is None:
            if ignore_unknown:
                continue
            raise ValueError(
                f"unknown field {key!r} for {msg.DESCRIPTOR.full_name}")
        if value is None:
            continue
        if _is_map(field):
            vf = field.message_type.fields_by_name["value"]
            target = getattr(msg, field.name)
            for k, v in value.items():
                target[str(k)] = _py_to_scalar(vf, v)
            continue
        if field.is_repeated:
            target = getattr(msg, field.name)
            for item in value:
                if field.type == FD.TYPE_MESSAGE:
                    sub = target.add()
                    if field.message_type.full_name == _STRUCT:
                        _py_to_struct(sub, item)
                    else:
                        dict_to_message(item, sub,
                                        ignore_unknown=ignore_unknown)
                else:
                    target.append(_py_to_scalar(field, item))
            continue
        if field.type == FD.TYPE_MESSAGE:
            sub = getattr(msg, field.name)
            if field.message_type.full_name == _STRUCT:
                _py_to_struct(sub, value)
                sub.SetInParent()
            else:
                dict_to_message(value, sub, ignore_unknown=ignore_unknown)
            continue
        setattr(msg, field.name, _py_to_scalar(field, value))
    return msg
