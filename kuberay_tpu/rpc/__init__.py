"""gRPC V1 surface: the versioned, typed RPC contract over the resource
layer (ref proto/*.proto + apiserver/cmd/main.go:97-147 gRPC services).

- ``schema``: loads the checked-in FileDescriptorSet (schema.binpb) and
  exposes message classes + dict<->message converters;
- ``server``: grpc server mapping the five services onto an ObjectStore
  (admission validation included — same gate as the REST front door);
- ``client``: typed client wrapper over a grpc channel.
"""
