"""Typed gRPC client for the tpu.v1 contract (ref proto/go_client — the
reference ships generated clients; here one typed wrapper is resolved
from the same checked-in descriptor set the server uses).

Speaks dicts at the boundary (the resource layer's native currency) and
messages on the wire, so callers never touch protobuf directly:

    rpc = RpcClient("127.0.0.1:8770", token="...")
    rpc.clusters.create(cluster_dict)
    rpc.jobs.list(namespace="prod", limit=50)
    rpc.services.delete("demo")

Errors map back to the store's exception types (NOT_FOUND -> NotFound,
ALREADY_EXISTS -> AlreadyExists, INVALID_ARGUMENT -> Invalid, ABORTED ->
Conflict) so SDK code paths are front-door agnostic.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import grpc

from kuberay_tpu.controlplane.store import (AlreadyExists, Conflict,
                                            Invalid, NotFound, StoreError)
from kuberay_tpu.rpc import schema

_CODE_MAP = {
    grpc.StatusCode.NOT_FOUND: NotFound,
    grpc.StatusCode.ALREADY_EXISTS: AlreadyExists,
    grpc.StatusCode.INVALID_ARGUMENT: Invalid,
    grpc.StatusCode.ABORTED: Conflict,
}


def _raise_mapped(err: grpc.RpcError):
    exc = _CODE_MAP.get(err.code())
    if exc is not None:
        raise exc(err.details()) from None
    raise StoreError(f"rpc failed: {err.code().name}: "
                     f"{err.details()}") from None


class _KindClient:
    def __init__(self, channel, service: str, suffix: str, field: str,
                 token: str):
        self._channel = channel
        self._service = service
        self._suffix = suffix
        self._field = field
        self._meta = [("authorization", f"Bearer {token}")] if token else []
        self._stubs: Dict[str, Any] = {}
        sd = schema.service_descriptor(service)
        for m in sd.methods:
            out_cls = schema.message_class(m.output_type.full_name)
            self._stubs[m.name] = channel.unary_unary(
                f"/tpu.v1.{service}/{m.name}",
                request_serializer=lambda msg: msg.SerializeToString(),
                response_deserializer=out_cls.FromString)

    def _call(self, method: str, request):
        try:
            return self._stubs[method](request, metadata=self._meta)
        except grpc.RpcError as e:
            _raise_mapped(e)

    # -- verbs ----------------------------------------------------------

    def create(self, obj: Dict[str, Any],
               namespace: str = "") -> Dict[str, Any]:
        req = schema.message_class(f"Create{self._suffix}Request")()
        schema.dict_to_message(obj, getattr(req, self._field))
        req.namespace = namespace
        return schema.message_to_dict(self._call(f"Create{self._suffix}",
                                                 req))

    def get(self, name: str, namespace: str = "default") -> Dict[str, Any]:
        req = schema.message_class("GetRequest")()
        req.name, req.namespace = name, namespace
        return schema.message_to_dict(self._call(f"Get{self._suffix}", req))

    def update(self, obj: Dict[str, Any],
               namespace: str = "") -> Dict[str, Any]:
        if f"Update{self._suffix}" not in self._stubs:
            raise StoreError(
                f"{self._service} defines no Update{self._suffix} RPC")
        req = schema.message_class(f"Update{self._suffix}Request")()
        schema.dict_to_message(obj, getattr(req, self._field))
        req.namespace = namespace
        return schema.message_to_dict(self._call(f"Update{self._suffix}",
                                                 req))

    def delete(self, name: str, namespace: str = "default") -> bool:
        req = schema.message_class("DeleteRequest")()
        req.name, req.namespace = name, namespace
        return self._call(f"Delete{self._suffix}", req).deleted

    def list(self, namespace: str = "default", limit: int = 0,
             continue_token: str = "",
             all_namespaces: bool = False
             ) -> Tuple[List[Dict[str, Any]], str]:
        req = schema.message_class("ListRequest")()
        req.namespace = namespace
        req.limit = limit
        req.continue_token = continue_token
        method = (f"ListAll{self._suffix}s" if all_namespaces
                  else f"List{self._suffix}s")
        resp = self._call(method, req)
        return ([schema.message_to_dict(i) for i in resp.items],
                resp.continue_token)

    def list_all_pages(self, namespace: str = "default", page_size: int = 0,
                       all_namespaces: bool = False
                       ) -> List[Dict[str, Any]]:
        """Follow continue tokens to exhaustion."""
        out: List[Dict[str, Any]] = []
        token = ""
        while True:
            items, token = self.list(namespace, page_size, token,
                                     all_namespaces)
            out.extend(items)
            if not token:
                return out


class RpcClient:
    """One channel, five typed kind clients."""

    def __init__(self, address: str, token: str = "",
                 credentials: Optional[grpc.ChannelCredentials] = None):
        if credentials is not None:
            self.channel = grpc.secure_channel(address, credentials)
        else:
            self.channel = grpc.insecure_channel(address)
        self.clusters = _KindClient(self.channel, "TpuClusterService",
                                    "Cluster", "cluster", token)
        self.jobs = _KindClient(self.channel, "TpuJobService", "Job",
                                "job", token)
        self.services = _KindClient(self.channel, "TpuServeService",
                                    "Service", "service", token)
        self.cronjobs = _KindClient(self.channel, "TpuCronJobService",
                                    "CronJob", "cronjob", token)
        self.compute_templates = _KindClient(
            self.channel, "ComputeTemplateService", "ComputeTemplate",
            "template", token)

    def close(self):
        self.channel.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
