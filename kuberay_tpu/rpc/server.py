"""gRPC V1 server: the typed front door over the resource layer
(ref apiserver/cmd/main.go:97-147 — ClusterServiceServer,
RayJobServiceServer, RayServeServiceServer registrations; here the five
tpu.v1 services map onto an ObjectStore, local or REST-backed).

Built with generic handlers resolved from the checked-in descriptor set
(kuberay_tpu/rpc/schema.py), so there is no generated service gencode to
drift from the contract.  Behavior parity with the REST front door:

- admission validation runs on create/update (same
  ``validate_admission`` gate — one validation surface, three front
  doors now: REST, webhook, gRPC);
- store errors map onto canonical gRPC codes (NotFound -> NOT_FOUND,
  AlreadyExists -> ALREADY_EXISTS, Invalid -> INVALID_ARGUMENT,
  Conflict -> ABORTED, like the reference's grpc-gateway mapping);
- optional bearer-token auth via call metadata (``authorization: Bearer
  <token>``), mirroring the REST server's token gate.

Pagination: ``limit``/``continue_token`` slice a name-sorted listing;
the token is the opaque offset of the next page.

    python -m kuberay_tpu.rpc.server --port 8770 [--token-file ...]
"""

from __future__ import annotations

import hmac
import threading
from concurrent import futures
from typing import Any, Callable, Dict, List, Optional, Tuple

import grpc

from kuberay_tpu.controlplane.store import (AlreadyExists, Conflict,
                                            Invalid, NotFound, ObjectStore)
from kuberay_tpu.controlplane.webhooks import validate_admission
from kuberay_tpu.api.computetemplate import ComputeTemplate
from kuberay_tpu.api.tpucluster import TpuCluster
from kuberay_tpu.api.tpucronjob import TpuCronJob
from kuberay_tpu.api.tpujob import TpuJob
from kuberay_tpu.api.tpuservice import TpuService
from kuberay_tpu.rpc import schema
from kuberay_tpu.utils import constants as C

# (service, rpc-prefix, request field, kind, apiVersion)
_SURFACES = (
    ("TpuClusterService", "Cluster", "cluster", C.KIND_CLUSTER),
    ("TpuJobService", "Job", "job", C.KIND_JOB),
    ("TpuServeService", "Service", "service", C.KIND_SERVICE),
    ("TpuCronJobService", "CronJob", "cronjob", C.KIND_CRONJOB),
    ("ComputeTemplateService", "ComputeTemplate", "template",
     "ComputeTemplate"),
)

_KIND_MSG = {
    C.KIND_CLUSTER: "TpuCluster",
    C.KIND_JOB: "TpuJob",
    C.KIND_SERVICE: "TpuService",
    C.KIND_CRONJOB: "TpuCronJob",
    "ComputeTemplate": "ComputeTemplate",
}

_KIND_CLS = {
    C.KIND_CLUSTER: TpuCluster,
    C.KIND_JOB: TpuJob,
    C.KIND_SERVICE: TpuService,
    C.KIND_CRONJOB: TpuCronJob,
    "ComputeTemplate": ComputeTemplate,
}


def _abort(context, exc):
    if isinstance(exc, NotFound):
        context.abort(grpc.StatusCode.NOT_FOUND, str(exc))
    if isinstance(exc, AlreadyExists):
        context.abort(grpc.StatusCode.ALREADY_EXISTS, str(exc))
    if isinstance(exc, Invalid) or isinstance(exc, ValueError):
        context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(exc))
    if isinstance(exc, Conflict):
        context.abort(grpc.StatusCode.ABORTED, str(exc))
    raise exc


class _KindService:
    """The six verb implementations for one kind."""

    def __init__(self, store: ObjectStore, kind: str, field: str):
        self.store = store
        self.kind = kind
        self.field = field
        self.msg_name = _KIND_MSG[kind]

    # -- helpers --------------------------------------------------------

    def _to_msg(self, obj: Dict[str, Any]):
        # Responses: store objects can carry metadata outside the typed
        # contract (SSA managedFields) — skip, never 500.  SSA-aware
        # clients use the REST front door.
        return schema.dict_to_message(obj, self.msg_name,
                                      ignore_unknown=True)

    def _obj_from_req(self, request, context) -> Dict[str, Any]:
        if not request.HasField(self.field):
            context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                          f"request.{self.field} must be set")
        obj = schema.message_to_dict(getattr(request, self.field))
        obj.setdefault("apiVersion", C.API_VERSION)
        obj["kind"] = self.kind
        md = obj.setdefault("metadata", {})
        if request.namespace:
            md["namespace"] = request.namespace
        md.setdefault("namespace", "default")
        # Canonicalize through the typed layer: defaults filled, empties
        # pruned — exactly the shape the REST path stores.  Without this
        # a get->update round trip densifies the spec and spuriously
        # bumps metadata.generation (store compares spec dicts).
        obj = _KIND_CLS[self.kind].from_dict(obj).to_dict()
        return obj

    # -- verbs ----------------------------------------------------------

    def create(self, request, context):
        obj = self._obj_from_req(request, context)
        errs = validate_admission(obj, None)
        if errs:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, "; ".join(errs))
        try:
            return self._to_msg(self.store.create(obj))
        except Exception as e:  # noqa: BLE001 — mapped to status codes
            _abort(context, e)

    def get(self, request, context):
        try:
            return self._to_msg(self.store.get(
                self.kind, request.name, request.namespace or "default"))
        except Exception as e:  # noqa: BLE001
            _abort(context, e)

    def update(self, request, context):
        obj = self._obj_from_req(request, context)
        old = self.store.try_get(self.kind, obj["metadata"].get("name", ""),
                                 obj["metadata"]["namespace"])
        errs = validate_admission(obj, old)
        if errs:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, "; ".join(errs))
        try:
            return self._to_msg(self.store.update(obj))
        except Exception as e:  # noqa: BLE001
            _abort(context, e)

    def delete(self, request, context):
        resp = schema.message_class("DeleteResponse")()
        try:
            self.store.delete(self.kind, request.name,
                              request.namespace or "default")
        except Exception as e:  # noqa: BLE001
            _abort(context, e)
        resp.deleted = True
        return resp

    def _list(self, request, context, namespace: Optional[str]):
        items: List[Dict[str, Any]] = sorted(
            self.store.list(self.kind, namespace),
            key=lambda o: (o["metadata"].get("namespace", ""),
                           o["metadata"].get("name", "")))
        if request.limit < 0:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                          "limit must be >= 0")
        start = 0
        if request.continue_token:
            try:
                start = int(request.continue_token)
            except ValueError:
                start = -1
            if start < 0:
                context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                              "bad continue_token")
        end = start + request.limit if request.limit else len(items)
        return items[start:end], (str(end) if end < len(items) else "")


class RpcServer:
    """Five services over one store; grpc.server lifecycle wrapper."""

    def __init__(self, store: ObjectStore, token: str = ""):
        self.store = store
        self.token = token

    # -- handler construction -------------------------------------------

    def _handlers(self):
        out = []
        for svc_name, rpc_suffix, field, kind in _SURFACES:
            svc = _KindService(self.store, kind, field)
            sd = schema.service_descriptor(svc_name)
            method_impls: Dict[str, Tuple[Callable, Any, Any]] = {}
            for m in sd.methods:
                req_cls = schema.message_class(m.input_type.full_name)
                out_cls = schema.message_class(m.output_type.full_name)
                fn = self._bind(svc, m.name, rpc_suffix, out_cls)
                method_impls[m.name] = grpc.unary_unary_rpc_method_handler(
                    fn, request_deserializer=req_cls.FromString,
                    response_serializer=lambda msg: msg.SerializeToString())
            out.append(grpc.method_handlers_generic_handler(
                f"tpu.v1.{svc_name}", method_impls))
        return out

    def _bind(self, svc: _KindService, method: str, suffix: str, out_cls):
        def list_fn(namespace_from_req: bool):
            def fn(request, context):
                self._authz(context)
                ns = (request.namespace or "default") \
                    if namespace_from_req else None
                items, cont = svc._list(request, context, ns)
                resp = out_cls()
                for obj in items:
                    schema.dict_to_message(obj, resp.items.add())
                resp.continue_token = cont
                return resp
            return fn

        if method == f"List{suffix}s":
            return list_fn(True)
        if method == f"ListAll{suffix}s":
            return list_fn(False)
        verb = {f"Create{suffix}": svc.create, f"Get{suffix}": svc.get,
                f"Update{suffix}": svc.update,
                f"Delete{suffix}": svc.delete}[method]

        def fn(request, context):
            self._authz(context)
            return verb(request, context)
        return fn

    def _authz(self, context):
        if not self.token:
            return
        md = dict(context.invocation_metadata())
        # Constant-time compare: a '!=' short-circuits at the first
        # differing byte, leaking the token prefix length through
        # response timing (byte-by-byte brute force over the network).
        if not hmac.compare_digest(md.get("authorization", ""),
                                   f"Bearer {self.token}"):
            context.abort(grpc.StatusCode.UNAUTHENTICATED,
                          "missing or invalid bearer token")

    # -- lifecycle ------------------------------------------------------

    def start(self, host: str = "127.0.0.1", port: int = 0,
              max_workers: int = 16) -> Tuple[grpc.Server, str]:
        server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers))
        for h in self._handlers():
            server.add_generic_rpc_handlers((h,))
        bound = server.add_insecure_port(f"{host}:{port}")
        server.start()
        return server, f"{host}:{bound}"


def serve_background(store: ObjectStore, token: str = "",
                     host: str = "127.0.0.1", port: int = 0):
    return RpcServer(store, token=token).start(host=host, port=port)


def main(argv=None) -> int:  # pragma: no cover - thin process wrapper
    import argparse
    ap = argparse.ArgumentParser(prog="tpu-rpc-server")
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int, default=8770)
    ap.add_argument("--token", default="")
    ap.add_argument("--token-file", default="")
    ap.add_argument("--journal", default="",
                    help="durable journal path for the backing store")
    args = ap.parse_args(argv)
    token = args.token
    if args.token_file:
        with open(args.token_file) as f:
            token = f.read().strip()
    store = ObjectStore(journal_path=args.journal)
    server, addr = RpcServer(store, token=token).start(
        host=args.host, port=args.port)
    print(f"tpu-rpc-server listening on {addr}", flush=True)
    stop = threading.Event()
    try:
        stop.wait()
    except KeyboardInterrupt:
        server.stop(grace=2.0)
    return 0


if __name__ == "__main__":  # pragma: no cover
    import sys
    sys.exit(main())
