"""CLI: ``python -m kuberay_tpu.analysis [paths...]``.

Exit code 0 when clean, 1 when findings remain, 2 on usage errors —
suitable for CI gates and the tools/lint.sh wrapper.

``--changed-only`` lints just the files git reports as modified or
untracked — *unless* the call graph shows an unchanged file calling
into a changed one, in which case the whole repo is linted anyway
(a wrapper you edited may have broken a seam its callers rely on).
The graph is always built from every file, so whole-program rules see
full chains either way; only the reported file set narrows.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
from typing import List, Optional, Set

from kuberay_tpu.analysis.core import (RULES, analyze_paths,
                                       iter_python_files)
from kuberay_tpu.analysis.graph import build_graph, parse_cached
from kuberay_tpu.analysis.reporters import (render_human, render_json,
                                            render_rule_list)


def _git_changed_files() -> Optional[Set[str]]:
    """Absolute paths of .py files modified vs HEAD or untracked;
    None when git is unavailable (caller falls back to whole-repo)."""
    out: Set[str] = set()
    for cmd in (["git", "diff", "--name-only", "HEAD"],
                ["git", "ls-files", "--others", "--exclude-standard"]):
        try:
            proc = subprocess.run(cmd, capture_output=True, text=True,
                                  check=True)
        except (OSError, subprocess.CalledProcessError):
            return None
        for line in proc.stdout.splitlines():
            line = line.strip()
            if line.endswith(".py"):
                out.add(os.path.abspath(line))
    return out


def _changed_restriction(paths: List[str]) -> Optional[Set[str]]:
    """The file set to report on, or None for whole-repo (no changes
    is reported as an empty set; the caller exits clean)."""
    changed_abs = _git_changed_files()
    if changed_abs is None:
        print("kuberay-lint: --changed-only: git unavailable, "
              "linting whole repo", file=sys.stderr)
        return None
    all_files = list(iter_python_files(paths))
    changed = {f for f in all_files if os.path.abspath(f) in changed_abs}
    if not changed:
        return set()
    triples = []
    for f in all_files:
        with open(f, encoding="utf-8", errors="replace") as fh:
            source = fh.read()
        try:
            triples.append((f, source, parse_cached(source, f)))
        except SyntaxError:
            continue  # analyze_paths reports it
    graph = build_graph(triples)
    for qual in sorted(graph.functions):
        fn = graph.functions[qual]
        if fn.path not in changed:
            continue
        for site in graph.callers(qual):
            caller = graph.functions[site.caller]
            if caller.path not in changed:
                print(f"kuberay-lint: --changed-only: {fn.path} has "
                      f"callers in unchanged {caller.path}; linting "
                      "whole repo", file=sys.stderr)
                return None
    return changed


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m kuberay_tpu.analysis",
        description="kuberay-tpu reconcile-invariant static analyzer")
    ap.add_argument("paths", nargs="*", default=["kuberay_tpu"],
                    help="files or directories to analyze "
                         "(default: kuberay_tpu)")
    ap.add_argument("--format", choices=("human", "json"), default="human")
    ap.add_argument("--rules", default="",
                    help="comma-separated subset of rules to run")
    ap.add_argument("--keep-suppressed", action="store_true",
                    help="report findings even when a suppression "
                         "comment matches (audit mode)")
    ap.add_argument("--changed-only", action="store_true",
                    help="report only on git-changed files (falls back "
                         "to whole-repo when unchanged callers depend "
                         "on a changed file)")
    ap.add_argument("--list-rules", action="store_true",
                    help="list registered rules and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        print(render_rule_list())
        return 0

    only = None
    if args.rules:
        only = [r.strip() for r in args.rules.split(",") if r.strip()]
        unknown = [r for r in only if r not in RULES]
        if unknown:
            print(f"unknown rule(s): {', '.join(unknown)}; "
                  f"known: {', '.join(sorted(RULES))}", file=sys.stderr)
            return 2

    paths = args.paths or ["kuberay_tpu"]
    restrict: Optional[Set[str]] = None
    if args.changed_only:
        restrict = _changed_restriction(paths)
        if restrict is not None and not restrict:
            print("kuberay-lint: clean (0 findings) [no changed files]")
            return 0

    report = analyze_paths(paths, only=only,
                           keep_suppressed=args.keep_suppressed,
                           restrict_to=restrict)
    out = (render_json(report.findings, report.suppressed_counts)
           if args.format == "json"
           else render_human(report.findings, report.suppressed_counts))
    print(out)
    return 1 if report.findings else 0


if __name__ == "__main__":
    sys.exit(main())
