"""CLI: ``python -m kuberay_tpu.analysis [paths...]``.

Exit code 0 when clean, 1 when findings remain, 2 on usage errors —
suitable for CI gates and the tools/lint.sh wrapper.
"""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional

from kuberay_tpu.analysis.core import RULES, run_paths
from kuberay_tpu.analysis.reporters import (render_human, render_json,
                                            render_rule_list)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m kuberay_tpu.analysis",
        description="kuberay-tpu reconcile-invariant static analyzer")
    ap.add_argument("paths", nargs="*", default=["kuberay_tpu"],
                    help="files or directories to analyze "
                         "(default: kuberay_tpu)")
    ap.add_argument("--format", choices=("human", "json"), default="human")
    ap.add_argument("--rules", default="",
                    help="comma-separated subset of rules to run")
    ap.add_argument("--keep-suppressed", action="store_true",
                    help="report findings even when a suppression "
                         "comment matches (audit mode)")
    ap.add_argument("--list-rules", action="store_true",
                    help="list registered rules and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        print(render_rule_list())
        return 0

    only = None
    if args.rules:
        only = [r.strip() for r in args.rules.split(",") if r.strip()]
        unknown = [r for r in only if r not in RULES]
        if unknown:
            print(f"unknown rule(s): {', '.join(unknown)}; "
                  f"known: {', '.join(sorted(RULES))}", file=sys.stderr)
            return 2

    findings = run_paths(args.paths or ["kuberay_tpu"], only=only,
                         keep_suppressed=args.keep_suppressed)
    out = (render_json(findings) if args.format == "json"
           else render_human(findings))
    print(out)
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
