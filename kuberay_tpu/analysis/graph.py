"""Whole-program symbol table + call graph for the invariant linter.

The per-file AST rules in :mod:`kuberay_tpu.analysis.rules` see one
module at a time, so a one-line wrapper function defeats any of the
seam-funnel rules.  This module gives rules the whole program:

- a **symbol table** of every module-level function, class, and method
  under the analyzed roots (qualnames are ``module:Class.method`` /
  ``module:function``);
- a **call graph** whose edges resolve ``self.method()`` calls through
  the enclosing class (and its project bases), ``self.attr.method()``
  through constructor-assigned attribute types, local ``var = Cls()``
  instances, plain and ``from``-imported module functions, constructor
  calls, and **bound-method references** passed as call arguments — the
  ``manager.register(kind, self.cluster_controller.reconcile)`` /
  ``threading.Thread(target=self._loop)`` registration idiom the
  controllers and the sim harness are built on;
- **normalized external call names** per function (import aliases
  rewritten to real module paths, ``from x import y`` rewritten to
  ``x.y``), which is what the nondeterminism / blocking sinks match
  against.

Per-file extraction is cached by content hash (sha256 of the source),
so the pytest gate, the CLI, and ``--changed-only`` runs share parses
within a process and whole-repo runs stay fast.

The graph is deliberately conservative in both directions: an edge is
added only when the target resolves to a project symbol (no guessing),
and reference edges over-approximate reachability (a callback that is
registered but never fired still counts as reachable — for determinism
and seam analysis that is the safe side).
"""

from __future__ import annotations

import ast
import hashlib
from typing import Dict, Iterable, List, Optional, Set, Tuple

__all__ = ["ProjectGraph", "FunctionNode", "ClassNode", "CallSite",
           "build_graph"]


# ---------------------------------------------------------------------------
# small AST helpers (kept local so graph.py has no import cycle with rules)
# ---------------------------------------------------------------------------

def _dotted(node: ast.AST) -> str:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _module_name_for(path: str) -> str:
    """Dotted module name for a file path: the part from the last
    well-known package root down (``kuberay_tpu.controlplane.store``),
    falling back to the bare stem for loose fixture files."""
    norm = path.replace("\\", "/")
    if norm.endswith(".py"):
        norm = norm[:-3]
    parts = [p for p in norm.split("/") if p and p != "."]
    for anchor in ("kuberay_tpu", "tests", "benchmark", "tools"):
        if anchor in parts:
            parts = parts[parts.index(anchor):]
            break
    else:
        parts = parts[-1:]
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


# ---------------------------------------------------------------------------
# data model
# ---------------------------------------------------------------------------

class CallSite:
    """One resolved edge: ``caller`` invokes (or references) ``callee``
    at ``path:line``.  ``kind`` is 'call' for an invocation, 'ref' for a
    bound-method reference passed as an argument (callback registration)."""

    __slots__ = ("caller", "callee", "path", "line", "col", "kind")

    def __init__(self, caller: str, callee: str, path: str, line: int,
                 col: int, kind: str = "call"):
        self.caller = caller
        self.callee = callee
        self.path = path
        self.line = line
        self.col = col
        self.kind = kind

    def __repr__(self):  # pragma: no cover - debugging aid
        return (f"CallSite({self.caller} -> {self.callee} "
                f"@ {self.path}:{self.line} [{self.kind}])")


class FunctionNode:
    """A module function, method, or nested function."""

    __slots__ = ("qualname", "name", "module", "path", "line", "node",
                 "class_qualname", "raw_calls")

    def __init__(self, qualname, name, module, path, line, node,
                 class_qualname):
        self.qualname = qualname
        self.name = name
        self.module = module
        self.path = path
        self.line = line
        self.node = node
        self.class_qualname = class_qualname
        #: normalized external call names: (dotted, line, col, call node)
        self.raw_calls: List[Tuple[str, int, int, ast.Call]] = []


class ClassNode:
    __slots__ = ("qualname", "name", "module", "path", "line", "bases",
                 "methods", "attr_types", "class_attrs")

    def __init__(self, qualname, name, module, path, line, bases):
        self.qualname = qualname
        self.name = name
        self.module = module
        self.path = path
        self.line = line
        #: base-class names as written (resolved lazily via imports)
        self.bases: List[str] = bases
        #: method name -> function qualname
        self.methods: Dict[str, str] = {}
        #: self.<attr> -> class qualname (from ctor assignments)
        self.attr_types: Dict[str, str] = {}
        #: names of class-level attributes (KIND etc.)
        self.class_attrs: Set[str] = set()


class _ModuleSummary:
    """Everything graph construction needs from one file, extracted in a
    single AST pass and cached by content hash."""

    __slots__ = ("path", "module", "import_aliases", "from_imports",
                 "functions", "classes", "tree")

    def __init__(self, path: str, module: str, tree: ast.Module):
        self.path = path
        self.module = module
        self.tree = tree
        #: local alias -> real dotted module ("np" -> "numpy")
        self.import_aliases: Dict[str, str] = {}
        #: local name -> (module, attr) for ``from m import a [as b]``
        self.from_imports: Dict[str, Tuple[str, str]] = {}
        self.functions: Dict[str, FunctionNode] = {}
        self.classes: Dict[str, ClassNode] = {}
        self._extract()

    # -- extraction ------------------------------------------------------

    def _extract(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    self.import_aliases[alias.asname or
                                        alias.name.split(".")[0]] = \
                        alias.name if alias.asname else \
                        alias.name.split(".")[0]
                    if alias.asname:
                        self.import_aliases[alias.asname] = alias.name
            elif isinstance(node, ast.ImportFrom):
                if node.module is None:
                    continue
                for alias in node.names:
                    self.from_imports[alias.asname or alias.name] = (
                        node.module, alias.name)
        self._extract_scope(self.tree.body, prefix="", class_node=None)

    def _extract_scope(self, body, prefix: str,
                       class_node: Optional[ClassNode]) -> None:
        for node in body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{self.module}:{prefix}{node.name}"
                fn = FunctionNode(qual, node.name, self.module, self.path,
                                  node.lineno, node,
                                  class_node.qualname if class_node else None)
                self.functions[qual] = fn
                if class_node is not None:
                    class_node.methods.setdefault(node.name, qual)
                # nested defs get their own nodes (edges resolved later)
                self._extract_scope(node.body, prefix + node.name + ".",
                                    class_node=None)
            elif isinstance(node, ast.ClassDef):
                qual = f"{self.module}:{prefix}{node.name}"
                cls = ClassNode(qual, node.name, self.module, self.path,
                                node.lineno,
                                [_dotted(b) for b in node.bases if _dotted(b)])
                self.classes[qual] = cls
                for stmt in node.body:
                    if isinstance(stmt, ast.Assign):
                        for tgt in stmt.targets:
                            if isinstance(tgt, ast.Name):
                                cls.class_attrs.add(tgt.id)
                    elif isinstance(stmt, ast.AnnAssign) and \
                            isinstance(stmt.target, ast.Name):
                        cls.class_attrs.add(stmt.target.id)
                self._extract_scope(node.body, prefix + node.name + ".",
                                    class_node=cls)


#: content-hash -> parsed tree (shared with core.analyze via parse_cached)
_TREE_CACHE: Dict[str, ast.Module] = {}
#: (content-hash, path) -> _ModuleSummary.  The path is part of the key:
#: two identical files at different paths must not share a summary, or
#: the second one's FunctionNodes would report the first one's location.
_SUMMARY_CACHE: Dict[Tuple[str, str], _ModuleSummary] = {}


def content_hash(source: str) -> str:
    return hashlib.sha256(source.encode("utf-8", "replace")).hexdigest()


def parse_cached(source: str, path: str) -> ast.Module:
    """``ast.parse`` with a content-hash cache: the pytest gate, the
    CLI, and repeated whole-program passes share one parse per file
    version.  Raises ``SyntaxError`` like ``ast.parse``."""
    key = content_hash(source)
    tree = _TREE_CACHE.get(key)
    if tree is None:
        tree = ast.parse(source, filename=path)
        _TREE_CACHE[key] = tree
    return tree


def _summarize(path: str, source: str, tree: ast.Module) -> _ModuleSummary:
    module = _module_name_for(path)
    key = (content_hash(source), path)
    summary = _SUMMARY_CACHE.get(key)
    if summary is None:
        summary = _ModuleSummary(path, module, tree)
        _SUMMARY_CACHE[key] = summary
    return summary


# ---------------------------------------------------------------------------
# the graph
# ---------------------------------------------------------------------------

class ProjectGraph:
    """Symbol table + resolved call graph over a set of parsed files."""

    def __init__(self):
        self.functions: Dict[str, FunctionNode] = {}
        self.classes: Dict[str, ClassNode] = {}
        #: caller qualname -> outgoing edges (deterministic order)
        self.edges: Dict[str, List[CallSite]] = {}
        #: callee qualname -> incoming edges
        self.redges: Dict[str, List[CallSite]] = {}
        self._modules: Dict[str, _ModuleSummary] = {}
        #: bare class name -> [qualnames] (cross-module resolution)
        self._class_by_name: Dict[str, List[str]] = {}
        self._func_by_modname: Dict[Tuple[str, str], str] = {}

    # -- construction ----------------------------------------------------

    def add_file(self, path: str, source: str, tree: ast.Module) -> None:
        summary = _summarize(path, source, tree)
        self._modules[summary.module] = summary
        self.functions.update(summary.functions)
        self.classes.update(summary.classes)
        for qual, cls in summary.classes.items():
            self._class_by_name.setdefault(cls.name, []).append(qual)
        for qual, fn in summary.functions.items():
            self._func_by_modname[(fn.module, fn.name)] = qual

    def finalize(self) -> None:
        """Resolve attribute types, then every call site.  Idempotent
        per build; call once after the last ``add_file``."""
        for cls in self.classes.values():
            self._infer_attr_types(cls)
        for qual in sorted(self.functions):
            self._resolve_function(self.functions[qual])

    # -- symbol resolution ----------------------------------------------

    def _lookup_class(self, name: str, module: str) -> Optional[str]:
        """Resolve a (possibly dotted) class name as seen from
        ``module`` to a project class qualname."""
        if not name:
            return None
        summary = self._modules.get(module)
        head, _, rest = name.partition(".")
        if summary is not None:
            if head in summary.from_imports and not rest:
                src_mod, attr = summary.from_imports[head]
                qual = f"{src_mod}:{attr}"
                if qual in self.classes:
                    return qual
                # from-import of a re-export: fall through to bare-name
            if head in summary.import_aliases and rest:
                qual = f"{summary.import_aliases[head]}:{rest}"
                if qual in self.classes:
                    return qual
        qual = f"{module}:{name}"
        if qual in self.classes:
            return qual
        # unique bare name anywhere in the project
        cands = self._class_by_name.get(name.split(".")[-1], [])
        if len(cands) == 1:
            return cands[0]
        return None

    def resolve_method(self, class_qual: str, method: str,
                       _seen: Optional[Set[str]] = None) -> Optional[str]:
        """Method lookup through the project-local MRO (depth-first over
        declared bases)."""
        cls = self.classes.get(class_qual)
        if cls is None:
            return None
        if method in cls.methods:
            return cls.methods[method]
        seen = _seen or set()
        seen.add(class_qual)
        for base in cls.bases:
            base_qual = self._lookup_class(base, cls.module)
            if base_qual and base_qual not in seen:
                hit = self.resolve_method(base_qual, method, seen)
                if hit:
                    return hit
        return None

    def _infer_attr_types(self, cls: ClassNode) -> None:
        """``self.x = ClassName(...)`` in any method (plus annotated
        ``self.x: ClassName``) types the attribute for
        ``self.x.method()`` resolution."""
        for mname, fq in cls.methods.items():
            fn = self.functions.get(fq)
            if fn is None:
                continue
            for node in ast.walk(fn.node):
                target = value = None
                if isinstance(node, ast.Assign) and len(node.targets) == 1:
                    target, value = node.targets[0], node.value
                elif isinstance(node, ast.AnnAssign):
                    target, value = node.target, node.value
                if not (isinstance(target, ast.Attribute) and
                        isinstance(target.value, ast.Name) and
                        target.value.id == "self"):
                    continue
                typ = None
                if isinstance(value, ast.Call):
                    typ = self._lookup_class(_dotted(value.func), cls.module)
                if typ is None and isinstance(node, ast.AnnAssign):
                    ann = node.annotation
                    ann_name = _dotted(ann) if not isinstance(
                        ann, ast.Subscript) else _dotted(ann.value)
                    if ann_name not in ("Optional", "List", "Dict"):
                        typ = self._lookup_class(ann_name, cls.module)
                if typ is not None:
                    cls.attr_types.setdefault(target.attr, typ)

    # -- call resolution -------------------------------------------------

    def _normalize(self, dotted: str, module: str) -> str:
        """Rewrite the leading segment through the module's import
        table: ``_time.sleep`` -> ``time.sleep``, ``dt.now`` ->
        ``datetime.now``, from-imported ``sleep`` -> ``time.sleep``."""
        if not dotted:
            return dotted
        summary = self._modules.get(module)
        if summary is None:
            return dotted
        head, _, rest = dotted.partition(".")
        if head in summary.from_imports:
            src_mod, attr = summary.from_imports[head]
            base = f"{src_mod}.{attr}"
            return f"{base}.{rest}" if rest else base
        if head in summary.import_aliases:
            real = summary.import_aliases[head]
            return f"{real}.{rest}" if rest else real
        return dotted

    def _receiver_type(self, expr: ast.AST, fn: FunctionNode,
                       local_types: Dict[str, str]) -> Optional[str]:
        """Class qualname of the value of ``expr`` inside ``fn``:
        ``self``, ``self.attr[.attr...]``, or a locally-typed name."""
        if isinstance(expr, ast.Name):
            if expr.id == "self" and fn.class_qualname:
                return fn.class_qualname
            return local_types.get(expr.id)
        if isinstance(expr, ast.Attribute):
            base_type = self._receiver_type(expr.value, fn, local_types)
            if base_type is None:
                return None
            cls = self.classes.get(base_type)
            if cls is None:
                return None
            return cls.attr_types.get(expr.attr)
        return None

    def _resolve_callable_expr(self, expr: ast.AST, fn: FunctionNode,
                               local_types: Dict[str, str]
                               ) -> Optional[str]:
        """Resolve a callable expression to a project function qualname
        (methods via receiver type, functions via imports, classes to
        their ``__init__``)."""
        if isinstance(expr, ast.Attribute):
            recv_type = self._receiver_type(expr.value, fn, local_types)
            if recv_type is not None:
                return self.resolve_method(recv_type, expr.attr)
            dotted = _dotted(expr)
            if dotted:
                norm = self._normalize(dotted, fn.module)
                # module.func / package.module.Class
                mod, _, attr = norm.rpartition(".")
                if mod in self._modules and attr:
                    hit = self._func_by_modname.get((mod, attr))
                    if hit:
                        return hit
                    cls_qual = f"{mod}:{attr}"
                    if cls_qual in self.classes:
                        return self.resolve_method(cls_qual, "__init__")
            return None
        if isinstance(expr, ast.Name):
            name = expr.id
            summary = self._modules.get(fn.module)
            # same-module function (non-nested)
            hit = self._func_by_modname.get((fn.module, name))
            if hit and self.functions[hit].class_qualname is None:
                return hit
            if summary is not None and name in summary.from_imports:
                src_mod, attr = summary.from_imports[name]
                hit = self._func_by_modname.get((src_mod, attr))
                if hit:
                    return hit
                cls_qual = f"{src_mod}:{attr}"
                if cls_qual in self.classes:
                    return self.resolve_method(cls_qual, "__init__")
            cls_qual = self._lookup_class(name, fn.module)
            if cls_qual:
                return self.resolve_method(cls_qual, "__init__")
        return None

    def _resolve_function(self, fn: FunctionNode) -> None:
        # summaries (and their FunctionNodes) are cached across graph
        # builds, so start from a clean slate rather than appending
        fn.raw_calls = []
        local_types: Dict[str, str] = {}
        # one linear pass for local ``var = ClassName(...)`` types
        for node in ast.walk(fn.node):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                    isinstance(node.targets[0], ast.Name) and \
                    isinstance(node.value, ast.Call):
                typ = self._lookup_class(_dotted(node.value.func), fn.module)
                if typ is not None:
                    local_types[node.targets[0].id] = typ
        # annotated parameters: ``def f(self, store: ObjectStore)``
        args_node = fn.node.args
        for arg in (list(args_node.args) + list(args_node.kwonlyargs)):
            if arg.annotation is not None:
                ann = arg.annotation
                if isinstance(ann, ast.Subscript):  # Optional[X] etc.
                    inner = ann.slice
                    ann_name = _dotted(inner)
                else:
                    ann_name = _dotted(ann)
                typ = self._lookup_class(ann_name, fn.module)
                if typ is not None:
                    local_types.setdefault(arg.arg, typ)

        edges: List[CallSite] = []
        for node in self._own_nodes(fn.node):
            if not isinstance(node, ast.Call):
                continue
            callee = self._resolve_callable_expr(node.func, fn, local_types)
            if callee is not None and callee in self.functions:
                edges.append(CallSite(fn.qualname, callee, fn.path,
                                      node.lineno, node.col_offset + 1,
                                      "call"))
            dotted = _dotted(node.func)
            if dotted:
                fn.raw_calls.append((self._normalize(dotted, fn.module),
                                     node.lineno, node.col_offset + 1, node))
            # bound-method references in the arguments: registrations,
            # Thread targets, route callbacks.
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                if isinstance(arg, ast.Attribute):
                    ref = self._resolve_callable_expr(arg, fn, local_types)
                    if ref is not None and ref in self.functions:
                        edges.append(CallSite(fn.qualname, ref, fn.path,
                                              arg.lineno,
                                              arg.col_offset + 1, "ref"))
        if edges:
            self.edges[fn.qualname] = edges
            for e in edges:
                self.redges.setdefault(e.callee, []).append(e)

    @staticmethod
    def _own_nodes(fn_node) -> Iterable[ast.AST]:
        """Walk a function body WITHOUT descending into nested function
        or lambda bodies — those are separate graph nodes (a sink inside
        ``lambda: uuid.uuid4()`` belongs to the lambda, which is only
        reachable if something calls it)."""
        stack: List[ast.AST] = list(ast.iter_child_nodes(fn_node))
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue
            yield node
            stack.extend(ast.iter_child_nodes(node))

    # -- queries ---------------------------------------------------------

    def callees(self, qualname: str) -> List[CallSite]:
        return self.edges.get(qualname, [])

    def callers(self, qualname: str) -> List[CallSite]:
        return self.redges.get(qualname, [])

    def functions_in_path(self, path: str) -> List[FunctionNode]:
        return [fn for fn in self.functions.values() if fn.path == path]


def build_graph(files: Iterable[Tuple[str, str, ast.Module]]
                ) -> ProjectGraph:
    """Build and finalize a graph from ``(path, source, tree)`` triples."""
    g = ProjectGraph()
    for path, source, tree in files:
        g.add_file(path, source, tree)
    g.finalize()
    return g
