"""Analyzer engine: rule registry, suppression comments, file walking.

A rule is a class with ``NAME``/``DESCRIPTION``/``INVARIANT`` and a
``check(tree, ctx)`` generator of :class:`Finding`.  Registration is the
``@rule`` decorator; the CLI and the pytest gate both consume the same
registry, so a new rule is one class away from being enforced.

Suppressions are source comments, narrowest-scope first:

- ``# kuberay-lint: disable=RULE[,RULE2]`` on the offending line;
- ``# kuberay-lint: disable-next-line=RULE`` on the line above;
- ``# kuberay-lint: disable-file=RULE`` anywhere in the file (whole file).

``disable=all`` matches every rule.  A suppression silences the finding
but the justification comment stays in the source — that is the point.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
import tokenize
from io import StringIO
from typing import Dict, Iterable, Iterator, List, Optional, Set

SUPPRESS_RE = re.compile(
    r"#\s*kuberay-lint:\s*(disable|disable-next-line|disable-file)"
    r"\s*=\s*([A-Za-z0-9_,\- ]+)")


@dataclasses.dataclass
class Finding:
    """One rule violation at one source location.  ``end_line`` is the
    end of the flagged construct: a ``disable`` comment anywhere inside
    the span suppresses (so the comment can sit on an except-handler's
    body, not just its header)."""

    rule: str
    path: str
    line: int
    col: int
    message: str
    end_line: int = 0

    def to_dict(self) -> Dict[str, object]:
        return dataclasses.asdict(self)

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: [{self.rule}] {self.message}"


RULES: Dict[str, type] = {}


def rule(cls: type) -> type:
    """Class decorator: register a rule under its ``NAME``."""
    if not getattr(cls, "NAME", ""):
        raise ValueError(f"rule {cls!r} has no NAME")
    RULES[cls.NAME] = cls
    return cls


class Rule:
    """Base class; subclasses implement ``check``."""

    NAME = ""
    DESCRIPTION = ""
    INVARIANT = ""

    def check(self, tree: ast.Module, ctx: "FileContext") -> Iterable[Finding]:
        raise NotImplementedError

    def finding(self, ctx: "FileContext", node: ast.AST, message: str) -> Finding:
        line = getattr(node, "lineno", 0)
        return Finding(rule=self.NAME, path=ctx.path, line=line,
                       col=getattr(node, "col_offset", 0) + 1,
                       message=message,
                       end_line=getattr(node, "end_lineno", None) or line)


class FileContext:
    """Per-file state shared by every rule: path, source, suppressions."""

    def __init__(self, path: str, source: str):
        self.path = path
        self.source = source
        # line -> set of rule names disabled on that line
        self.line_disables: Dict[int, Set[str]] = {}
        self.file_disables: Set[str] = set()
        self._parse_suppressions()

    def _parse_suppressions(self) -> None:
        try:
            tokens = tokenize.generate_tokens(StringIO(self.source).readline)
            comments = [(t.start[0], t.string) for t in tokens
                        if t.type == tokenize.COMMENT]
        except (tokenize.TokenError, IndentationError, SyntaxError):
            # Suppressions are best-effort on files that don't tokenize;
            # the analyzer itself reports the parse error.
            return
        for lineno, text in comments:
            m = SUPPRESS_RE.search(text)
            if m is None:
                continue
            mode, names = m.group(1), {
                n.strip() for n in m.group(2).split(",") if n.strip()}
            if mode == "disable-file":
                self.file_disables |= names
            elif mode == "disable-next-line":
                self.line_disables.setdefault(lineno + 1, set()).update(names)
            else:
                self.line_disables.setdefault(lineno, set()).update(names)

    def suppressed(self, finding: Finding) -> bool:
        def hit(names: Set[str]) -> bool:
            return "all" in names or finding.rule in names
        if hit(self.file_disables):
            return True
        last = max(finding.line, finding.end_line or finding.line)
        return any(hit(self.line_disables.get(ln, set()))
                   for ln in range(finding.line, last + 1))


def _selected_rules(only: Optional[Iterable[str]] = None) -> List[Rule]:
    if only is None:
        names = sorted(RULES)
    else:
        names = list(only)
        unknown = [n for n in names if n not in RULES]
        if unknown:
            raise KeyError(f"unknown rule(s): {', '.join(unknown)}")
    return [RULES[n]() for n in names]


def analyze_source(source: str, path: str = "<string>",
                   only: Optional[Iterable[str]] = None,
                   keep_suppressed: bool = False) -> List[Finding]:
    """Run rules over one source string; returns unsuppressed findings
    (all findings when ``keep_suppressed``)."""
    ctx = FileContext(path, source)
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [Finding(rule="parse-error", path=path,
                        line=e.lineno or 0, col=(e.offset or 0),
                        message=f"could not parse: {e.msg}")]
    out: List[Finding] = []
    for r in _selected_rules(only):
        for f in r.check(tree, ctx):
            if keep_suppressed or not ctx.suppressed(f):
                out.append(f)
    out.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return out


def analyze_file(path: str, only: Optional[Iterable[str]] = None,
                 keep_suppressed: bool = False) -> List[Finding]:
    with open(path, encoding="utf-8", errors="replace") as fh:
        source = fh.read()
    return analyze_source(source, path=path, only=only,
                          keep_suppressed=keep_suppressed)


SKIP_DIRS = {"__pycache__", ".git", ".venv", "node_modules", ".eggs"}


def iter_python_files(paths: Iterable[str]) -> Iterator[str]:
    """Expand files/directories into a sorted, de-duplicated .py list."""
    seen: Set[str] = set()
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py") and p not in seen:
                seen.add(p)
                yield p
            continue
        for root, dirs, files in os.walk(p):
            dirs[:] = sorted(d for d in dirs if d not in SKIP_DIRS)
            for name in sorted(files):
                if not name.endswith(".py"):
                    continue
                full = os.path.join(root, name)
                if full not in seen:
                    seen.add(full)
                    yield full


def run_paths(paths: Iterable[str], only: Optional[Iterable[str]] = None,
              keep_suppressed: bool = False) -> List[Finding]:
    """Analyze every .py under ``paths``; findings sorted by location."""
    out: List[Finding] = []
    for path in iter_python_files(paths):
        out.extend(analyze_file(path, only=only,
                                keep_suppressed=keep_suppressed))
    return out
