"""Analyzer engine: rule registry, suppression comments, file walking,
and the whole-program pass.

A rule is a class with ``NAME``/``DESCRIPTION``/``INVARIANT``.  File
rules implement ``check(tree, ctx)``; whole-program rules subclass
:class:`ProjectRule` and implement ``check_project(project)`` against
the project-wide symbol table / call graph (``analysis/graph.py``).
Registration is the ``@rule`` decorator; the CLI and the pytest gate
both consume the same registry, so a new rule is one class away from
being enforced.

Suppressions are source comments, narrowest-scope first, and **must
carry a reason** after ``--`` (a bare suppression is itself a finding —
the ``suppression-without-reason`` rule):

- ``# kuberay-lint: disable=RULE[,RULE2] -- <why>`` on the offending line;
- ``# kuberay-lint: disable-next-line=RULE -- <why>`` on the line above;
- ``# kuberay-lint: disable-file=RULE -- <why>`` anywhere in the file.

``disable=all`` matches every rule.  A suppression silences the finding
but the justification stays in the source — that is the point.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
import tokenize
from io import StringIO
from typing import Dict, Iterable, Iterator, List, Optional, Set, Tuple

from kuberay_tpu.analysis.graph import ProjectGraph, parse_cached

SUPPRESS_RE = re.compile(
    r"#\s*kuberay-lint:\s*(disable|disable-next-line|disable-file)"
    r"\s*=\s*([A-Za-z0-9_,\- ]+?)"
    r"(?:\s*--\s*(\S.*))?$")


@dataclasses.dataclass
class Finding:
    """One rule violation at one source location.  ``end_line`` is the
    end of the flagged construct: a ``disable`` comment anywhere inside
    the span suppresses (so the comment can sit on an except-handler's
    body, not just its header).  Whole-program findings carry ``chain``
    — the call path root → … → sink, one ``{function, path, line}`` dict
    per hop, rendered as clickable ``via`` lines."""

    rule: str
    path: str
    line: int
    col: int
    message: str
    end_line: int = 0
    chain: Optional[List[Dict[str, object]]] = None

    def to_dict(self) -> Dict[str, object]:
        d = dataclasses.asdict(self)
        if self.chain is None:
            d.pop("chain")
        return d

    def render(self) -> str:
        out = f"{self.path}:{self.line}:{self.col}: [{self.rule}] {self.message}"
        for hop in self.chain or ():
            note = f"  ({hop['note']})" if hop.get("note") else ""
            out += (f"\n    via {hop['path']}:{hop['line']}: "
                    f"{hop['function']}{note}")
        return out


RULES: Dict[str, type] = {}


def rule(cls: type) -> type:
    """Class decorator: register a rule under its ``NAME``."""
    if not getattr(cls, "NAME", ""):
        raise ValueError(f"rule {cls!r} has no NAME")
    RULES[cls.NAME] = cls
    return cls


class Rule:
    """Base class for per-file rules; subclasses implement ``check``."""

    NAME = ""
    DESCRIPTION = ""
    INVARIANT = ""
    SCOPE = "file"

    def check(self, tree: ast.Module, ctx: "FileContext") -> Iterable[Finding]:
        raise NotImplementedError

    def finding(self, ctx: "FileContext", node: ast.AST, message: str,
                chain: Optional[List[Dict[str, object]]] = None) -> Finding:
        line = getattr(node, "lineno", 0)
        return Finding(rule=self.NAME, path=ctx.path, line=line,
                       col=getattr(node, "col_offset", 0) + 1,
                       message=message,
                       end_line=getattr(node, "end_lineno", None) or line,
                       chain=chain)


class ProjectRule(Rule):
    """A whole-program rule: sees every file at once, plus the call
    graph.  Implement ``check_project``; ``check`` never runs."""

    SCOPE = "project"

    def check(self, tree: ast.Module, ctx: "FileContext") -> Iterable[Finding]:
        return ()

    def check_project(self, project: "ProjectContext") -> Iterable[Finding]:
        raise NotImplementedError


@dataclasses.dataclass
class Suppression:
    """One parsed suppression comment."""

    line: int                 # the comment's own line
    mode: str                 # disable | disable-next-line | disable-file
    names: Set[str]
    reason: str               # '' when the comment is bare

    @property
    def target_line(self) -> int:
        return self.line + 1 if self.mode == "disable-next-line" else self.line


class FileContext:
    """Per-file state shared by every rule: path, source, suppressions."""

    def __init__(self, path: str, source: str):
        self.path = path
        self.source = source
        self.suppressions: List[Suppression] = []
        self._parse_suppressions()

    def _parse_suppressions(self) -> None:
        try:
            tokens = tokenize.generate_tokens(StringIO(self.source).readline)
            comments = [(t.start[0], t.string) for t in tokens
                        if t.type == tokenize.COMMENT]
        except (tokenize.TokenError, IndentationError, SyntaxError):
            # Suppressions are best-effort on files that don't tokenize;
            # the analyzer itself reports the parse error.
            return
        for lineno, text in comments:
            m = SUPPRESS_RE.search(text)
            if m is None:
                continue
            names = {n.strip() for n in m.group(2).split(",") if n.strip()}
            self.suppressions.append(Suppression(
                line=lineno, mode=m.group(1), names=names,
                reason=(m.group(3) or "").strip()))

    def suppressed(self, finding: Finding) -> bool:
        last = max(finding.line, finding.end_line or finding.line)
        for rec in self.suppressions:
            if "all" not in rec.names and finding.rule not in rec.names:
                continue
            if finding.rule == "suppression-without-reason" and \
                    not rec.reason:
                # a bare suppression cannot silence the finding ABOUT
                # bare suppressions — that would defeat the hygiene rule
                continue
            if rec.mode == "disable-file":
                return True
            if finding.line <= rec.target_line <= last:
                return True
        return False


class ProjectContext:
    """What whole-program rules see: every parsed file (with its
    suppression context) plus the finalized call graph."""

    def __init__(self, graph: ProjectGraph,
                 files: List[Tuple[str, str, ast.Module, FileContext]]):
        self.graph = graph
        self.files = files
        self.contexts: Dict[str, FileContext] = {
            path: ctx for path, _, _, ctx in files}

    def suppressed(self, finding: Finding) -> bool:
        ctx = self.contexts.get(finding.path)
        return ctx is not None and ctx.suppressed(finding)


@dataclasses.dataclass
class AnalysisReport:
    """Findings plus the suppression ledger (per-rule suppressed counts
    — ``--format json`` exposes these so CI can trend them)."""

    findings: List[Finding]
    suppressed_counts: Dict[str, int]


def _selected_rules(only: Optional[Iterable[str]] = None) -> List[Rule]:
    if only is None:
        names = sorted(RULES)
    else:
        names = list(only)
        unknown = [n for n in names if n not in RULES]
        if unknown:
            raise KeyError(f"unknown rule(s): {', '.join(unknown)}")
    return [RULES[n]() for n in names]


def _split_rules(only):
    selected = _selected_rules(only)
    file_rules = [r for r in selected if r.SCOPE == "file"]
    project_rules = [r for r in selected if r.SCOPE == "project"]
    return file_rules, project_rules


def _sort(findings: List[Finding]) -> List[Finding]:
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule, f.message))
    return findings


def _partition(findings, ctx_lookup, keep_suppressed, counts):
    out = []
    for f in findings:
        if ctx_lookup(f):
            counts[f.rule] = counts.get(f.rule, 0) + 1
            if keep_suppressed:
                out.append(f)
        else:
            out.append(f)
    return out


def analyze_source(source: str, path: str = "<string>",
                   only: Optional[Iterable[str]] = None,
                   keep_suppressed: bool = False) -> List[Finding]:
    """Run rules over one source string; returns unsuppressed findings
    (all findings when ``keep_suppressed``).  Whole-program rules see a
    single-file project — enough for same-file wrapper fixtures."""
    ctx = FileContext(path, source)
    try:
        tree = parse_cached(source, path)
    except SyntaxError as e:
        return [Finding(rule="parse-error", path=path,
                        line=e.lineno or 0, col=(e.offset or 0),
                        message=f"could not parse: {e.msg}")]
    file_rules, project_rules = _split_rules(only)
    raw: List[Finding] = []
    for r in file_rules:
        raw.extend(r.check(tree, ctx))
    if project_rules:
        project = ProjectContext(
            _build_graph([(path, source, tree)]),
            [(path, source, tree, ctx)])
        for r in project_rules:
            raw.extend(r.check_project(project))
    out = [f for f in raw if keep_suppressed or not ctx.suppressed(f)]
    return _sort(out)


def _build_graph(triples) -> ProjectGraph:
    g = ProjectGraph()
    for path, source, tree in triples:
        g.add_file(path, source, tree)
    g.finalize()
    return g


def analyze_file(path: str, only: Optional[Iterable[str]] = None,
                 keep_suppressed: bool = False) -> List[Finding]:
    with open(path, encoding="utf-8", errors="replace") as fh:
        source = fh.read()
    return analyze_source(source, path=path, only=only,
                          keep_suppressed=keep_suppressed)


SKIP_DIRS = {"__pycache__", ".git", ".venv", "node_modules", ".eggs"}


def iter_python_files(paths: Iterable[str]) -> Iterator[str]:
    """Expand files/directories into a sorted, de-duplicated .py list."""
    seen: Set[str] = set()
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py") and p not in seen:
                seen.add(p)
                yield p
            continue
        for root, dirs, files in os.walk(p):
            dirs[:] = sorted(d for d in dirs if d not in SKIP_DIRS)
            for name in sorted(files):
                if not name.endswith(".py"):
                    continue
                full = os.path.join(root, name)
                if full not in seen:
                    seen.add(full)
                    yield full


def analyze_paths(paths: Iterable[str],
                  only: Optional[Iterable[str]] = None,
                  keep_suppressed: bool = False,
                  restrict_to: Optional[Set[str]] = None) -> AnalysisReport:
    """The whole-program entry point: parse every .py under ``paths``
    once (content-hash cached), run file rules per file and project
    rules over the full graph, and return findings plus the per-rule
    suppressed-count ledger.

    ``restrict_to`` (absolute or as-walked paths) limits *reporting* to
    those files — the graph is still built from everything, so chains
    through unchanged files stay visible (``--changed-only``)."""
    file_rules, project_rules = _split_rules(only)
    parsed: List[Tuple[str, str, ast.Module, FileContext]] = []
    raw: List[Finding] = []
    for path in iter_python_files(paths):
        with open(path, encoding="utf-8", errors="replace") as fh:
            source = fh.read()
        ctx = FileContext(path, source)
        try:
            tree = parse_cached(source, path)
        except SyntaxError as e:
            raw.append(Finding(rule="parse-error", path=path,
                               line=e.lineno or 0, col=(e.offset or 0),
                               message=f"could not parse: {e.msg}"))
            continue
        parsed.append((path, source, tree, ctx))
        if restrict_to is None or path in restrict_to:
            for r in file_rules:
                raw.extend(r.check(tree, ctx))
    if project_rules and parsed:
        project = ProjectContext(
            _build_graph([(p, s, t) for p, s, t, _ in parsed]), parsed)
        for r in project_rules:
            for f in r.check_project(project):
                if restrict_to is None or f.path in restrict_to:
                    raw.append(f)
    contexts = {path: ctx for path, _, _, ctx in parsed}
    counts: Dict[str, int] = {}
    out = _partition(
        raw, lambda f: (f.path in contexts and
                        contexts[f.path].suppressed(f)),
        keep_suppressed, counts)
    return AnalysisReport(_sort(out), counts)


def run_paths(paths: Iterable[str], only: Optional[Iterable[str]] = None,
              keep_suppressed: bool = False) -> List[Finding]:
    """Analyze every .py under ``paths``; findings sorted by location.
    (Compatibility face of :func:`analyze_paths`.)"""
    return analyze_paths(paths, only=only,
                         keep_suppressed=keep_suppressed).findings
