"""Whole-program rules: seam funnels, determinism taint, lock blocking,
and exception escape — each checked *through any depth of wrappers*.

The per-file rules in :mod:`kuberay_tpu.analysis.rules` enforce the
framework's seams where they are declared; a one-line wrapper in another
function (or another module) defeats every one of them.  These four
rules re-state the same invariants over the project call graph
(:mod:`kuberay_tpu.analysis.graph`) and the dataflow layer
(:mod:`kuberay_tpu.analysis.dataflow`), so a finding is a *path*, not a
line — and every finding prints that path as clickable ``via
file:line`` hops.

Division of labour with the per-file rules: a direct violation inside
the seam-owning function itself (chain length 1) stays the per-file
rule's finding; the whole-program rules report only chains of length
≥ 2 — the wrapper bypasses the per-file pass cannot see.  Running both
therefore never double-reports one construct.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, Iterator, List, Optional, Set, Tuple

from kuberay_tpu.analysis.core import (Finding, FileContext, ProjectContext,
                                       ProjectRule, rule)
from kuberay_tpu.analysis.dataflow import (EscapeAnalysis, Hop, chain_to,
                                           reach, sink_closure)
from kuberay_tpu.analysis.graph import FunctionNode, ProjectGraph
from kuberay_tpu.analysis.rules import (_BLOCKING_EXACT, _BLOCKING_METHODS,
                                        _BLOCKING_PREFIX, _lock_model,
                                        iter_classes)

try:  # the live patch list is the source of truth for the time seam
    from kuberay_tpu.sim.clock import DEFAULT_PATCH_MODULES as _PATCHED_TIME
except Exception:  # pragma: no cover - analyzing a tree without the sim
    _PATCHED_TIME = ()

#: module whose direct stdlib-time/uuid/random use IS the seam
_CLOCK_MODULE = "kuberay_tpu.sim.clock"


# ---------------------------------------------------------------------------
# root discovery (shared)
# ---------------------------------------------------------------------------

def _reconcile_roots(graph: ProjectGraph) -> List[str]:
    """Controller reconcile entry points: ``reconcile`` methods of
    control-plane classes (or, for fixtures, of any class that declares
    a ``KIND`` class attribute — the controller registration marker)."""
    roots: List[str] = []
    for qual in sorted(graph.classes):
        cls = graph.classes[qual]
        target = cls.methods.get("reconcile")
        if target is None:
            continue
        if cls.module.startswith("kuberay_tpu.controlplane") or \
                "KIND" in cls.class_attrs:
            roots.append(target)
    return roots


def _sim_roots(graph: ProjectGraph) -> List[str]:
    """Everything the sim harness package can run is a determinism
    root (the journal hash covers all of it)."""
    return [q for q in sorted(graph.functions)
            if graph.functions[q].module.startswith("kuberay_tpu.sim")]


def _hops(chain: List[Hop]) -> List[Dict[str, object]]:
    return [h.to_dict() for h in chain]


def _mk_finding(rule_obj, fn: FunctionNode, line: int, col: int,
                message: str, chain: List[Hop]) -> Finding:
    return Finding(rule=rule_obj.NAME, path=fn.path, line=line, col=col,
                   message=message, end_line=line, chain=_hops(chain))


# ---------------------------------------------------------------------------
# 14. sim-determinism
# ---------------------------------------------------------------------------

_TIME_SINKS = {
    "time.time", "time.time_ns", "time.monotonic", "time.monotonic_ns",
    "time.perf_counter", "time.perf_counter_ns",
}
_DATETIME_LEAVES = {"now", "utcnow", "today"}
_UUID_SINKS = {"uuid.uuid1", "uuid.uuid4"}
_RANDOM_SANCTIONED = {"Random", "SystemRandom"}


def _det_sink(name: str) -> Optional[str]:
    """Label when ``name`` (normalized dotted call) draws entropy or
    wall-clock time.  Seeded ``random.Random(...)`` construction is the
    sanctioned pattern, so it is not a sink; neither are calls on such
    an instance (their receiver is an attribute, not the module)."""
    if name in _TIME_SINKS:
        return "wall-clock time"
    leaf = name.rsplit(".", 1)[-1]
    if name.startswith("datetime.") and leaf in _DATETIME_LEAVES:
        return "wall-clock datetime"
    if name.startswith("random.") and name.count(".") == 1 and \
            leaf not in _RANDOM_SANCTIONED:
        return "unseeded module-level random"
    if name in _UUID_SINKS:
        return "random uuid"
    if name == "os.urandom" or name.startswith("secrets."):
        return "os entropy"
    return None


@rule
class SimDeterminismRule(ProjectRule):
    """The chaos sim's byte-identical journal-hash gate only holds if no
    code reachable from a controller reconcile path or the sim package
    draws wall-clock time or entropy outside the sanctioned seams:
    ``sim/clock.py`` (whose shim virtualizes ``time.time`` in the
    ``DEFAULT_PATCH_MODULES``), the store's injectable ``uid_factory``,
    and seeded ``random.Random`` instances.  This rule makes that a
    static guarantee instead of a 40-run empirical one: it taints every
    function reachable from those roots and reports each
    ``time``/``datetime``/``random``/``uuid``/entropy call that does not
    pass a seam, with the call chain that reaches it.
    """

    NAME = "sim-determinism"
    DESCRIPTION = ("code reachable from reconcile paths or the sim "
                   "harness must draw time/entropy only through the "
                   "clock seam, uid_factory, or a seeded Random")
    INVARIANT = ("sim journal hashes are a pure function of "
                 "(scenario, seed) — statically, not just empirically")

    def check_project(self, project: ProjectContext) -> Iterable[Finding]:
        graph = project.graph
        roots = sorted(set(_reconcile_roots(graph)) | set(_sim_roots(graph)))
        if not roots:
            return
        parents = reach(graph, roots)
        seen: Set[Tuple[str, str]] = set()
        for qual in sorted(parents):
            fn = graph.functions[qual]
            if fn.module == _CLOCK_MODULE or \
                    fn.module.split(".")[-1] == "clock":
                continue  # the seam itself
            for name, line, col, _node in fn.raw_calls:
                label = _det_sink(name)
                if label is None:
                    continue
                if label == "wall-clock time" and \
                        fn.module in _PATCHED_TIME:
                    continue  # virtualized by sim.clock.patch_time
                if (qual, name) in seen:
                    continue
                seen.add((qual, name))
                chain = chain_to(graph, parents, qual)
                root = chain[0].qualname if chain else qual
                yield _mk_finding(
                    self, fn, line, col,
                    f"'{name}' ({label}) is reachable from '{root}' "
                    "without passing a determinism seam; inject the sim "
                    "clock, a factory, or a seeded random.Random instead",
                    chain)


# ---------------------------------------------------------------------------
# 15. transitive-seam-bypass
# ---------------------------------------------------------------------------

class _SeamSpec:
    """One funnel: a seam-owning class (identified by ``required``
    methods), the methods wrappers may legitimately end in
    (``allowed``), which methods root the search, and a sink detector
    run on every function reachable from those roots without entering
    the seam."""

    __slots__ = ("label", "required", "allowed", "roots_filter", "why")

    def __init__(self, label: str, required: Set[str], allowed: Set[str],
                 why: str, roots_filter: Optional[Set[str]] = None):
        self.label = label
        self.required = required
        self.allowed = allowed
        self.why = why
        self.roots_filter = roots_filter  # None = every non-allowed method

    def sinks(self, fn: FunctionNode, graph: ProjectGraph,
              seam_cls) -> Iterator[Tuple[int, int, str]]:
        raise NotImplementedError


class _QuotaSeam(_SeamSpec):
    _ASKS = ("on_cluster_submission", "on_job_submission")

    def sinks(self, fn, graph, seam_cls):
        if fn.module.startswith("kuberay_tpu.scheduler"):
            return  # the scheduler's own internals
        if fn.class_qualname:
            owner = graph.classes.get(fn.class_qualname)
            if owner is not None and \
                    any(a in owner.methods for a in self._ASKS):
                return  # a scheduler implementation
        for name, line, col, _node in fn.raw_calls:
            if name.rsplit(".", 1)[-1] in self._ASKS:
                yield line, col, f"scheduler ask '{name}'"


class _WeightSeam(_SeamSpec):
    _FIELD = "trafficWeightPercent"

    def sinks(self, fn, graph, seam_cls):
        if fn.class_qualname == seam_cls.qualname and \
                fn.name in self.allowed:
            return
        for node in graph._own_nodes(fn.node):
            targets: List[ast.AST] = []
            if isinstance(node, ast.Assign):
                targets = list(node.targets)
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                targets = [node.target]
            for tgt in targets:
                hit = (isinstance(tgt, ast.Attribute) and
                       tgt.attr == self._FIELD) or \
                      (isinstance(tgt, ast.Subscript) and
                       isinstance(tgt.slice, ast.Constant) and
                       tgt.slice.value == self._FIELD)
                if hit:
                    yield (node.lineno, node.col_offset + 1,
                           f"{self._FIELD} write")


class _TeardownSeam(_SeamSpec):
    _RAW = "_delete_pod"

    def sinks(self, fn, graph, seam_cls):
        if fn.name in self.allowed or fn.name == self._RAW:
            return
        for name, line, col, _node in fn.raw_calls:
            if name.rsplit(".", 1)[-1] == self._RAW:
                yield line, col, f"raw pod delete '{name}'"


_SEAMS: List[_SeamSpec] = [
    _QuotaSeam(
        "quota admission", required={"_admission_verdict"},
        allowed={"_admission_verdict"},
        why=("the quota claim, PodGroup status, and admission counter "
             "must stay one-per-reconcile")),
    _WeightSeam(
        "upgrade weight gate", required={"_apply_upgrade_decision"},
        allowed={"_apply_upgrade_decision", "_promote"},
        why=("every ramp weight write must stay downstream of one "
             "orchestrator decision (ring cap + burn-rate verdict)")),
    _TeardownSeam(
        "drain seam", required={"_delete_slice", "_reconcile_worker_group"},
        allowed={"_delete_slice"},
        why=("preemption-noticed pods must be drained (checkpoint + "
             "stamp) before any slice pod is deleted"),
        roots_filter={"_reconcile_worker_group"}),
]


@rule
class TransitiveSeamBypassRule(ProjectRule):
    """The three seam-funnel rules (quota admission, the upgrade weight
    gate, the slice-teardown drain seam) catch *direct* violations in
    the seam-owning class; a helper wrapper — in the same class or
    another module — bypasses all of them invisibly.  This rule walks
    the call graph from every seam-class method, refusing to traverse
    through the seam itself, and flags any reachable capacity ask,
    traffic-weight write, or raw pod delete at depth ≥ 2 (depth 1 is
    the per-file rules' territory), with the wrapper chain.
    """

    NAME = "transitive-seam-bypass"
    DESCRIPTION = ("capacity asks, traffic-weight writes, and slice "
                   "teardown must route through their seams through "
                   "any depth of wrappers")
    INVARIANT = ("no call path reaches a seam-guarded effect without "
                 "passing the seam")

    def check_project(self, project: ProjectContext) -> Iterable[Finding]:
        graph = project.graph
        for spec in _SEAMS:
            for cls_qual in sorted(graph.classes):
                cls = graph.classes[cls_qual]
                if not all(m in cls.methods for m in spec.required):
                    continue
                avoid = {cls.methods[m] for m in spec.allowed
                         if m in cls.methods}
                if spec.roots_filter is None:
                    roots = [q for m, q in sorted(cls.methods.items())
                             if m not in spec.allowed]
                else:
                    roots = [cls.methods[m] for m in sorted(spec.roots_filter)
                             if m in cls.methods]
                parents = reach(graph, roots, avoid=avoid)
                for qual in sorted(parents):
                    if qual in avoid:
                        continue
                    chain = chain_to(graph, parents, qual)
                    if len(chain) < 2:
                        continue  # direct: the per-file rule's finding
                    fn = graph.functions[qual]
                    for line, col, what in spec.sinks(fn, graph, cls):
                        yield _mk_finding(
                            self, fn, line, col,
                            f"{what} reached from "
                            f"'{chain[0].qualname}' without passing the "
                            f"{spec.label} ('{'/'.join(sorted(spec.allowed))}"
                            f"'); {spec.why}",
                            chain)


# ---------------------------------------------------------------------------
# 16. transitive-blocking-under-lock
# ---------------------------------------------------------------------------

def _blocking_sink(name: str, fn: FunctionNode) -> Optional[str]:
    """Mirror of the per-file blocking matcher over normalized names,
    minus ``self.X`` method calls (those resolve to graph edges and are
    judged by their own bodies) and the sim clock module (its sleeps
    are virtualized)."""
    if fn.module == _CLOCK_MODULE or fn.module.split(".")[-1] == "clock":
        return None
    if not name:
        return None
    if name in _BLOCKING_EXACT:
        return f"blocking call '{name}'"
    if any(name.startswith(p) for p in _BLOCKING_PREFIX):
        return f"blocking call '{name}'"
    if name.startswith("self.") and name.count(".") == 1:
        return None
    leaf = name.rsplit(".", 1)[-1]
    if "." in name and leaf in _BLOCKING_METHODS:
        return f"blocking call '{name}'"
    return None


@rule
class TransitiveBlockingUnderLockRule(ProjectRule):
    """``blocking-under-lock`` sees one class at a time: a locked call
    into a helper that sleeps or does socket/HTTP/subprocess I/O — in a
    different method with unlocked callers, or a different module —
    stalls every thread behind the lock just the same.  This rule
    computes the blocking closure of the whole call graph once, then
    flags every lock-held call site whose resolved callee can reach a
    blocking sink, printing the path from the locked call to the I/O.
    """

    NAME = "transitive-blocking-under-lock"
    DESCRIPTION = ("no lock-held call may reach time.sleep / socket / "
                   "HTTP / subprocess I/O through any chain of helpers")
    INVARIANT = ("lock hold times are bounded by computation through "
                 "the whole call graph, not just the locked body")

    def check_project(self, project: ProjectContext) -> Iterable[Finding]:
        graph = project.graph
        # 'call' edges only: a Thread/callback target registered under a
        # lock runs its I/O on another stack, not under this lock
        closure = sink_closure(graph, _blocking_sink, kinds=("call",))
        if not closure:
            return
        for path, _source, tree, _ctx in project.files:
            for cls in iter_classes(tree):
                model = _lock_model(cls)
                if not model.lock_attrs:
                    continue
                yield from self._check_class(graph, closure, path, cls,
                                             model)

    def _check_class(self, graph, closure, path, cls, model):
        held_sites: List[Tuple[str, ast.Call]] = list(
            (method, node) for _f, node, method in model.held_calls)
        for method in sorted(model.held_methods):
            for node in ast.walk(model.methods[method]):
                if isinstance(node, ast.Call):
                    held_sites.append((method, node))
        seen: Set[Tuple[int, int]] = set()
        for method, node in held_sites:
            caller_qual = self._method_qual(graph, path, cls.name, method)
            if caller_qual is None:
                continue
            for site in graph.callees(caller_qual):
                if site.line != node.lineno or \
                        site.col != node.col_offset + 1 or \
                        site.kind != "call":
                    continue
                chain = closure.get(site.callee)
                if chain is None:
                    continue
                if self._per_file_territory(cls, model, site, chain,
                                            graph):
                    continue
                key = (node.lineno, node.col_offset)
                if key in seen:
                    continue
                seen.add(key)
                fn = graph.functions[caller_qual]
                sink_hop = chain[-1]
                head = Hop(caller_qual, fn.path, node.lineno,
                           f"holds the '{cls.name}' lock")
                yield _mk_finding(
                    self, fn, node.lineno, node.col_offset + 1,
                    f"call from '{caller_qual}' while holding the "
                    f"'{cls.name}' lock reaches {sink_hop.note or 'I/O'} "
                    f"at {sink_hop.path}:{sink_hop.line}; move the I/O "
                    "outside the locked region",
                    [head] + chain)

    @staticmethod
    def _method_qual(graph: ProjectGraph, path: str, cls_name: str,
                     method: str) -> Optional[str]:
        for fn in graph.functions_in_path(path):
            if fn.name == method and fn.class_qualname and \
                    fn.class_qualname.rsplit(":", 1)[-1] == cls_name:
                return fn.qualname
        return None

    @staticmethod
    def _per_file_territory(cls, model, site, chain, graph) -> bool:
        """Depth-1 blocking inside a method of this class that the
        per-file rule already reports (held call sites and held
        methods) — skip to avoid double findings."""
        callee = graph.functions.get(site.callee)
        if callee is None or len(chain) != 1:
            return False
        return (callee.class_qualname is not None and
                callee.class_qualname.rsplit(":", 1)[-1] == cls.name and
                callee.name in model.held_methods)


# ---------------------------------------------------------------------------
# 17. reconcile-exception-escape
# ---------------------------------------------------------------------------

#: exceptions the Manager contract converts on purpose: Conflict is the
#: optimistic-concurrency retry signal (fast requeue + metric).
_SANCTIONED_ESCAPES = {"Conflict"}


@rule
class ReconcileExceptionEscapeRule(ProjectRule):
    """An exception that propagates out of a controller's ``reconcile``
    lands in ``Manager._process``'s blanket ``except Exception`` — a
    blind 5-second backoff and a ``reconcile_error`` metric, with no
    status write and no targeted requeue.  Only ``Conflict`` (the rv
    retry signal, fast-requeued by contract) is meant to escape.  This
    rule runs the escape analysis over the call graph and reports every
    other exception type that can reach the Manager from a reconcile
    entry point, with the raise site and the call chain to it.
    """

    NAME = "reconcile-exception-escape"
    DESCRIPTION = ("only Conflict may propagate out of a controller "
                   "reconcile; other exceptions must become a requeue "
                   "or status write")
    INVARIANT = ("reconcile failures are handled decisions, not blind "
                 "Manager backoff")

    def check_project(self, project: ProjectContext) -> Iterable[Finding]:
        graph = project.graph
        analysis = EscapeAnalysis(graph)
        for root in _reconcile_roots(graph):
            fn = graph.functions[root]
            for exc_name in sorted(analysis.escapes(root)):
                if exc_name in _SANCTIONED_ESCAPES:
                    continue
                chain = analysis.escapes(root)[exc_name]
                raise_hop = chain[-1]
                yield _mk_finding(
                    self, fn, chain[0].line, 1,
                    f"{exc_name} raised at "
                    f"{raise_hop.path}:{raise_hop.line} can escape "
                    f"'{root}' to the Manager's blind backoff; catch it "
                    "and return a requeue or write status instead",
                    chain)
