"""Dataflow layers over :class:`~kuberay_tpu.analysis.graph.ProjectGraph`.

Three small analyses, each returning *call chains* (root → … → sink)
so every whole-program finding can print the exact wrapper path that
defeats a seam:

- :func:`reach` — forward reachability from a root set with parent
  links, optionally refusing to traverse *through* a set of sanitizer
  / seam nodes (a path that enters the seam is, by definition, not a
  bypass);
- :func:`sink_closure` — for every function, the first chain to a
  matching call sink (used for transitive blocking-under-lock: the
  closure is computed once, then consulted at every locked call site);
- :class:`EscapeAnalysis` — per-function escaping exception types with
  the raise site and chain, honouring try/except handlers along the
  way (name-based, with the project class hierarchy and a small
  builtin table for broad handlers).

All iteration orders are sorted, so analyzer output is byte-stable
across runs and processes — the same determinism bar the sim journal
holds itself to.
"""

from __future__ import annotations

import ast
from typing import Callable, Dict, Iterable, List, Optional, Set, Tuple

from kuberay_tpu.analysis.graph import CallSite, FunctionNode, ProjectGraph

__all__ = ["reach", "chain_to", "sink_closure", "EscapeAnalysis", "Hop"]


class Hop:
    """One link of a reported call chain."""

    __slots__ = ("qualname", "path", "line", "note")

    def __init__(self, qualname: str, path: str, line: int, note: str = ""):
        self.qualname = qualname
        self.path = path
        self.line = line
        self.note = note

    def to_dict(self) -> Dict[str, object]:
        d = {"function": self.qualname, "path": self.path, "line": self.line}
        if self.note:
            d["note"] = self.note
        return d


# ---------------------------------------------------------------------------
# reachability
# ---------------------------------------------------------------------------

def reach(graph: ProjectGraph, roots: Iterable[str],
          avoid: Iterable[str] = ()) -> Dict[str, Optional[CallSite]]:
    """BFS over the call graph from ``roots``.  Returns
    ``{reachable qualname: parent CallSite}`` (roots map to ``None``).
    Nodes in ``avoid`` are never *expanded* (their callees stay
    unreached through them) — pass seam methods here so "reachable
    without passing through the seam" falls out directly."""
    avoid_set = set(avoid)
    parents: Dict[str, Optional[CallSite]] = {}
    frontier: List[str] = []
    for r in sorted(set(roots)):
        if r in graph.functions and r not in parents:
            parents[r] = None
            frontier.append(r)
    while frontier:
        nxt: List[str] = []
        for qual in frontier:
            if qual in avoid_set:
                continue
            for site in graph.callees(qual):
                if site.callee not in parents:
                    parents[site.callee] = site
                    nxt.append(site.callee)
        frontier = sorted(nxt)
    return parents


def chain_to(graph: ProjectGraph, parents: Dict[str, Optional[CallSite]],
             target: str) -> List[Hop]:
    """Reconstruct root → … → target as hops; each hop's ``line`` is
    where the *next* function is entered (the call site), and the first
    hop is the root's own definition line."""
    if target not in parents:
        return []
    sites: List[CallSite] = []
    cur = target
    while parents.get(cur) is not None:
        site = parents[cur]
        sites.append(site)
        cur = site.caller
    root_fn = graph.functions[cur]
    hops = [Hop(cur, root_fn.path, root_fn.line)]
    for site in reversed(sites):
        note = "registered callback" if site.kind == "ref" else ""
        hops.append(Hop(site.callee, site.path, site.line, note))
    return hops


# ---------------------------------------------------------------------------
# sink closure (transitive blocking etc.)
# ---------------------------------------------------------------------------

def sink_closure(graph: ProjectGraph,
                 sink: Callable[[str, FunctionNode], Optional[str]],
                 kinds: Iterable[str] = ("call", "ref")
                 ) -> Dict[str, List[Hop]]:
    """For every function that can reach a *call sink*, the shortest
    chain ``[... , sink-call hop]``.

    ``sink(normalized_name, fn)`` returns a human label when the named
    call inside ``fn`` is a sink (else None).  The closure propagates
    backwards over the edge ``kinds`` given — both by default (a
    registered callback that blocks still blocks); pass ``("call",)``
    for properties that do not cross thread/callback boundaries, like
    lock-hold analysis (a Thread target's I/O does not run under the
    spawner's lock).  Chains are minimal-length and deterministic
    (sorted tie-breaks)."""
    kind_set = set(kinds)
    chains: Dict[str, List[Hop]] = {}
    # seed: functions with a direct sink call
    for qual in sorted(graph.functions):
        fn = graph.functions[qual]
        for name, line, _col, _node in fn.raw_calls:
            label = sink(name, fn)
            if label is not None:
                chains[qual] = [Hop(qual, fn.path, line, label)]
                break
    # propagate callers-of: BFS layers give shortest chains
    frontier = sorted(chains)
    while frontier:
        nxt: List[str] = []
        for qual in frontier:
            for site in sorted(graph.callers(qual),
                               key=lambda s: (s.caller, s.line)):
                if site.kind not in kind_set or site.caller in chains:
                    continue
                caller_fn = graph.functions[site.caller]
                chains[site.caller] = \
                    [Hop(site.caller, caller_fn.path, site.line)] + \
                    chains[qual]
                nxt.append(site.caller)
        frontier = sorted(nxt)
    return chains


# ---------------------------------------------------------------------------
# exception escape
# ---------------------------------------------------------------------------

#: Builtin exception subtyping the handler matcher understands.  Keys
#: are handler names; values are the raised names they also catch.
_BUILTIN_CATCHES: Dict[str, Set[str]] = {
    "BaseException": {"*"},
    "Exception": {"*"},
    "OSError": {"IOError", "FileNotFoundError", "ConnectionError",
                "TimeoutError", "PermissionError"},
    "LookupError": {"KeyError", "IndexError"},
    "ValueError": {"UnicodeDecodeError"},
    "ArithmeticError": {"ZeroDivisionError", "OverflowError"},
    "RuntimeError": {"RecursionError", "NotImplementedError"},
}


class EscapeAnalysis:
    """Which exception types can escape each function, with the raise
    site and call chain.

    Explicit ``raise Name(...)`` statements are the sources (library-
    internal raises are invisible to static analysis and out of scope).
    A raise escapes its function unless an enclosing ``try`` in the
    same function has a matching handler; an escape propagates to a
    caller unless the *call site* is inside a matching ``try``.  Handler
    matching is name-based, widened by the project class hierarchy
    (``except StoreError`` catches ``Conflict(StoreError)``) and the
    builtin table above."""

    def __init__(self, graph: ProjectGraph):
        self.graph = graph
        #: exception class name -> its base names (project classes)
        self._bases: Dict[str, List[str]] = {}
        for qual in sorted(graph.classes):
            cls = graph.classes[qual]
            self._bases.setdefault(cls.name, [b.split(".")[-1]
                                              for b in cls.bases])
        #: function -> {exc name: (raise Hop chain tail)}
        self._escapes: Dict[str, Dict[str, List[Hop]]] = {}
        self._in_progress: Set[str] = set()

    # -- handler matching -----------------------------------------------

    def _catches(self, handler_name: str, exc_name: str,
                 _seen: Optional[Set[str]] = None) -> bool:
        if handler_name in ("", "BaseException", "Exception"):
            return True
        if handler_name == exc_name:
            return True
        if exc_name in _BUILTIN_CATCHES.get(handler_name, ()):
            return True
        # project hierarchy: walk exc's bases up to the handler
        seen = _seen or set()
        if exc_name in seen:
            return False
        seen.add(exc_name)
        for base in self._bases.get(exc_name, ()):  # may be builtin names
            if base == handler_name or \
                    self._catches(handler_name, base, seen):
                return True
        return False

    def _handler_names(self, try_node: ast.Try) -> List[str]:
        names: List[str] = []
        for handler in try_node.handlers:
            if handler.type is None:
                names.append("")
            elif isinstance(handler.type, ast.Tuple):
                for elt in handler.type.elts:
                    d = _last_name(elt)
                    if d:
                        names.append(d)
            else:
                d = _last_name(handler.type)
                if d:
                    names.append(d)
        return names

    def _caught_at(self, fn_node, target: ast.AST, exc_name: str) -> bool:
        """Is ``target`` (a raise or call node) inside a try whose
        handlers catch ``exc_name``, within this function?"""
        for try_node in ast.walk(fn_node):
            if not isinstance(try_node, ast.Try):
                continue
            in_body = any(_contains(stmt, target) for stmt in try_node.body)
            if not in_body:
                continue
            for hname in self._handler_names(try_node):
                if self._catches(hname, exc_name):
                    return True
        return False

    # -- per-function escapes -------------------------------------------

    def escapes(self, qualname: str) -> Dict[str, List[Hop]]:
        """``{exception name: chain of hops ending at the raise site}``
        for exceptions that can propagate out of ``qualname``."""
        memo = self._escapes.get(qualname)
        if memo is not None:
            return memo
        if qualname in self._in_progress:      # recursion: assume clean
            return {}
        self._in_progress.add(qualname)
        fn = self.graph.functions.get(qualname)
        out: Dict[str, List[Hop]] = {}
        if fn is None:
            self._in_progress.discard(qualname)
            self._escapes[qualname] = out
            return out
        # (a) explicit raises in this body
        for node in self.graph._own_nodes(fn.node):
            if not isinstance(node, ast.Raise) or node.exc is None:
                continue
            name = _last_name(node.exc.func if isinstance(node.exc, ast.Call)
                              else node.exc)
            if not name:
                continue
            if not self._caught_at(fn.node, node, name):
                out.setdefault(name, [Hop(
                    qualname, fn.path, node.lineno, f"raises {name}")])
        # (b) escapes from resolved callees at uncaught call sites
        for site in sorted(self.graph.callees(qualname),
                           key=lambda s: (s.line, s.callee)):
            if site.kind != "call":
                continue
            callee_esc = self.escapes(site.callee)
            if not callee_esc:
                continue
            call_node = _call_at(fn.node, site.line, site.callee,
                                 self.graph)
            for exc_name in sorted(callee_esc):
                if exc_name in out:
                    continue
                if call_node is not None and \
                        self._caught_at(fn.node, call_node, exc_name):
                    continue
                out[exc_name] = [Hop(qualname, fn.path, site.line)] + \
                    callee_esc[exc_name]
        self._in_progress.discard(qualname)
        self._escapes[qualname] = out
        return out


def _last_name(node: ast.AST) -> str:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return ""


def _contains(root: ast.AST, target: ast.AST) -> bool:
    for sub in ast.walk(root):
        if sub is target:
            return True
    return False


def _call_at(fn_node, line: int, callee: str, graph: ProjectGraph
             ) -> Optional[ast.Call]:
    for node in ast.walk(fn_node):
        if isinstance(node, ast.Call) and node.lineno == line:
            return node
    return None
